"""mxprec — dtype-flow analysis + committed precision ledgers
(ISSUE 10).

Covers: the hazard classifier on synthetic HLO; four seeded
perturbations that each trip EXACTLY one rule with the op and source
site named (bf16 accumulating reduce, sub-f32 dot, f64 creep, missing
fp32 master weight); the one-dtype-analyzer migration (`summarize`'s
dtype block == dtypeflow's, committed hlocheck contracts keep their
shape); the `python -m tools.mxprec` CLI exit-code/byte-determinism
contract; the `MXTPU_PREC_AUDIT` runtime knob; and the optimizer
multi-precision fix end to end (bf16 params track f32 training within
tolerance while staying bf16, eager and compiled).

Lowerings go through ``analysis.lowered_summary`` — the sanctioned
pre-optimization route — so mxlint's ``hlo-raw-assert`` rule stays
happy.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from mxtpu import analysis, nd, parallel
from mxtpu.analysis import dtypeflow
from mxtpu.base import MXNetError
from mxtpu.gluon import nn
from mxtpu.parallel import restore_params, snapshot_params

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------
# synthetic module: a bf16 dot feeding a bf16 accumulating reduce —
# the two textbook AMP hazards — plus one dead upcast for the
# dtype-summary bookkeeping
# ---------------------------------------------------------------------
BF16_SYNTH = """HloModule bf16synth

%accum (x: bf16[], y: bf16[]) -> bf16[] {
  %x = bf16[] parameter(0)
  %y = bf16[] parameter(1)
  ROOT %z = bf16[] add(bf16[] %x, bf16[] %y)
}

ENTRY %main (p0: bf16[8,16], p1: bf16[16,4]) -> bf16[8] {
  %p0 = bf16[8,16]{1,0} parameter(0)
  %p1 = bf16[16,4]{1,0} parameter(1)
  %d = bf16[8,4]{1,0} dot(bf16[8,16]{1,0} %p0, bf16[16,4]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %cv = f32[8,4]{1,0} convert(bf16[8,4]{1,0} %d)
  %z = bf16[] constant(0)
  ROOT %r = bf16[8]{0} reduce(bf16[8,4]{1,0} %d, bf16[] %z), dimensions={1}, to_apply=%accum
}
"""

CLEAN_F32 = """HloModule clean

ENTRY %main (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  ROOT %r = f32[4]{0} add(f32[4]{0} %p0, f32[4]{0} %p0)
}
"""


def _rules(hazards):
    return [h["rule"] for h in hazards]


# ------------------------------------------------- hazard classifier

def test_hazards_on_synthetic_bf16():
    hz = dtypeflow.hazard_findings(BF16_SYNTH)
    assert sorted(_rules(hz)) == ["bf16-accum-reduction",
                                  "matmul-preferred-type"]
    by_rule = {h["rule"]: h for h in hz}
    assert by_rule["bf16-accum-reduction"]["op"] == "reduce"
    assert by_rule["matmul-preferred-type"]["op"] == "dot"
    # every hazard formats to the one-line audit shape
    for h in hz:
        assert dtypeflow.format_hazard(h).startswith(f"[{h['rule']}]")


def test_clean_f32_has_no_hazards():
    assert dtypeflow.hazard_findings(CLEAN_F32) == []


def test_dtype_summary_counts():
    s = dtypeflow.dtype_summary(BF16_SYNTH)
    assert s["f64_ops"] == 0
    assert s["converts"] == {"bf16->f32": 1}
    assert s["upcasts"] == {"bf16->f32": 1}


def test_program_ledger_shape():
    led = dtypeflow.program_ledger(BF16_SYNTH)
    assert set(led) == {"flows", "float_ops", "hazards"}
    assert led["float_ops"]["bf16"] > 0


# ------------------------------------------------- ONE dtype analyzer

def test_summarize_dtype_block_delegates_to_dtypeflow():
    """hlocheck's `dtype` contract section and dtypeflow must be the
    same analyzer — byte-identical output on the same text."""
    assert analysis.summarize(BF16_SYNTH, {})["dtype"] == \
        dtypeflow.dtype_summary(BF16_SYNTH)


def test_committed_contracts_keep_dtype_shape():
    """The migration is compat: every committed hlocheck contract
    still carries the {converts, f64_ops, upcasts} dtype block."""
    cdir = os.path.join(_ROOT, "contracts")
    foreign = {"lockorder", "amp_policy", "quant_policy"}
    seen = 0
    for fn in sorted(os.listdir(cdir)):
        if not fn.endswith(".json") or fn[:-5] in foreign:
            continue
        with open(os.path.join(cdir, fn)) as f:
            contract = json.load(f)
        for prog, summ in contract["programs"].items():
            assert set(summ["dtype"]) == \
                {"converts", "f64_ops", "upcasts"}, (fn, prog)
            seen += 1
    assert seen >= 6


# --------------------------------------------- seeded perturbations
# each seeds ONE hazard into a real pre-opt lowering and asserts the
# classifier names exactly that rule, the op, and this file as site

def test_seeded_bf16_accum_reduction():
    import jax
    import jax.numpy as jnp

    def softmaxish(a):                       # hand-rolled bf16 sum
        e = jnp.exp(a)
        return jax.lax.reduce(e, jnp.bfloat16(0), jax.lax.add, (1,))

    led = analysis.lowered_summary(
        softmaxish, jnp.ones((4, 8), jnp.bfloat16))
    assert _rules(led["hazards"]) == ["bf16-accum-reduction"]
    h = led["hazards"][0]
    assert h["op"] == "reduce"
    assert "test_prec.py" in h["site"]


def test_seeded_sub_f32_matmul():
    import jax.numpy as jnp

    led = analysis.lowered_summary(
        lambda a, b: a @ b,
        jnp.ones((4, 8), jnp.bfloat16), jnp.ones((8, 2), jnp.bfloat16))
    assert _rules(led["hazards"]) == ["matmul-preferred-type"]
    h = led["hazards"][0]
    assert h["op"] == "dot"
    assert "test_prec.py" in h["site"]
    assert "preferred_element_type" in h["detail"]


def test_seeded_f64_creep():
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    with enable_x64():
        led = analysis.lowered_summary(
            lambda a: (a.astype(jnp.float64) * 2.0).sum(),
            jnp.ones((4,), jnp.float32))
    # f64 flows through several ops, but ONLY the f64 rule fires
    assert set(_rules(led["hazards"])) == {"f64-creep"}
    assert any(h["op"] == "convert" and "test_prec.py" in h["site"]
               for h in led["hazards"])


def test_seeded_int8_accum_matmul():
    import jax
    import jax.numpy as jnp

    def q8_dot_no_accum(a, b):
        # tagged like the real pass, so ONLY the accumulation rule
        # fires — the missing preferred_element_type lets the s8xs8
        # product land back in s8
        with jax.named_scope("q8_seeded"):
            return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())))

    led = analysis.lowered_summary(
        q8_dot_no_accum,
        jnp.ones((4, 8), jnp.int8), jnp.ones((8, 2), jnp.int8))
    assert _rules(led["hazards"]) == ["int8-accum-matmul"]
    h = led["hazards"][0]
    assert h["op"] == "dot"
    assert "test_prec.py" in h["site"]
    assert "preferred_element_type=int32" in h["detail"]


def test_seeded_quant_missing_scale():
    import jax.numpy as jnp
    from jax import lax

    led = analysis.lowered_summary(
        lambda a, b: lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.int32),
        jnp.ones((4, 8), jnp.int8), jnp.ones((8, 2), jnp.int8))
    assert _rules(led["hazards"]) == ["quant-missing-scale"]
    h = led["hazards"][0]
    assert h["op"] == "dot"
    assert "test_prec.py" in h["site"]
    assert "q8_" in h["detail"]


def _bf16_step(x, y, oparams):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, flatten=False), nn.Dense(4, flatten=False))
    net.initialize(init="xavier")
    net(x)
    net.cast("bfloat16")
    return parallel.build_train_step(
        net, lambda p, t: ((p - t) ** 2).mean(), "sgd", dict(oparams))


def test_seeded_missing_master_weight():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(4, 8).astype(np.float32))
    y = nd.array(rng.randn(4, 4).astype(np.float32))
    step = _bf16_step(x, y, {"learning_rate": 0.05,
                             "multi_precision": False})
    sigs = step.param_sigs(x, y)
    finds = dtypeflow.master_weight_findings(step.optimizer, sigs)
    # one finding per bf16 param, each naming the param as the site
    assert len(finds) == len(sigs) > 0
    assert {f["rule"] for f in finds} == {"master-weight"}
    assert {f["op"] for f in finds} == {"sgd"}
    assert sorted(f["site"] for f in finds) == \
        sorted(name for name, _, _ in sigs)
    # the default (multi_precision unset -> auto) carries the master
    step_on = _bf16_step(x, y, {"learning_rate": 0.05})
    assert dtypeflow.master_weight_findings(
        step_on.optimizer, step_on.param_sigs(x, y)) == []


# ----------------------------------------- optimizer multi-precision

def test_bf16_master_weight_parity():
    """bf16 params + fp32 masters track full-f32 sgd within bf16
    resolution (measured max rel err 2.3e-3 over 5 steps), params
    STAY bf16 across steps, and the optimizer state carries only
    f32 leaves (the masters)."""
    import jax

    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, 16).astype(np.float32))
    y = nd.array(rng.randn(8, 4).astype(np.float32))

    def make():
        net = nn.HybridSequential()
        net.add(nn.Dense(16, flatten=False), nn.Dense(4, flatten=False))
        net.initialize(init="xavier")
        net(x)
        return net

    loss = lambda p, t: ((p - t) ** 2).mean()  # noqa: E731
    net_f = make()
    snap = snapshot_params(net_f)
    net_b = make()
    restore_params(net_b, snap)
    net_b.cast("bfloat16")

    step_f = parallel.build_train_step(net_f, loss, "sgd",
                                       {"learning_rate": 0.05})
    step_b = parallel.build_train_step(net_b, loss, "sgd",
                                       {"learning_rate": 0.05})
    lf = [float(step_f(x, y).asscalar()) for _ in range(5)]
    lb = [float(step_b(x, y).asscalar()) for _ in range(5)]
    np.testing.assert_allclose(lf, lb, rtol=0.02, atol=1e-3)
    assert lf[-1] < lf[0]  # both actually trained

    # weights never left bf16 (the pre-fix failure mode: the sgd rule
    # promoted them to f32 on step one and step two blew up)
    sigs = step_b.param_sigs(x, y)
    assert {dt for _, _, dt in sigs} == {"bfloat16"}
    # plain sgd has no base state, so every state leaf IS a master
    leaves = jax.tree_util.tree_leaves(step_b._opt_state)
    assert leaves and {str(l.dtype) for l in leaves} == {"float32"}
    assert dtypeflow.master_weight_findings(step_b.optimizer,
                                            sigs) == []


def test_eager_multi_precision_update():
    """The eager (gluon.Trainer) path: create_state_multi_precision
    hangs an f32 master off the state and update_multi_precision
    downcasts once per step."""
    from mxtpu import optimizer as optmod

    opt = optmod.SGD(learning_rate=0.1)
    w = nd.array(np.ones((4,), np.float32)).astype("bfloat16")
    g = nd.array(np.full((4,), 0.5, np.float32)).astype("bfloat16")
    state = opt.create_state_multi_precision(0, w)
    master = state[0]
    assert str(np.dtype(master.dtype)) == "float32"
    opt.update_multi_precision(0, w, g, state)
    assert "bfloat16" in str(np.dtype(w.dtype))
    got = w.asnumpy().astype(np.float32)
    # 1 - 0.1*0.5 = 0.95, rounded to the nearest bf16 (0.949219)
    np.testing.assert_allclose(got, np.full((4,), 0.949219), atol=1e-4)


# ------------------------------------------------------ runtime audit

class _FakeCompiled:
    def __init__(self, text):
        self._text = text

    def as_text(self):
        return self._text


def test_prec_audit_knob(monkeypatch):
    for k in ("MXTPU_PREC_AUDIT", "MXNET_PREC_AUDIT",
              "MXTPU_HLO_AUDIT", "MXNET_HLO_AUDIT"):
        monkeypatch.delenv(k, raising=False)
    dirty = _FakeCompiled(BF16_SYNTH)
    assert analysis.maybe_audit(dirty, label="t", mem={}) is None
    monkeypatch.setenv("MXTPU_PREC_AUDIT", "1")
    with pytest.warns(RuntimeWarning, match="precision audit"):
        analysis.maybe_audit(dirty, label="t", mem={})
    monkeypatch.setenv("MXTPU_PREC_AUDIT", "2")
    with pytest.raises(MXNetError, match="MXTPU_PREC_AUDIT=2"):
        analysis.maybe_audit(dirty, label="t", mem={})
    # a clean program passes silently even in raise mode
    assert analysis.maybe_audit(_FakeCompiled(CLEAN_F32), label="t",
                                mem={}) is not None


# ---------------------------------------------------------------- CLI

def _mxprec(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.mxprec", *args],
        capture_output=True, text=True, cwd=_ROOT, timeout=240)


def test_cli_roundtrip_determinism_and_drift(tmp_path):
    """--update then --check is a fixed point; two --update runs are
    byte-identical; a corrupted ledger fails with the target named."""
    d = str(tmp_path)
    up1 = _mxprec("--update", "selftest", "--contracts-dir", d)
    assert up1.returncode == 0, up1.stdout + up1.stderr
    path = tmp_path / "prec" / "selftest.json"
    first = path.read_bytes()

    up2 = _mxprec("--update", "selftest", "--contracts-dir", d)
    assert up2.returncode == 0, up2.stdout + up2.stderr
    assert path.read_bytes() == first  # byte-deterministic

    ok = _mxprec("--check", "selftest", "--contracts-dir", d)
    assert ok.returncode == 0, ok.stdout + ok.stderr

    ledger = json.loads(first)
    prog = next(iter(ledger["programs"]))
    ledger["programs"][prog]["float_ops"]["f64"] = 7
    path.write_text(json.dumps(ledger, indent=1, sort_keys=True)
                    + "\n")
    bad = _mxprec("--check", "selftest", "--contracts-dir", d)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "selftest" in bad.stdout


def test_cli_usage_errors(tmp_path):
    unk = _mxprec("--check", "no_such_target")
    assert unk.returncode == 2
    assert "unknown target" in unk.stderr

    empty = _mxprec("--check", "--contracts-dir", str(tmp_path))
    assert empty.returncode == 2
    assert "no ledgers" in empty.stderr

    (tmp_path / "prec").mkdir()
    (tmp_path / "prec" / "ghost.json").write_text("{}\n")
    orphan = _mxprec("--check", "--contracts-dir", str(tmp_path))
    assert orphan.returncode == 2
    assert "ghost" in orphan.stderr


@pytest.mark.slow
def test_committed_prec_ledgers_check_clean():
    """THE acceptance check: the committed tree passes a full
    `python -m tools.mxprec --check` (ledgers + amp_policy + README
    table) with exit 0."""
    r = _mxprec("--check")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 violation(s)" in r.stdout


def test_amp_policy_is_machine_derived():
    """amp_policy.json carries the four op classes with per-target
    evidence plus the kernel custom-call metadata — the exact inputs
    the AMP PR consumes."""
    with open(os.path.join(_ROOT, "contracts",
                           "amp_policy.json")) as f:
        policy = json.load(f)
    for cls in ("allow", "deny", "fp32_force", "inherit"):
        assert policy[cls], cls
        for op, entry in policy[cls].items():
            assert entry["reason"]
            assert entry["evidence"]  # {target: float-op count}
    assert "dot" in policy["allow"]
    assert "exponential" in policy["deny"]
    assert "reduce" in policy["fp32_force"]
    assert set(policy["custom_calls"]) == \
        {"batch_norm", "flash_attention", "layer_norm"}
    for meta in policy["custom_calls"].values():
        assert meta["accum_dtype"] == "f32"


def test_quant_policy_is_machine_derived():
    """quant_policy.json carries the allow/deny classes with
    per-target evidence plus the calibration block — thresholds under
    both estimators, per-channel weight scales, and the int8
    contraction census the serving contract pins."""
    with open(os.path.join(_ROOT, "contracts",
                           "quant_policy.json")) as f:
        policy = json.load(f)
    assert policy["targets"] == ["resnet18", "serving_bert"]
    for cls in ("allow", "deny"):
        assert policy[cls], cls
        for op, entry in policy[cls].items():
            assert entry["reason"], op
    assert "dot" in policy["allow"]
    assert "convolution" in policy["allow"]
    for op, entry in policy["allow"].items():
        assert entry["evidence"], op  # {target: float-op count}
    assert "exponential" in policy["deny"]
    assert "rsqrt" in policy["deny"]
    calib = policy["calibration"]
    th = calib["activation_thresholds"]
    assert set(th) == {"entropy", "minmax"}
    assert set(th["entropy"]) == set(th["minmax"]) \
        == set(calib["weight_scales"])
    for key, scales in calib["weight_scales"].items():
        assert scales and all(s > 0 for s in scales), key
    for census in calib["int8_contractions"].values():
        assert census == {"s8xs8->s32": 9}
