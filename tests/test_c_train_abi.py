"""Training-tier C ABI (VERDICT r3 item 8): a real compiled C program
trains 10 SGD steps of linear regression end-to-end through
MXNDArray* + NNGetOpHandle + MXImperativeInvoke, then save/load
roundtrips the weights.

Reference: ``src/c_api/c_api_ndarray.cc``† / ``c_api.cc``†
(SURVEY §2.1-N13).
"""
import os
import shutil
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CORE = os.path.join(_ROOT, "core")
_LIB = os.path.join(_CORE, "libmxtpu_ndarray.so")


def _build():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("g++/make not available")
    r = subprocess.run(["make", "ndarray", f"PYTHON={sys.executable}"],
                       cwd=_CORE, capture_output=True, text=True)
    assert r.returncode == 0, \
        f"libmxtpu_ndarray build failed: {r.stderr[-1000:]}"


def test_c_program_trains_linear_model(tmp_path):
    _build()
    cc = shutil.which("gcc") or shutil.which("g++")
    exe = str(tmp_path / "train_example")
    r = subprocess.run(
        [cc, os.path.join(_CORE, "train_example.c"),
         f"-L{_CORE}", "-lmxtpu_ndarray",
         f"-Wl,-rpath,{_CORE}", "-o", exe],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-1000:]
    env = dict(os.environ)
    # the embedded interpreter must see the repo package and run on
    # CPU (this tier tests the ABI, not the chip)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([exe], capture_output=True, text=True,
                       timeout=600, env=env)
    assert r.returncode == 0, \
        f"stdout:{r.stdout[-800:]}\nstderr:{r.stderr[-800:]}"
    assert "C-ABI training OK" in r.stdout, r.stdout[-800:]
    # 10 steps logged
    assert r.stdout.count("step ") == 10, r.stdout
