"""Module system + legacy model + callbacks/monitor/viz/profiler
(reference ``test_module.py``†, ``test_profiler.py``†)."""
import json
import logging

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu.io import DataBatch, NDArrayIter


def _mlp_symbol(hidden=16, classes=3):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _toy_iter(n=120, dim=6, classes=3, batch_size=20, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, dim).astype(np.float32)
    y = (X[:, :classes].argmax(axis=1)).astype(np.float32)
    return NDArrayIter(X, y, batch_size=batch_size, shuffle=True,
                       last_batch_handle="discard",
                       label_name="softmax_label")


def test_softmax_output_grad_semantics():
    """SoftmaxOutput backward = softmax - onehot (the implicit CE head
    every legacy symbol relies on)."""
    data = np.random.randn(4, 3).astype(np.float64)
    label = np.array([0, 2, 1, 1], np.float64)
    sym = _mlp_symbol()
    # direct op-level check
    x = nd.array(data)
    x.attach_grad()
    from mxtpu import autograd
    with autograd.record():
        out = nd.SoftmaxOutput(x, nd.array(label))
    out.backward()
    p = np.exp(data) / np.exp(data).sum(1, keepdims=True)
    onehot = np.eye(3)[label.astype(int)]
    np.testing.assert_allclose(x.grad.asnumpy(), p - onehot, rtol=1e-5,
                               atol=1e-6)


def test_module_fit_converges():
    """Module.fit on a separable toy problem reaches high accuracy
    (reference tests/python/train/test_mlp†)."""
    logging.disable(logging.CRITICAL)
    try:
        mod = mx.mod.Module(_mlp_symbol(), data_names=("data",),
                            label_names=("softmax_label",))
        train = _toy_iter()
        mod.fit(train, num_epoch=12, optimizer="adam",
                optimizer_params={"learning_rate": 0.05},
                initializer="xavier", eval_metric="acc")
        score = mod.score(_toy_iter(seed=1), "acc")
        assert dict(score)["accuracy"] > 0.9, score
    finally:
        logging.disable(logging.NOTSET)


def test_module_predict_and_io():
    mod = mx.mod.Module(_mlp_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    it = _toy_iter(n=40, batch_size=10)
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    mod.init_params(initializer="xavier")
    out = mod.predict(it)
    assert out.shape == (40, 3)
    probs = out.asnumpy()
    np.testing.assert_allclose(probs.sum(axis=1), np.ones(40),
                               rtol=1e-5)


def test_module_checkpoint_roundtrip(tmp_path):
    prefix = str(tmp_path / "mlp")
    mod = mx.mod.Module(_mlp_symbol())
    it = _toy_iter(n=20, batch_size=10)
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    mod.init_params(initializer="xavier")
    mod.save_checkpoint(prefix, 3)

    sym, arg, aux = mx.model.load_checkpoint(prefix, 3)
    assert "fc1_weight" in arg
    mod2 = mx.mod.Module.load(prefix, 3)
    mod2.bind(data_shapes=it.provide_data,
              label_shapes=it.provide_label)
    mod2.init_params()
    batch = next(it)
    mod.forward(batch, is_train=False)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                               mod2.get_outputs()[0].asnumpy(),
                               rtol=1e-5)


def test_bucketing_module():
    """Variable-length 'sequences' via bucketed symbols sharing
    params."""
    def sym_gen(seq_len):
        # params are seq-length independent (pooled over time), the
        # classic bucketing contract
        data = mx.sym.var("data")  # (N, seq_len, dim)
        pooled = mx.sym.mean(data, axis=1)
        fc = mx.sym.FullyConnected(pooled, num_hidden=4,
                                   name="shared_fc")
        out = mx.sym.SoftmaxOutput(fc, name="softmax")
        return out, ("data",), ("softmax_label",)

    from mxtpu.io import DataDesc
    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    mod.bind(data_shapes=[DataDesc("data", (4, 8, 5))],
             label_shapes=[DataDesc("softmax_label", (4,))])
    mod.init_params(initializer="xavier")
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})

    def batch_for(seq_len):
        b = DataBatch(
            data=[nd.array(np.random.randn(4, seq_len, 5)
                           .astype(np.float32))],
            label=[nd.array(np.zeros(4, np.float32))])
        b.bucket_key = seq_len
        b.provide_data = [DataDesc("data", (4, seq_len, 5))]
        b.provide_label = [DataDesc("softmax_label", (4,))]
        return b

    for seq_len in (8, 4, 8, 4):
        mod.forward(batch_for(seq_len), is_train=True)
        mod.backward()
        mod.update()
    assert set(mod._buckets) == {8, 4}
    w8 = mod._buckets[8]._exec.arg_dict["shared_fc_weight"]
    w4 = mod._buckets[4]._exec.arg_dict["shared_fc_weight"]
    assert w8 is w4  # same array object → shared


def test_callbacks_and_monitor():
    from mxtpu import callback
    from mxtpu.module.base_module import BatchEndParam
    from mxtpu import metric as metric_mod
    sp = callback.Speedometer(batch_size=32, frequent=2)
    m = metric_mod.create("acc")
    m.update([nd.array(np.array([0.0, 1.0]))],
             [nd.array(np.array([[0.9, 0.1], [0.1, 0.9]]))])
    for i in range(5):
        sp(BatchEndParam(epoch=0, nbatch=i, eval_metric=m,
                         locals=None))

    from mxtpu.monitor import Monitor
    sym = _mlp_symbol()
    it = _toy_iter(n=20, batch_size=10)
    mod = mx.mod.Module(sym)
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    mod.init_params(initializer="xavier")
    mon = Monitor(interval=1, pattern=".*")
    mod.install_monitor(mon)
    mon.tic()
    mod.forward(next(it), is_train=False)
    stats = mon.toc()
    assert len(stats) > 0


def test_profiler_chrome_trace(tmp_path):
    from mxtpu import profiler
    profiler.set_config(filename=str(tmp_path / "trace.json"))
    profiler.set_state("run")
    with profiler.Task("toy_task"):
        a = nd.array(np.random.randn(8, 8).astype(np.float32))
        b = nd.relu(a)
        (b * 2).asnumpy()
    c = profiler.Counter("my_counter", 0)
    c.increment(5)
    profiler.Marker("here").mark()
    profiler.set_state("stop")
    path = profiler.dump()
    trace = json.load(open(path))
    names = {ev["name"] for ev in trace["traceEvents"]}
    assert "toy_task" in names
    assert "relu" in names  # op-level event from the dispatcher
    assert "my_counter" in names
    table = profiler.aggregate_stats()
    assert "relu" in table


def test_print_summary(capsys):
    from mxtpu import visualization
    total = visualization.print_summary(_mlp_symbol(),
                                        shape={"data": (1, 6)})
    out = capsys.readouterr().out
    assert "Total params" in out
    # fc1: 6*16+16, fc2: 16*3+3
    assert total == 6 * 16 + 16 + 16 * 3 + 3


def test_feedforward_facade(tmp_path):
    logging.disable(logging.CRITICAL)
    try:
        ff = mx.model.FeedForward(_mlp_symbol(), num_epoch=3,
                                  optimizer="adam",
                                  optimizer_params={
                                      "learning_rate": 0.05},
                                  initializer="xavier")
        ff.fit(_toy_iter())
        pred = ff.predict(_toy_iter(seed=2, n=20, batch_size=10))
        assert pred.shape == (20, 3)
        ff.save(str(tmp_path / "ff"), 3)
        ff2 = mx.model.FeedForward.load(str(tmp_path / "ff"), 3)
        assert "fc1_weight" in ff2.arg_params
    finally:
        logging.disable(logging.NOTSET)


def test_softmax_output_int_labels():
    """Integer label arrays flow through the custom VJP (float0
    tangent; review regression)."""
    from mxtpu import autograd
    x = nd.array(np.random.randn(4, 3).astype(np.float32))
    x.attach_grad()
    label = nd.array(np.array([0, 2, 1, 1], np.int32))
    with autograd.record():
        out = nd.SoftmaxOutput(x, label)
    out.backward()
    assert np.isfinite(x.grad.asnumpy()).all()


def test_module_load_restores_optimizer_states(tmp_path):
    """save_checkpoint(save_optimizer_states=True) → Module.load(...,
    load_optimizer_states=True) restores momentum (review
    regression)."""
    prefix = str(tmp_path / "m")
    mod = mx.mod.Module(_mlp_symbol())
    it = _toy_iter(n=40, batch_size=20)
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    mod.init_params(initializer="xavier")
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    for b in it:
        mod.forward_backward(b)
        mod.update()
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)

    mod2 = mx.mod.Module.load(prefix, 1, load_optimizer_states=True)
    mod2.bind(data_shapes=it.provide_data,
              label_shapes=it.provide_label)
    mod2.init_params()
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1,
                                          "momentum": 0.9})
    s1 = mod._updater.states
    s2 = mod2._updater.states
    assert set(s1) == set(s2) and len(s1) > 0
    for k in s1:
        a = s1[k][0] if isinstance(s1[k], (tuple, list)) else s1[k]
        b = s2[k][0] if isinstance(s2[k], (tuple, list)) else s2[k]
        if a is None:
            assert b is None
        else:
            np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                       rtol=1e-6)


def test_module_init_params_allow_missing_initializes():
    """allow_missing params run the initializer, not zeros (review
    regression)."""
    mod = mx.mod.Module(_mlp_symbol())
    it = _toy_iter(n=20, batch_size=10)
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    partial = {"fc1_weight": nd.array(
        np.ones((16, 6), np.float32))}
    mod.init_params(initializer="xavier", arg_params=partial,
                    allow_missing=True)
    arg, _ = mod.get_params()
    np.testing.assert_allclose(arg["fc1_weight"].asnumpy(), 1.0)
    assert np.abs(arg["fc2_weight"].asnumpy()).sum() > 0  # initialized
