"""Pallas kernels vs lax references (SURVEY §7 M6; the
check_consistency discipline applied to the kernel tier).  On CPU the
kernels run in interpreter mode via MXTPU_PALLAS=interpret."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxtpu.kernels import (layer_norm, flash_attention)
from mxtpu.kernels.layer_norm import (layer_norm_reference,
                                      _layer_norm_pallas)
from mxtpu.kernels.flash_attention import (attention_reference,
                                           _flash_attention_pallas)


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("MXTPU_PALLAS", "interpret")
    # force the blockwise backward kernels (auto mode would pick the
    # AD-through-reference path at these small test shapes)
    monkeypatch.setenv("MXTPU_FLASH_BWD", "pallas")


def test_layer_norm_forward_parity():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 64).astype(np.float32))
    g = jnp.asarray(rng.uniform(0.5, 1.5, 64).astype(np.float32))
    b = jnp.asarray(rng.randn(64).astype(np.float32))
    got = _layer_norm_pallas(x, g, b, 1e-5)
    ref = layer_norm_reference(x, g.reshape(1, -1), b.reshape(1, -1))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_layer_norm_3d_and_odd_rows():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(3, 7, 48).astype(np.float32))
    g = jnp.asarray(rng.uniform(0.5, 1.5, (1, 1, 48)).astype(np.float32))
    b = jnp.asarray(rng.randn(1, 1, 48).astype(np.float32))
    got = layer_norm(x, g, b)
    ref = layer_norm_reference(x, g, b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_layer_norm_backward_parity():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(16, 32).astype(np.float32))
    g = jnp.asarray(rng.uniform(0.5, 1.5, 32).astype(np.float32))
    b = jnp.asarray(rng.randn(32).astype(np.float32))
    dy = jnp.asarray(rng.randn(16, 32).astype(np.float32))

    def f_pallas(x, g, b):
        return jnp.sum(_layer_norm_pallas(x, g, b, 1e-5) * dy)

    def f_ref(x, g, b):
        return jnp.sum(layer_norm_reference(
            x, g.reshape(1, -1), b.reshape(1, -1)) * dy)

    gp = jax.grad(f_pallas, argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(x, g, b)
    for a, e, name in zip(gp, gr, ["dx", "dgamma", "dbeta"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_layer_norm_op_integration():
    """nd.LayerNorm routes through the fused kernel and still matches
    the composite."""
    from mxtpu import nd
    rng = np.random.RandomState(3)
    x = rng.randn(4, 24).astype(np.float32)
    g = rng.uniform(0.5, 1.5, 24).astype(np.float32)
    b = rng.randn(24).astype(np.float32)
    out = nd.LayerNorm(nd.array(x), nd.array(g), nd.array(b))
    ref = layer_norm_reference(jnp.asarray(x),
                               jnp.asarray(g).reshape(1, -1),
                               jnp.asarray(b).reshape(1, -1))
    np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# ----------------------------------------------------------------------
# flash attention
# ----------------------------------------------------------------------

def test_flash_attention_parity():
    rng = np.random.RandomState(4)
    B, H, T, D = 2, 3, 32, 16
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.5
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.5
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    got = _flash_attention_pallas(q, k, v, False, 1.0 / np.sqrt(D))
    ref = attention_reference(q, k, v, False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_causal():
    rng = np.random.RandomState(5)
    B, H, T, D = 1, 2, 24, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.5
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.5
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    got = _flash_attention_pallas(q, k, v, True, 1.0 / np.sqrt(D))
    ref = attention_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # causality: output at t must not depend on future v
    v2 = v.at[:, :, -1].set(v[:, :, -1] + 100.0)
    got2 = _flash_attention_pallas(q, k, v2, True, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(got[:, :, :-1]),
                               np.asarray(got2[:, :, :-1]),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_cross_lengths():
    """Tk > Tq (decoding with cache) incl. causal diagonal alignment."""
    rng = np.random.RandomState(6)
    B, H, Tq, Tk, D = 1, 2, 8, 32, 16
    q = jnp.asarray(rng.randn(B, H, Tq, D).astype(np.float32)) * 0.5
    k = jnp.asarray(rng.randn(B, H, Tk, D).astype(np.float32)) * 0.5
    v = jnp.asarray(rng.randn(B, H, Tk, D).astype(np.float32))
    for causal in (False, True):
        got = _flash_attention_pallas(q, k, v, causal, 1.0 / np.sqrt(D))
        ref = attention_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"causal={causal}")


def test_flash_attention_grad():
    rng = np.random.RandomState(7)
    B, H, T, D = 1, 1, 16, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    do = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) * do)

    def f_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) * do)

    gp = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, e, name in zip(gp, gr, ["dq", "dk", "dv"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_flash_attention_grad_multiblock():
    """Backward with several q and kv blocks (T=256 → 2×128 blocks),
    causal and not — exercises the blockwise dq/dkv accumulation and
    the causal block-skip in both backward kernels."""
    rng = np.random.RandomState(9)
    B, H, T, D = 1, 2, 256, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    do = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    for causal in (False, True):
        def f(q, k, v):
            return jnp.sum(_flash_attention_pallas(
                q, k, v, causal, 1.0 / np.sqrt(D)) * do)

        def f_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal) * do)

        gp = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, e, name in zip(gp, gr, ["dq", "dk", "dv"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=2e-4, atol=2e-4,
                err_msg=f"{name} causal={causal}")


def test_flash_attention_grad_cross_lengths():
    """Tk != Tq backward (cached decoding shapes), causal diagonal
    offset included."""
    rng = np.random.RandomState(10)
    B, H, Tq, Tk, D = 1, 1, 8, 32, 8
    q = jnp.asarray(rng.randn(B, H, Tq, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, Tk, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, Tk, D).astype(np.float32))
    do = jnp.asarray(rng.randn(B, H, Tq, D).astype(np.float32))
    for causal in (False, True):
        def f(q, k, v):
            return jnp.sum(_flash_attention_pallas(
                q, k, v, causal, 1.0 / np.sqrt(D)) * do)

        def f_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal) * do)

        gp = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, e, name in zip(gp, gr, ["dq", "dk", "dv"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=2e-4, atol=2e-4,
                err_msg=f"{name} causal={causal}")


def test_flash_attention_causal_tq_gt_tk():
    """Tq > Tk causal: the first Tq-Tk rows have NO visible key.
    Convention: those rows output 0 with zero gradients (kernel and
    reference agree); regression for the lse-sentinel-absorption bug
    that inflated their backward by Tk×."""
    rng = np.random.RandomState(12)
    B, H, Tq, Tk, D = 1, 1, 16, 8, 8
    q = jnp.asarray(rng.randn(B, H, Tq, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, Tk, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, Tk, D).astype(np.float32))
    do = jnp.asarray(rng.randn(B, H, Tq, D).astype(np.float32))
    got = _flash_attention_pallas(q, k, v, True, 1.0 / np.sqrt(D))
    ref = attention_reference(q, k, v, True)
    # fully-masked rows are exactly zero in both
    assert np.all(np.asarray(got)[:, :, :Tq - Tk] == 0.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def f(q, k, v):
        return jnp.sum(_flash_attention_pallas(
            q, k, v, True, 1.0 / np.sqrt(D)) * do)

    def f_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, True) * do)

    gp = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, e, name in zip(gp, gr, ["dq", "dk", "dv"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-4, atol=1e-4, err_msg=name)
    # masked rows contribute zero dq
    assert np.all(np.asarray(gp[0])[:, :, :Tq - Tk] == 0.0)


def test_flash_attention_grad_dispatch_modes(monkeypatch):
    """'auto' (→ ref path at small T) and 'ref' agree with 'pallas';
    unknown modes raise.  Covers the dispatch predicate the autouse
    fixture otherwise pins to 'pallas'."""
    rng = np.random.RandomState(11)
    B, H, T, D = 1, 1, 16, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    do = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))

    def grads():
        def f(q, k, v):
            return jnp.sum(_flash_attention_pallas(
                q, k, v, True, 1.0 / np.sqrt(D)) * do)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    results = {}
    for mode in ("pallas", "auto", "ref"):
        monkeypatch.setenv("MXTPU_FLASH_BWD", mode)
        results[mode] = grads()
    for mode in ("auto", "ref"):
        for a, e in zip(results[mode], results["pallas"]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       rtol=1e-4, atol=1e-4)
    monkeypatch.setenv("MXTPU_FLASH_BWD", "blockwise")
    with pytest.raises(ValueError):
        grads()


def test_flash_attention_op():
    from mxtpu import nd
    rng = np.random.RandomState(8)
    q = rng.randn(1, 2, 16, 8).astype(np.float32)
    k = rng.randn(1, 2, 16, 8).astype(np.float32)
    v = rng.randn(1, 2, 16, 8).astype(np.float32)
    out = nd.flash_attention(nd.array(q), nd.array(k), nd.array(v),
                             causal=True)
    ref = attention_reference(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), True)
    np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_unaligned_pads_not_falls_back():
    """T not a multiple of 8 (e.g. the observed T=12) keeps the fused
    kernel via exact pad-and-mask — no warning, reference parity."""
    import warnings
    rng = np.random.RandomState(9)
    B, H, D = 2, 3, 16
    cases = [(12, 12, True), (12, 12, False), (5, 5, True),
             (7, 19, False), (12, 20, True),
             (12, 16, True), (13, 7, True)]  # incl. Tq ≢ Tk mod 8
    for Tq, Tk, causal in cases:
        q = jnp.asarray(rng.randn(B, H, Tq, D).astype(np.float32)) * 0.5
        k = jnp.asarray(rng.randn(B, H, Tk, D).astype(np.float32)) * 0.5
        v = jnp.asarray(rng.randn(B, H, Tk, D).astype(np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            got = flash_attention(q, k, v, causal=causal)
        assert not w, (Tq, Tk, causal, [str(x.message) for x in w])
        ref = attention_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"Tq={Tq} Tk={Tk} "
                                           f"causal={causal}")


def test_flash_attention_unaligned_causal_no_future_leak():
    """Padded causal run stays causal: perturbing future keys/values
    must not change earlier outputs."""
    rng = np.random.RandomState(10)
    B, H, T, D = 1, 2, 12, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.5
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.5
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    got = flash_attention(q, k, v, causal=True)
    v2 = v.at[:, :, -1].set(v[:, :, -1] + 100.0)
    got2 = flash_attention(q, k, v2, causal=True)
    np.testing.assert_allclose(np.asarray(got[:, :, :-1]),
                               np.asarray(got2[:, :, :-1]),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_unaligned_grad():
    """Gradients flow through the pad-and-mask path and match the
    reference."""
    rng = np.random.RandomState(11)
    B, H, T, D = 1, 2, 12, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.5
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.5
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    for causal in (True, False):
        gp = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=causal) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(
            attention_reference(q, k, v, causal=causal) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, e, name in zip(gp, gr, ["dq", "dk", "dv"]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"{name} causal={causal}")


def test_flash_attention_unaligned_causal_cross_hits_kernel():
    """Causal cross lengths with Tq % 8 != Tk % 8 used to warn and
    fall back (plain padding would shift the diagonal); the static
    valid_kv mask + explicit delta now keep them on the fused kernel:
    no warning, reference parity for values AND grads."""
    import warnings
    rng = np.random.RandomState(12)
    B, H, D = 1, 2, 8
    for Tq, Tk in ((12, 16), (13, 7), (5, 30)):
        q = jnp.asarray(rng.randn(B, H, Tq, D).astype(np.float32)) * 0.5
        k = jnp.asarray(rng.randn(B, H, Tk, D).astype(np.float32)) * 0.5
        v = jnp.asarray(rng.randn(B, H, Tk, D).astype(np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            got = flash_attention(q, k, v, causal=True)
        assert not w, [str(x.message) for x in w]
        ref = attention_reference(q, k, v, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        gp = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(
            attention_reference(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, e, name in zip(gp, gr, ["dq", "dk", "dv"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-4,
                err_msg=f"{name} Tq={Tq} Tk={Tk}")


def test_transformer_model_odd_seq_hits_kernel():
    """Model-layer guarantee: an encoder forward at an odd sequence
    length emits no fallback warning and matches the reference
    attention semantics (ISSUE 2 tentpole 3)."""
    import warnings
    from mxtpu import nd
    from mxtpu.models.transformer import TransformerEncoder
    rng = np.random.RandomState(13)
    net = TransformerEncoder(1, 32, 64, 4, dropout=0.0)
    net.initialize()
    x = nd.array(rng.randn(2, 13, 32).astype(np.float32))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        y = net(x)
    fallback = [x for x in w if "falling back" in str(x.message)]
    assert not fallback, [str(x.message) for x in fallback]
    assert y.shape == (2, 13, 32)
