"""Model zoo construction + forward shapes (reference
``tests/python/unittest/test_gluon_model_zoo.py``†).  Small spatial
inputs keep CPU runtime sane; resnet50 also checks hybridize and a
training step."""
import numpy as np
import pytest

import os

import mxtpu as mx
from mxtpu import autograd, nd
from mxtpu.gluon.model_zoo import get_model, vision

# The full zoo sweep is minutes of CPU conv time; the quick suite keeps
# one model per family and the nightly-style sweep runs with
# MXTPU_TEST_SLOW=1 (the reference splits unittest vs nightly the same
# way, SURVEY §4.3).
slow = pytest.mark.skipif(not os.environ.get("MXTPU_TEST_SLOW"),
                          reason="set MXTPU_TEST_SLOW=1 for full sweep")


@pytest.mark.parametrize("name", [
    "resnet18_v2", "squeezenet1.1",
])
def test_zoo_forward_shapes(name):
    net = get_model(name, classes=10)
    net.initialize(init="xavier")
    x = nd.array(np.random.randn(2, 3, 64, 64).astype(np.float32))
    out = net(x)
    assert out.shape == (2, 10), (name, out.shape)


@slow
def test_zoo_forward_shapes_full():
    for name in ["resnet18_v1", "resnet50_v1", "resnet50_v2",
                 "mobilenet0.25", "mobilenetv2_0.25"]:
        net = get_model(name, classes=10)
        net.initialize(init="xavier")
        x = nd.array(np.random.randn(2, 3, 64, 64).astype(np.float32))
        assert net(x).shape == (2, 10), name


def test_vgg_small():
    net = vision.vgg11(classes=7)
    net.initialize(init="xavier")
    out = net(nd.array(np.random.randn(1, 3, 32, 32).astype(np.float32)))
    assert out.shape == (1, 7)


@slow
def test_alexnet_shape():
    # alexnet's dense head needs the full 224x224 spatial extent
    net = vision.alexnet(classes=5)
    net.initialize(init="xavier")
    out = net(nd.array(
        np.random.randn(1, 3, 224, 224).astype(np.float32)))
    assert out.shape == (1, 5)


@slow
def test_densenet_shape():
    net = vision.densenet121(classes=4)
    net.initialize(init="xavier")
    out = net(nd.array(np.random.randn(1, 3, 64, 64).astype(np.float32)))
    assert out.shape == (1, 4)


def test_resnet_thumbnail_cifar():
    net = vision.get_resnet(1, 18, thumbnail=True, classes=10)
    net.initialize(init="xavier")
    out = net(nd.array(np.random.randn(2, 3, 32, 32).astype(np.float32)))
    assert out.shape == (2, 10)


def test_get_model_errors():
    with pytest.raises(mx.MXNetError):
        get_model("resnet9000")
    with pytest.raises(mx.MXNetError):
        vision.resnet18_v1(pretrained=True)


def test_resnet18_hybridize_and_train_step():
    from mxtpu import gluon
    from mxtpu.gluon import loss as gloss
    net = vision.get_resnet(1, 18, thumbnail=True, classes=3)
    net.initialize(init="xavier")
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    L = gloss.SoftmaxCrossEntropyLoss()
    x = nd.array(np.random.randn(4, 3, 32, 32).astype(np.float32))
    y = nd.array(np.array([0, 1, 2, 0], np.float32))
    losses = []
    for _ in range(4):
        with autograd.record():
            l = L(net(x), y)
        l.backward()
        trainer.step(4)
        losses.append(float(l.mean().asnumpy()))
    assert losses[-1] < losses[0], losses
    # eval mode uses running stats (different from batch stats)
    out_train_off = net(x)
    assert np.isfinite(out_train_off.asnumpy()).all()


@slow
def test_inception_shape():
    net = vision.inception_v3(classes=6)
    net.initialize(init="xavier")
    # inception v3 needs >= 299x299 nominally; 299 keeps the 8x8 pool
    x = nd.array(np.random.randn(1, 3, 299, 299).astype(np.float32))
    out = net(x)
    assert out.shape == (1, 6)
