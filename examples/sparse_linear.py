"""Sparse linear classification (reference
``example/sparse/linear_classification/train.py``†): libsvm data,
kvstore-held row_sparse weight with server-side optimizer,
row_sparse_pull per batch.

TPU-native: storage is dense-backed (SURVEY §7 hard-part 3) — the
row_sparse API surface is kept while XLA computes dense math; the
recipe (LibSVMIter → dot → push grads → row_sparse_pull) matches the
reference.

  python examples/sparse_linear.py --epochs 5
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu import autograd, nd
from mxtpu.io import LibSVMIter


def write_synthetic_libsvm(path, n=512, dim=100, density=0.1, seed=0):
    """Sparse features; label = sign of a fixed sparse hyperplane."""
    rng = np.random.RandomState(seed)
    w_true = rng.randn(dim) * (rng.rand(dim) < 0.3)
    with open(path, "w") as f:
        for _ in range(n):
            nnz = max(1, int(density * dim))
            idx = np.sort(rng.choice(dim, nnz, replace=False))
            val = rng.randn(nnz)
            y = 1 if float(val @ w_true[idx]) > 0 else 0
            feats = " ".join(f"{i}:{v:.4f}" for i, v in zip(idx, val))
            f.write(f"{y} {feats}\n")
    return path


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None,
                    help="libsvm file (default: synthesize one)")
    ap.add_argument("--dim", type=int, default=100)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)

    path = args.data or write_synthetic_libsvm(
        "/tmp/sparse_train.libsvm", dim=args.dim)
    it = LibSVMIter(path, data_shape=(args.dim,),
                    batch_size=args.batch_size)

    # kvstore owns the row_sparse weight; optimizer runs server-side
    # on push (the reference's update_on_kvstore path)
    weight = nd.sparse.zeros("row_sparse", (args.dim, 2))
    bias = nd.zeros((2,))
    kv = mx.kvstore.create("local")
    kv.init("w", weight)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=args.lr))

    for epoch in range(args.epochs):
        it.reset()
        total, n, correct, seen = 0.0, 0, 0, 0
        for batch in it:
            x = batch.data[0]
            y = batch.label[0].reshape((-1,))
            # pull only the touched rows (API parity; dense-backed)
            row_ids = nd.array(np.arange(args.dim, dtype=np.float32))
            w_cur = nd.zeros((args.dim, 2))
            kv.row_sparse_pull("w", out=w_cur, row_ids=row_ids)
            w_cur.attach_grad()
            bias.attach_grad()
            with autograd.record():
                logits = nd.dot(x, w_cur) + bias
                logp = nd.log_softmax(logits, axis=-1)
                loss = -nd.mean(nd.pick(logp, y, axis=-1))
            loss.backward()
            kv.push("w", w_cur.grad)      # server applies SGD
            bias -= args.lr * bias.grad
            total += float(loss.asscalar())
            n += 1
            pred = logits.asnumpy().argmax(axis=1)
            keep = len(pred) - batch.pad
            correct += int((pred[:keep] == y.asnumpy()[:keep]).sum())
            seen += keep
        logging.info("epoch %d: loss %.4f acc %.3f", epoch, total / n,
                     correct / seen)


if __name__ == "__main__":
    main()
