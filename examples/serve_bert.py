"""End-to-end ``mxtpu.serving`` demo: export a small BERT, stand up an
``InferenceServer`` with dynamic batching over two sequence buckets,
fire concurrent mixed-length requests at it, and print the stats
snapshot (p50/p95/p99, fill-rate, req/sec).

  python examples/serve_bert.py
  python examples/serve_bert.py --clients 8 --requests 50 --layers 2

Knobs the serving layer reads from the environment (see README
"Serving"): MXTPU_SERVING_MAX_BATCH, MXTPU_SERVING_MAX_DELAY_US,
MXTPU_SERVING_MAX_QUEUE, MXTPU_SERVING_DONATE.
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxtpu import nd
from mxtpu.models.transformer import BERTModel
from mxtpu.serving import InferenceServer, ModelRunner, ServerBusy


def export_model(args, workdir):
    """Train-side artifact step: build, init, export (the same
    ``-symbol.json`` + ``.params`` pair ``Module.save_checkpoint``
    produces)."""
    net = BERTModel(args.vocab, args.units, 4 * args.units,
                    args.layers, args.heads, max_length=args.seq_len,
                    dropout=0.0)
    net.initialize(init="xavier")
    rng = np.random.RandomState(0)
    net(nd.array(rng.randint(0, args.vocab, (1, args.seq_len))
                 .astype(np.float32)))       # materialize params
    return net.export(os.path.join(workdir, "bert"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=25,
                    help="requests per client")
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--units", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=64,
                    help="largest sequence bucket")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--timeout-s", type=float, default=30.0)
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as d:
        sym_file, param_file = export_model(args, d)
        print(f"exported: {os.path.basename(sym_file)} + "
              f"{os.path.basename(param_file)}")

        # serve-side: load the artifacts, pre-compile the bucket
        # ladder (pow2 batches x two sequence buckets), share ONE
        # weight upload across every bucket executable
        runner = ModelRunner.from_export(
            sym_file, param_file, input_specs={"data": (None,)},
            seq_buckets=[args.seq_len // 2, args.seq_len],
            max_batch_size=args.max_batch)
        t0 = time.perf_counter()
        runner.warmup()
        print(f"warmup: compiled {runner.num_compiled()} bucket "
              f"executables in {time.perf_counter() - t0:.1f}s "
              f"(weights uploaded once: "
              f"{runner.weight_bytes() / 2**20:.1f} MB)")

        server = InferenceServer(log_every_s=2.0)
        server.register("bert", runner, warmup=False)

        rng = np.random.RandomState(1)
        failures = []

        def client(cid):
            for _ in range(args.requests):
                n = int(rng.randint(args.seq_len // 4,
                                    args.seq_len + 1))
                toks = rng.randint(0, args.vocab, (n,)) \
                    .astype(np.float32)
                try:
                    req = server.submit("bert", {"data": toks},
                                        timeout_s=args.timeout_s)
                    (logits,) = req.result(
                        timeout=args.timeout_s + 5.0)
                    assert logits.shape == (n, args.vocab), \
                        logits.shape
                except ServerBusy:
                    failures.append((cid, "busy"))
                except Exception as e:  # noqa: BLE001
                    failures.append((cid, repr(e)))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(args.clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0

        # close BEFORE the snapshot: workers account a batch after
        # delivering its results, so joining them first makes the
        # final tally exact (completed == every delivered request)
        server.close()
        snap = server.stats("bert")
        total = args.clients * args.requests
        print(f"\n{total} requests from {args.clients} concurrent "
              f"clients in {wall:.2f}s "
              f"({snap['completed'] / wall:.1f} req/sec end-to-end)")
        print(json.dumps(snap, indent=2))
        if failures:
            print(f"failures: {failures[:10]}")
            return 1
        return 0


if __name__ == "__main__":
    sys.exit(main())
