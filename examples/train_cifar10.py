"""CIFAR-10 training through the canonical recipe
(reference ``example/image-classification/train_cifar10.py``† over
``common/fit.py``†).

Reads CIFAR-10 python-pickle batches under --data-dir when present,
else synthesizes CIFAR-shaped data (no network access here).

  python examples/train_cifar10.py --num-epochs 2 --network cifar_cnn
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import mxtpu as mx
from common_fit import add_fit_args, fit
from mxtpu.io import NDArrayIter


def residual_unit(data, num_filter, stride, dim_match, name):
    """Symbol-level ResNet v2 unit (reference
    ``symbols/resnet.py``† residual_unit)."""
    bn1 = mx.sym.BatchNorm(data, fix_gamma=False, name=name + "_bn1")
    act1 = mx.sym.Activation(bn1, act_type="relu")
    conv1 = mx.sym.Convolution(act1, num_filter=num_filter,
                               kernel=(3, 3), stride=(stride, stride),
                               pad=(1, 1), no_bias=True,
                               name=name + "_conv1")
    bn2 = mx.sym.BatchNorm(conv1, fix_gamma=False, name=name + "_bn2")
    act2 = mx.sym.Activation(bn2, act_type="relu")
    conv2 = mx.sym.Convolution(act2, num_filter=num_filter,
                               kernel=(3, 3), stride=(1, 1),
                               pad=(1, 1), no_bias=True,
                               name=name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = mx.sym.Convolution(act1, num_filter=num_filter,
                                      kernel=(1, 1),
                                      stride=(stride, stride),
                                      no_bias=True, name=name + "_sc")
    return conv2 + shortcut


def resnet_cifar(num_classes=10, num_layers=8):
    """resnet-(6n+2) for 32x32 inputs (reference cifar resnet)."""
    assert (num_layers - 2) % 6 == 0
    n = (num_layers - 2) // 6
    data = mx.sym.Variable("data")
    body = mx.sym.Convolution(data, num_filter=16, kernel=(3, 3),
                              stride=(1, 1), pad=(1, 1), no_bias=True,
                              name="conv0")
    for stage, filters in enumerate((16, 32, 64)):
        for unit in range(n):
            stride = 2 if stage > 0 and unit == 0 else 1
            body = residual_unit(body, filters, stride,
                                 dim_match=(stage == 0 or unit > 0),
                                 name=f"stage{stage}_unit{unit}")
    bn = mx.sym.BatchNorm(body, fix_gamma=False, name="bn_final")
    act = mx.sym.Activation(bn, act_type="relu")
    pool = mx.sym.Pooling(act, global_pool=True, pool_type="avg",
                          kernel=(8, 8))
    flat = mx.sym.Flatten(pool)
    fc = mx.sym.FullyConnected(flat, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def cifar_cnn(num_classes=10):
    """Small convnet for smoke runs."""
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=32, kernel=(3, 3),
                             pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, num_filter=64, kernel=(3, 3),
                             pad=(1, 1), name="conv2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def load_cifar(data_dir, batch_size, n_synth=2048):
    import pickle
    train_files = [os.path.join(data_dir, f"data_batch_{i}")
                   for i in range(1, 6)]
    if all(os.path.exists(f) for f in train_files):
        xs, ys = [], []
        for f in train_files:
            with open(f, "rb") as fh:
                d = pickle.load(fh, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.float32)
                      .reshape(-1, 3, 32, 32) / 255.0)
            ys.append(np.asarray(d[b"labels"], np.float32))
        X = np.concatenate(xs)
        y = np.concatenate(ys)
    else:
        logging.warning("CIFAR-10 batches not found under %s — "
                        "synthetic data", data_dir)
        rng = np.random.RandomState(0)
        X = rng.rand(n_synth, 3, 32, 32).astype(np.float32)
        # learnable synthetic signal: class shifts channel 0 brightness
        y = rng.randint(0, 2, n_synth).astype(np.float32)
        X[:, 0] += y[:, None, None] * 0.3
    split = int(0.9 * len(X))
    train = NDArrayIter(X[:split], y[:split], batch_size=batch_size,
                        shuffle=True, last_batch_handle="discard")
    val = NDArrayIter(X[split:], y[split:], batch_size=batch_size,
                      last_batch_handle="discard")
    return train, val


NETWORKS = {"cifar_cnn": cifar_cnn,
            "resnet8": lambda num_classes: resnet_cifar(num_classes, 8),
            "resnet20": lambda num_classes: resnet_cifar(num_classes,
                                                         20)}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-dir", default=os.path.expanduser(
        "~/.mxnet/datasets/cifar10/cifar-10-batches-py"))
    add_fit_args(parser)
    parser.set_defaults(network="cifar_cnn", num_classes=10,
                        num_epochs=3, batch_size=128, lr=0.01)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    net_fn = NETWORKS.get(args.network)
    if net_fn is None:
        sys.exit(f"unknown --network {args.network}; "
                 f"choices {sorted(NETWORKS)}")
    sym = net_fn(num_classes=args.num_classes)
    train, val = load_cifar(args.data_dir, args.batch_size)
    fit(args, sym, train, val)


if __name__ == "__main__":
    main()
