"""Character-level LSTM language model — the reference's classic
``example/rnn/char-lstm``† / ``char_lstm.ipynb``† recipe.

Trains on a text file (or a built-in Shakespeare-ish snippet when no
--data is given), then samples text.  The whole unrolled step runs as
one compiled program (Embedding → LSTM → Dense over time).

  python examples/char_rnn.py --epochs 3 --seq-len 64
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu import autograd, gluon, nd
from mxtpu.gluon import nn, rnn

_FALLBACK = (
    "the quick brown fox jumps over the lazy dog. "
    "to be or not to be, that is the question: whether tis nobler "
    "in the mind to suffer the slings and arrows of outrageous "
    "fortune, or to take arms against a sea of troubles. "
) * 40


class CharLM(gluon.HybridBlock):
    def __init__(self, vocab, embed=64, hidden=128, layers=2,
                 **kwargs):
        super().__init__(**kwargs)
        self.embed = nn.Embedding(vocab, embed)
        self.lstm = rnn.LSTM(hidden, num_layers=layers,
                             layout="NTC")
        self.out = nn.Dense(vocab, flatten=False)

    def hybrid_forward(self, F, x):
        return self.out(self.lstm(self.embed(x)))


def batches(ids, batch_size, seq_len, rng):
    n = (len(ids) - 1) // seq_len
    starts = rng.permutation(n)[: (n // batch_size) * batch_size]
    for i in range(0, len(starts), batch_size):
        s = starts[i:i + batch_size]
        x = np.stack([ids[j * seq_len:(j + 1) * seq_len] for j in s])
        y = np.stack([ids[j * seq_len + 1:(j + 1) * seq_len + 1]
                      for j in s])
        yield nd.array(x.astype(np.float32)), \
            nd.array(y.astype(np.float32))


def sample(net, stoi, itos, seed_text, length, temperature=0.8):
    ids = [stoi[c] for c in seed_text if c in stoi]
    rng = np.random.RandomState(0)
    for _ in range(length):
        x = nd.array(np.asarray(ids, np.float32)[None])
        logits = net(x).asnumpy()[0, -1] / temperature
        p = np.exp(logits - logits.max())
        p /= p.sum()
        ids.append(int(rng.choice(len(p), p=p)))
    return "".join(itos[i] for i in ids)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", type=str, default=None,
                    help="path to a text file")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--sample-len", type=int, default=120)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    text = open(args.data).read() if args.data else _FALLBACK
    chars = sorted(set(text))
    stoi = {c: i for i, c in enumerate(chars)}
    itos = {i: c for c, i in stoi.items()}
    ids = np.asarray([stoi[c] for c in text], np.int32)
    logging.info("corpus: %d chars, vocab %d", len(ids), len(chars))

    mx.random.seed(0)
    net = CharLM(len(chars))
    net.initialize(init="xavier")
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = None
    rng = np.random.RandomState(0)
    for epoch in range(args.epochs):
        total, n = 0.0, 0
        for x, y in batches(ids, args.batch_size, args.seq_len, rng):
            if trainer is None:
                net(x)
                trainer = gluon.Trainer(net.collect_params(), "adam",
                                        {"learning_rate": args.lr})
            with autograd.record():
                logits = net(x)
                loss = nd.mean(loss_fn(logits, y))
            loss.backward()
            trainer.step(batch_size=x.shape[0])
            total += float(loss.asscalar())
            n += 1
        logging.info("epoch %d: perplexity %.2f", epoch,
                     float(np.exp(total / max(n, 1))))
    print(sample(net, stoi, itos, "the ", args.sample_len))


if __name__ == "__main__":
    main()
