"""Policy-gradient RL recipe (the reference ships DQN/A3C under
``example/reinforcement-learning/``†; no game emulator exists in this
environment, so the environment is a built-in numpy gridworld — the
recipe shape is what carries over: rollout → returns → REINFORCE
update through autograd).

  python examples/reinforce.py --episodes 150
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu import autograd, gluon, nd
from mxtpu.gluon import nn


class GridWorld:
    """5x5 grid; start random, goal fixed; actions URDL; reward 1 at
    the goal, -0.01 per step; episode cap 20 steps."""

    SIZE = 5
    GOAL = (4, 4)
    MOVES = ((-1, 0), (0, 1), (1, 0), (0, -1))

    def __init__(self, rng):
        self.rng = rng

    def reset(self):
        self.pos = (int(self.rng.randint(self.SIZE)),
                    int(self.rng.randint(self.SIZE)))
        self.t = 0
        return self._obs()

    def _obs(self):
        o = np.zeros((self.SIZE, self.SIZE), np.float32)
        o[self.pos] = 1.0
        o[self.GOAL] += 0.5
        return o.ravel()

    def step(self, action):
        dy, dx = self.MOVES[action]
        y = min(max(self.pos[0] + dy, 0), self.SIZE - 1)
        x = min(max(self.pos[1] + dx, 0), self.SIZE - 1)
        self.pos = (y, x)
        self.t += 1
        done = self.pos == self.GOAL or self.t >= 20
        reward = 1.0 if self.pos == self.GOAL else -0.01
        return self._obs(), reward, done


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=150)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--gamma", type=float, default=0.95)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    env = GridWorld(rng)

    policy = nn.Sequential()
    policy.add(nn.Dense(64, activation="relu"), nn.Dense(4))
    policy.initialize(init="xavier")
    policy(nd.array(np.zeros((1, 25), np.float32)))
    trainer = gluon.Trainer(policy.collect_params(), "adam",
                            {"learning_rate": args.lr})

    recent = []
    for ep in range(args.episodes):
        obs = env.reset()
        states, actions, rewards = [], [], []
        done = False
        while not done:
            logits = policy(nd.array(obs[None])).asnumpy()[0]
            p = np.exp(logits - logits.max())
            p /= p.sum()
            a = int(rng.choice(4, p=p))
            states.append(obs)
            actions.append(a)
            obs, r, done = env.step(a)
            rewards.append(r)
        # discounted returns, normalized (the standard REINFORCE
        # baseline-free recipe)
        G = np.zeros(len(rewards), np.float32)
        acc = 0.0
        for t in range(len(rewards) - 1, -1, -1):
            acc = rewards[t] + args.gamma * acc
            G[t] = acc
        if len(G) > 1:
            G = (G - G.mean()) / (G.std() + 1e-6)
        with autograd.record():
            logits = policy(nd.array(np.stack(states)))
            logp = nd.log_softmax(logits, axis=-1)
            chosen = nd.pick(logp, nd.array(
                np.asarray(actions, np.float32)), axis=-1)
            loss = -nd.mean(chosen * nd.array(G))
        loss.backward()
        trainer.step(batch_size=len(states))
        recent.append(sum(rewards))
        if (ep + 1) % 25 == 0:
            logging.info("episode %d: avg return %.3f", ep + 1,
                         float(np.mean(recent[-25:])))

    avg = float(np.mean(recent[-25:]))
    logging.info("final avg return over last 25 episodes: %.3f", avg)


if __name__ == "__main__":
    main()
