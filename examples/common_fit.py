"""The canonical Module training recipe — a faithful rebuild of
``example/image-classification/common/fit.py``†: argparse flags for
network/optimizer/kvstore/lr-schedule/checkpointing, then
``mod.fit`` with Speedometer + checkpoint callbacks.

Import ``add_fit_args``/``fit`` from training scripts
(train_cifar10.py does), exactly how the reference's image-
classification examples share one loop.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx


def add_fit_args(parser: argparse.ArgumentParser):
    """Reference ``common.fit.add_fit_args``† flag surface (the subset
    meaningful on TPU — dtype/kvstore/monitor kept, GPU toggles
    dropped)."""
    train = parser.add_argument_group("fit", "training recipe")
    train.add_argument("--network", type=str, default="resnet18_v1")
    train.add_argument("--num-classes", type=int, default=10)
    train.add_argument("--num-epochs", type=int, default=3)
    train.add_argument("--lr", type=float, default=0.05)
    train.add_argument("--lr-factor", type=float, default=0.1)
    train.add_argument("--lr-step-epochs", type=str, default="",
                       help="comma-separated epochs to decay lr at")
    train.add_argument("--optimizer", type=str, default="sgd")
    train.add_argument("--mom", type=float, default=0.9)
    train.add_argument("--wd", type=float, default=1e-4)
    train.add_argument("--batch-size", type=int, default=128)
    train.add_argument("--kv-store", type=str, default="local")
    train.add_argument("--disp-batches", type=int, default=20)
    train.add_argument("--model-prefix", type=str, default=None,
                       help="checkpoint prefix (enables per-epoch "
                            "checkpoints)")
    train.add_argument("--load-epoch", type=int, default=None,
                       help="resume from this checkpoint epoch")
    train.add_argument("--dtype", type=str, default="float32",
                       choices=("float32", "bfloat16"))
    train.add_argument("--top-k", type=int, default=0)
    train.add_argument("--num-examples", type=int, default=None,
                       help="dataset size; sets the updates-per-epoch "
                            "the lr schedule counts (reference "
                            "common/fit.py flag).  Defaults to "
                            "len(train_iter) when the iterator "
                            "knows it")
    return train


def _lr_scheduler(args, epoch_size):
    if not args.lr_step_epochs:
        return None
    steps = [int(e) for e in args.lr_step_epochs.split(",") if e]
    begin = args.load_epoch or 0
    steps = [epoch_size * (s - begin) for s in steps
             if s - begin > 0]
    if not steps:
        return None
    from mxtpu.optimizer.lr_scheduler import MultiFactorScheduler
    return MultiFactorScheduler(step=steps, factor=args.lr_factor)


def fit(args, network, train_iter, val_iter=None, **kwargs):
    """The reference ``common.fit.fit``† loop: bind/init via Module,
    kvstore-driven updates, lr schedule, Speedometer, checkpoints."""
    logging.basicConfig(level=logging.INFO)
    kv = mx.kvstore.create(args.kv_store)

    if getattr(args, "num_examples", None):
        epoch_size = max(args.num_examples // args.batch_size, 1)
    elif hasattr(train_iter, "__len__"):
        epoch_size = max(len(train_iter), 1)
    else:
        epoch_size = None  # schedule in epochs impossible — see below
    if epoch_size is None and args.lr_step_epochs:
        raise SystemExit(
            "--lr-step-epochs needs the epoch size: pass "
            "--num-examples or use an iterator with __len__")
    epoch_size = epoch_size or 1
    arg_params = aux_params = None
    if args.model_prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        logging.info("resumed from %s-%04d", args.model_prefix,
                     args.load_epoch)

    mod = mx.mod.Module(network, data_names=["data"],
                        label_names=["softmax_label"])
    optimizer_params = {
        "learning_rate": args.lr,
        "wd": args.wd,
    }
    if args.optimizer in ("sgd", "nag", "signum"):
        optimizer_params["momentum"] = args.mom
    optimizer_params["rescale_grad"] = 1.0 / args.batch_size
    sched = _lr_scheduler(args, epoch_size)
    if sched is not None:
        optimizer_params["lr_scheduler"] = sched

    eval_metrics = [mx.metric.Accuracy()]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.TopKAccuracy(top_k=args.top_k))

    callbacks = [mx.callback.Speedometer(args.batch_size,
                                         args.disp_batches)]
    epoch_cbs = []
    if args.model_prefix:
        epoch_cbs.append(mx.callback.do_checkpoint(args.model_prefix))

    mod.fit(train_iter,
            eval_data=val_iter,
            eval_metric=mx.metric.CompositeEvalMetric(eval_metrics)
            if len(eval_metrics) > 1 else eval_metrics[0],
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=mx.init.Xavier(),
            arg_params=arg_params,
            aux_params=aux_params,
            begin_epoch=args.load_epoch or 0,
            num_epoch=args.num_epochs,
            kvstore=kv,
            batch_end_callback=callbacks,
            epoch_end_callback=epoch_cbs,
            **kwargs)
    return mod
