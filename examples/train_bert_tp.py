"""BERT training with tensor (model) parallelism — the user-facing
recipe for ``param_spec_fn`` sharding (VERDICT r3 item 9; the
reference's behavior spec for manual placement is
``example/model-parallel-lstm/``†).

The mesh is dp x mp: the batch shards over ``dp``, and every
transformer block's weights shard megatron-style over ``mp`` —
qkv/ffn1 row-parallel (output dim), proj/ffn2 column-parallel (input
dim), embedding + MLM head vocab-parallel.  XLA/GSPMD inserts the
matching collectives; on real hardware they ride ICI.

Virtual 8-device mesh (no TPU pod needed):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
  python examples/train_bert_tp.py --model tiny --dp 2 --mp 4

Multi-process (one process per host, same flags on each; see
tools/launch.py for the ssh/local launcher):
  python tools/launch.py -n 2 -H hosts.txt \\
    "python examples/train_bert_tp.py --model base --dp 2 --mp 4"

``--parity`` re-runs the same batch + init on ONE device and asserts
the sharded losses match — the wrong-collective tripwire.
"""
import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu import nd, parallel
from mxtpu.gluon import loss as gloss
from mxtpu.models.transformer import BERTModel
from mxtpu.parallel import P

CONFIGS = {
    "tiny": dict(units=128, hidden_size=512, num_layers=2, num_heads=2),
    "base": dict(units=768, hidden_size=3072, num_layers=12,
                 num_heads=12),
    "large": dict(units=1024, hidden_size=4096, num_layers=24,
                  num_heads=16),
}


def megatron_spec(mp: int):
    """Shape-pattern megatron sharding for BERTModel parameters.

    Dense weights are (out, in): qkv/ffn1/mlm-head have out > in and
    shard ROW-parallel (each mp rank owns a slice of the fused heads /
    hidden units / vocab logits); proj/ffn2 have in >= out and shard
    COLUMN-parallel (each rank consumes its slice of the sharded
    activation, XLA all-reduces the partial sums).  Embedding tables
    (vocab, units) go vocab-parallel.  LayerNorm/bias stay replicated.
    """

    def spec(p):
        if p.shape is None or len(p.shape) != 2:
            return None
        out_d, in_d = p.shape
        if out_d % mp == 0 and out_d > in_d:
            return P("mp", None)       # row-parallel (qkv, ffn1, head)
        if in_d % mp == 0:
            return P(None, "mp")       # column-parallel (proj, ffn2)
        return None

    return spec


def build(args, mesh, init_vals=None):
    net = BERTModel(args.vocab, max_length=args.seq_len, dropout=0.0,
                    **CONFIGS[args.model])
    net.initialize(init="xavier")
    net(nd.array(np.zeros((2, args.seq_len), np.float32)))
    if init_vals is not None:
        parallel.restore_params(net, init_vals)

    def mlm_loss(pred, y):
        return gloss.SoftmaxCrossEntropyLoss()(
            pred.reshape((-1, args.vocab)), y.reshape((-1,)))

    step = parallel.build_train_step(
        net, mlm_loss, "adam", {"learning_rate": args.lr}, mesh=mesh,
        dp_axis="dp",
        param_spec_fn=megatron_spec(args.mp) if mesh is not None
        and args.mp > 1 else None,
        compute_dtype=args.dtype or None, cast_batch=False)
    return net, step


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=CONFIGS, default="tiny")
    p.add_argument("--vocab", type=int, default=8000)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--mp", type=int, default=4)
    p.add_argument("--dtype", default="")
    p.add_argument("--parity", action="store_true",
                   help="assert sharded losses == 1-device losses")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    import jax
    n = args.dp * args.mp
    devices = jax.devices()
    if len(devices) < n:
        sys.exit(f"need {n} devices (dp*mp), have {len(devices)}; "
                 f"set XLA_FLAGS=--xla_force_host_platform_device_"
                 f"count={n} JAX_PLATFORMS=cpu for a virtual mesh")
    mesh = parallel.make_mesh({"dp": args.dp, "mp": args.mp},
                              devices=devices[:n])

    mx.random.seed(0)
    net, step = build(args, mesh)
    init_vals = parallel.snapshot_params(net)

    rng = np.random.RandomState(0)
    toks = nd.array(rng.randint(0, args.vocab,
                                (args.batch_size, args.seq_len))
                    .astype(np.float32))
    losses = []
    t0 = time.perf_counter()
    for i in range(args.steps):
        losses.append(float(step(toks, toks).asscalar()))
        if (i + 1) % 5 == 0:
            logging.info("step %d loss %.4f", i + 1, losses[-1])
    dt = time.perf_counter() - t0
    tokens = args.batch_size * args.seq_len * args.steps
    logging.info("dp%dxmp%d: %.1f tokens/sec", args.dp, args.mp,
                 tokens / dt)

    # prove the weights really shard: a qkv weight must live on every
    # mesh device, in mp pieces
    qkv = [p for p in net.collect_params().values()
           if p.shape is not None and len(p.shape) == 2
           and p.shape[0] > p.shape[1]]
    spec = qkv[0].data().data.sharding.spec
    # a replicated sharding also spans every device; the SPEC naming
    # the mp axis is what proves tensor parallelism engaged
    assert "mp" in jax.tree_util.tree_leaves(tuple(spec)), spec
    logging.info("TP sharding verified: %s spec=%s over %d devices",
                 qkv[0].name, tuple(spec), n)

    if args.parity:
        _, ref_step = build(args, mesh=None, init_vals=init_vals)
        ref = [float(ref_step(toks, toks).asscalar())
               for _ in range(min(args.steps, 3))]
        dev = max(abs(a - b) for a, b in zip(losses, ref))
        assert np.allclose(losses[:len(ref)], ref, rtol=2e-4,
                           atol=2e-4), (losses[:len(ref)], ref)
        logging.info("parity vs 1-device OK (max delta %.2e)", dev)


if __name__ == "__main__":
    main()
