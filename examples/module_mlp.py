"""Legacy symbolic workflow: symbol + Module.fit
(reference ``example/image-classification/common/fit.py``† shape).

  python examples/module_mlp.py
"""
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu.io import NDArrayIter


def build_symbol(hidden=64, classes=10):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(0)
    X = rng.randn(2000, 20).astype(np.float32)
    y = X[:, :10].argmax(1).astype(np.float32)
    train = NDArrayIter(X[:1600], y[:1600], batch_size=64, shuffle=True,
                        label_name="softmax_label")
    val = NDArrayIter(X[1600:], y[1600:], batch_size=64,
                      label_name="softmax_label")

    mod = mx.mod.Module(build_symbol())
    mod.fit(train, eval_data=val, num_epoch=8, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer="xavier",
            batch_end_callback=mx.callback.Speedometer(64, 10),
            epoch_end_callback=mx.callback.do_checkpoint("mlp",
                                                         period=4))
    print(mod.score(val, "acc"))


if __name__ == "__main__":
    main()
