"""Mixture-of-Experts training with expert parallelism — the ``ep``
counterpart of examples/train_bert_tp.py (new capability; the
reference era predates MoE).

A small MoE MLP classifier trains on synthetic data over a dp x ep
mesh: the batch shards over ``dp``, the expert-axis parameters of
every MoEDense layer shard over ``ep`` (param_spec_fn), and GSPMD
lowers the dispatch/return einsums to all-to-alls.

Virtual 8-device mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
  python examples/train_moe.py --dp 2 --ep 4

``--parity`` re-runs the same batch + init unsharded and asserts the
losses match.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu import nd, parallel
from mxtpu.gluon import loss as gloss, nn
from mxtpu.gluon.block import HybridBlock
from mxtpu.gluon.contrib.nn import MoEDense
from mxtpu.parallel import P


class MoEClassifier(HybridBlock):
    """Dense -> MoEDense -> Dense head; the MoE aux loss rides along
    as a second output for the training loss to consume."""

    def __init__(self, classes, units=32, hidden=64, experts=4,
                 **kwargs):
        super().__init__(**kwargs)
        self.proj = nn.Dense(units, activation="relu", flatten=False)
        self.moe = MoEDense(units=units, hidden=hidden,
                            num_experts=experts, in_units=units)
        self.head = nn.Dense(classes, flatten=False)

    def hybrid_forward(self, F, x):
        h = self.proj(x)
        y, aux = self.moe(h)
        return self.head(h + y), aux  # residual around the MoE block


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--ep", type=int, default=4)
    p.add_argument("--classes", type=int, default=4)
    p.add_argument("--experts", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--aux-weight", type=float, default=0.01)
    p.add_argument("--parity", action="store_true")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    import jax
    n = args.dp * args.ep
    devices = jax.devices()
    if len(devices) < n:
        sys.exit(f"need {n} devices (dp*ep), have {len(devices)}")
    mesh = parallel.make_mesh({"dp": args.dp, "ep": args.ep},
                              devices=devices[:n])

    def expert_spec(param):
        # expert-axis parameters (E, ...) shard over ep; everything
        # else (and a non-divisible expert count) replicates, like
        # megatron_spec in train_bert_tp.py
        if param.shape is not None and len(param.shape) >= 2 \
                and "expert" in param.name \
                and param.shape[0] % args.ep == 0:
            return P("ep")
        return None

    def moe_loss(outs, y):
        pred, aux = outs
        return gloss.SoftmaxCrossEntropyLoss()(pred, y).mean() \
            + args.aux_weight * aux

    def build(init_vals=None, use_mesh=True):
        mx.random.seed(0)
        net = MoEClassifier(args.classes, experts=args.experts)
        net.initialize(init="xavier")
        net(nd.array(np.zeros((2, 16), np.float32)))
        if init_vals is not None:
            parallel.restore_params(net, init_vals)
        step = parallel.build_train_step(
            net, moe_loss, "adam", {"learning_rate": args.lr},
            mesh=mesh if use_mesh else None, dp_axis="dp",
            param_spec_fn=expert_spec if use_mesh else None)
        return net, step

    net, step = build()
    init_vals = parallel.snapshot_params(net)

    rng = np.random.RandomState(0)
    X = rng.randn(args.batch_size, 16).astype(np.float32)
    y = rng.randint(0, args.classes, (args.batch_size,))
    # separable synthetic task: class mean offset in a random direction
    dirs = rng.randn(args.classes, 16).astype(np.float32)
    X += 1.5 * dirs[y]
    Xn, yn = nd.array(X), nd.array(y.astype(np.float32))

    losses = [float(step(Xn, yn).asscalar()) for _ in range(args.steps)]
    logging.info("dp%dxep%d: loss %.4f -> %.4f", args.dp, args.ep,
                 losses[0], losses[-1])
    assert losses[-1] < losses[0], "did not learn"

    # prove the expert weights really shard over ep
    w1 = [q for name, q in net.collect_params().items()
          if "expert_w1" in name][0]
    spec = w1.data().data.sharding.spec
    assert "ep" in jax.tree_util.tree_leaves(tuple(spec)), spec
    logging.info("EP sharding verified: expert_w1 spec=%s",
                 tuple(spec))

    if args.parity:
        _, ref_step = build(init_vals=init_vals, use_mesh=False)
        ref = [float(ref_step(Xn, yn).asscalar())
               for _ in range(min(args.steps, 3))]
        dev = max(abs(a - b) for a, b in zip(losses, ref))
        assert np.allclose(losses[:len(ref)], ref, rtol=2e-4,
                           atol=2e-4), (losses[:len(ref)], ref)
        logging.info("parity vs unsharded OK (max delta %.2e)", dev)


if __name__ == "__main__":
    main()
