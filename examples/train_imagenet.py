"""ImageNet-class training through the canonical recipe (reference
``example/image-classification/train_imagenet.py``† over
``common/fit.py``†).

Data: ``--data-train`` names an ImageRecordIter .rec file (the
reference's path); without it the script synthesizes
ImageNet-shaped batches so the full recipe — gluon model_zoo network,
fit loop, LR schedule, checkpointing, Speedometer — still runs
end-to-end (this environment has no dataset download).

  # synthetic smoke run, ResNet-18 at 64x64:
  python examples/train_imagenet.py --network resnet18_v1 \\
      --image-shape 3,64,64 --num-classes 10 --num-examples 256 \\
      --num-epochs 1
  # real records:
  python examples/train_imagenet.py --data-train train.rec \\
      --network resnet50_v1 --batch-size 256 --dtype bfloat16
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import mxtpu as mx
from common_fit import add_fit_args, fit
from mxtpu.io import NDArrayIter


def get_symbol(network, num_classes):
    """Gluon model_zoo network traced to a training symbol (the
    reference used symbols/*.py factories; the zoo is this
    framework's canonical model source)."""
    from mxtpu.gluon.model_zoo import vision
    net = vision.get_model(network, classes=num_classes)
    net.initialize(init="xavier")
    data = mx.sym.Variable("data")
    out = net(data)
    return mx.sym.SoftmaxOutput(out, mx.sym.Variable("softmax_label"),
                                name="softmax")


def synthetic_iter(batch_size, image_shape, num_classes, num_examples,
                   seed=0):
    rng = np.random.RandomState(seed)
    shape = (num_examples,) + tuple(image_shape)
    x = rng.randn(*shape).astype(np.float32)
    y = rng.randint(0, num_classes, (num_examples,)).astype(np.float32)
    # make the labels learnable: bias a class-specific spatial
    # quadrant (channel-count independent, no class collisions)
    H, W = image_shape[1], image_shape[2]
    for i in range(num_examples):
        c = int(y[i])
        r0 = (c // 2 % 2) * (H // 2)
        c0 = (c % 2) * (W // 2)
        x[i, :, r0:r0 + H // 2, c0:c0 + W // 2] += 1.5
    return NDArrayIter(x, y, batch_size=batch_size, shuffle=True,
                       label_name="softmax_label")


def main():
    parser = argparse.ArgumentParser(
        description="train an imagenet-class model",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    add_fit_args(parser)  # incl. --network/--num-classes/--dtype/...
    parser.set_defaults(network="resnet50_v1", num_classes=1000)
    parser.add_argument("--image-shape", default="3,224,224")
    parser.add_argument("--data-train", default=None,
                        help=".rec file for ImageRecordIter")
    parser.add_argument("--data-val", default=None)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    if args.data_train:
        from mxtpu.io import ImageRecordIter
        train = ImageRecordIter(
            path_imgrec=args.data_train, data_shape=image_shape,
            batch_size=args.batch_size, shuffle=True,
            label_name="softmax_label")
        val = ImageRecordIter(
            path_imgrec=args.data_val, data_shape=image_shape,
            batch_size=args.batch_size,
            label_name="softmax_label") if args.data_val else None
    else:
        n = min(args.num_examples or 1024, 4096)
        train = synthetic_iter(args.batch_size, image_shape,
                               args.num_classes, n)
        val = synthetic_iter(args.batch_size, image_shape,
                             args.num_classes, max(n // 4, 32),
                             seed=1)

    sym = get_symbol(args.network, args.num_classes)
    fit(args, sym, train, val)


if __name__ == "__main__":
    main()
