"""Faster R-CNN training recipe on synthetic scenes (reference
``example/rcnn/train_end2end.py``† shape, toy scale: no dataset
downloads in this environment).

RPN objectness/regression train against MultiBoxTarget assignment on
the generated anchors; detection quality is reported as VOC07 mAP via
``FasterRCNN.detect``.

  python examples/train_rcnn.py --epochs 8
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu import autograd, gluon, nd
from mxtpu.metric import VOC07MApMetric
from mxtpu.models.rcnn import faster_rcnn_small, rpn_anchors


def synthetic_scene(rng, batch, size, classes):
    x = rng.rand(batch, 3, size, size).astype(np.float32) * 0.1
    labels = -np.ones((batch, 1, 5), np.float32)
    for i in range(batch):
        cls = int(rng.randint(classes))
        w = int(rng.randint(size // 3, size // 2))
        x0 = int(rng.randint(0, size - w))
        y0 = int(rng.randint(0, size - w))
        x[i, cls, y0:y0 + w, x0:x0 + w] = 1.0
        labels[i, 0] = [cls, x0 / size, y0 / size, (x0 + w) / size,
                        (y0 + w) / size]
    return x, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--num-classes", type=int, default=2)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    rng = np.random.RandomState(0)

    net = faster_rcnn_small(num_classes=args.num_classes)
    net.initialize(init="xavier")
    size = args.image_size
    info = nd.array(np.array([[size, size, 1.0]] * args.batch_size,
                             np.float32))
    x0, _ = synthetic_scene(rng, args.batch_size, size,
                            args.num_classes)
    net(nd.array(x0), info)  # deferred init
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    fh = fw = size // net._stride
    anchors = rpn_anchors(fh, fw, net._stride, net._scales,
                          net._ratios, size)
    A = net._A

    for epoch in range(args.epochs):
        total, n = 0.0, 0
        for _ in range(args.steps):
            xb, lb = synthetic_scene(rng, args.batch_size, size,
                                     args.num_classes)
            x = nd.array(xb)
            labels = nd.array(lb)
            with autograd.record():
                rois, cls_scores, _, rpn_raw, rpn_reg = net(x, info)
                bg = nd.transpose(
                    nd.slice_axis(rpn_raw, axis=1, begin=0, end=A),
                    axes=(0, 2, 3, 1)).reshape((args.batch_size, -1))
                fg = nd.transpose(
                    nd.slice_axis(rpn_raw, axis=1, begin=A, end=2 * A),
                    axes=(0, 2, 3, 1)).reshape((args.batch_size, -1))
                logits = nd.stack(bg, fg, axis=1)
                bt, bm, ct = nd.MultiBoxTarget(
                    anchors, labels, logits, overlap_threshold=0.3,
                    negative_mining_ratio=3.0)
                logp = nd.log_softmax(logits, axis=1)
                # ct == -1 marks non-mined anchors (MultiBoxTarget
                # ignore label): mask them out or the mining ratio is
                # a no-op and easy negatives swamp the loss
                keep = ct >= 0
                per_anchor = -nd.pick(logp, nd.maximum(
                    ct, nd.zeros_like(ct)), axis=1) * keep
                rpn_ce = nd.sum(per_anchor) / nd.maximum(
                    nd.sum(keep), nd.ones_like(nd.sum(keep)))
                # box regression on positives (smooth-L1 over masked
                # deltas), the reference's rpn_bbox_loss
                reg = nd.transpose(rpn_reg, axes=(0, 2, 3, 1)) \
                    .reshape((args.batch_size, -1))
                reg_loss = nd.mean(nd.smooth_l1(
                    (reg - bt) * bm, scalar=3.0))
                # head classification: each ROI labelled by IoU with
                # its image's gt (bg = class 0), the reference's
                # rcnn_cls loss with the proposal-target assignment
                # computed inline
                roi_np = rois  # (R, 5): [batch, x1, y1, x2, y2]
                gt_boxes = labels[:, 0, 1:5] * size   # (B, 4)
                gt_cls = labels[:, 0, 0]              # (B,)
                bidx = nd.slice_axis(roi_np, axis=1, begin=0,
                                     end=1).reshape((-1,))
                boxes = nd.slice_axis(roi_np, axis=1, begin=1, end=5)
                gt_for_roi = nd.take(gt_boxes, bidx)  # (R, 4)
                from mxtpu.ndarray.contrib import box_iou
                iou = box_iou(boxes.reshape((-1, 1, 4)),
                              gt_for_roi.reshape((-1, 1, 4))) \
                    .reshape((-1,))
                # fg threshold 0.35 (toy-scale proposals) + 4x fg
                # weighting against the ~95% background ROIs — the
                # reference balances by sampling 25% fg instead
                fg = iou > 0.35
                roi_cls = nd.where(
                    fg, nd.take(gt_cls, bidx) + 1.0,
                    nd.zeros_like(iou))
                w = nd.where(fg, 4.0 * nd.ones_like(iou),
                             nd.ones_like(iou))
                head_logp = nd.log_softmax(cls_scores, axis=-1)
                head_ce = -nd.sum(w * nd.pick(head_logp, roi_cls,
                                              axis=-1)) / nd.sum(w)
                loss = rpn_ce + reg_loss + head_ce
            loss.backward()
            trainer.step(batch_size=args.batch_size)
            total += float(loss.asscalar())
            n += 1
        logging.info("epoch %d: rpn loss %.4f", epoch, total / n)

    # evaluate: RPN proposal recall (the standard first-stage
    # diagnostic) + end-to-end detect() mAP.  detect() returns PIXEL
    # boxes, so ground truth scales up to pixels too.  At this toy
    # scale the RPN localizes well while the two-stage head stays
    # noisy — mirror of the reference recipe's behavior before its
    # long VOC schedules.
    from mxtpu.ndarray.contrib import box_iou
    metric = VOC07MApMetric(iou_thresh=0.3)
    hits, gts = 0, 0
    for _ in range(4):
        xb, lb = synthetic_scene(rng, args.batch_size, size,
                                 args.num_classes)
        rois, *_ = net(nd.array(xb), info)
        r = rois.asnumpy()
        for i in range(args.batch_size):
            props = r[r[:, 0] == i][:, 1:]
            gt = lb[i, 0, 1:5] * size
            iou = box_iou(nd.array(props),
                          nd.array(gt[None].astype(np.float32))) \
                .asnumpy()
            hits += int(iou.max() >= 0.5)
            gts += 1
        det = net.detect(nd.array(xb), info)
        lb_px = lb.copy()
        lb_px[:, :, 1:5] *= size
        metric.update([nd.array(lb_px)], [det])
    name, value = metric.get()
    logging.info("proposal recall@0.5: %.3f   %s: %.4f",
                 hits / gts, name, value)
    net.save_parameters("rcnn_toy.params")
    logging.info("saved rcnn_toy.params")


if __name__ == "__main__":
    main()
