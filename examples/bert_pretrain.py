"""BERT MLM pretraining step — north-star workload 3
(BASELINE.md; the reference era ran this via GluonNLP scripts).

Single chip:
  python examples/bert_pretrain.py --model base --batch-size 32
Multi-chip data parallel (virtual CPU mesh for testing):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/bert_pretrain.py --model tiny --dp 8
"""
import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu import nd, parallel
from mxtpu.gluon import loss as gloss
from mxtpu.models.transformer import BERTModel

CONFIGS = {
    "tiny": dict(units=128, hidden_size=512, num_layers=2, num_heads=2),
    "base": dict(units=768, hidden_size=3072, num_layers=12,
                 num_heads=12),
    "large": dict(units=1024, hidden_size=4096, num_layers=24,
                  num_heads=16),
}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=CONFIGS, default="base")
    p.add_argument("--vocab", type=int, default=30522)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel mesh size (0 = single device)")
    p.add_argument("--remat", action="store_true")
    p.add_argument("--dtype", default="bfloat16")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    net = BERTModel(args.vocab, max_length=args.seq_len, dropout=0.1,
                    remat=args.remat, **CONFIGS[args.model])
    net.initialize(init="xavier")

    def mlm_loss(pred, y):
        return gloss.SoftmaxCrossEntropyLoss()(
            pred.reshape((-1, args.vocab)), y.reshape((-1,)))

    mesh = parallel.make_mesh({"dp": args.dp}) if args.dp else None
    step = parallel.build_train_step(
        net, mlm_loss, "adam", {"learning_rate": args.lr}, mesh=mesh,
        compute_dtype=args.dtype or None, cast_batch=False)

    rng = np.random.RandomState(0)
    toks = nd.array(rng.randint(0, args.vocab,
                                (args.batch_size, args.seq_len))
                    .astype(np.float32))
    loss = step(toks, toks)  # compile
    t0 = time.perf_counter()
    for i in range(args.steps):
        loss = step(toks, toks)
        if (i + 1) % 10 == 0:
            logging.info("step %d loss %.4f", i + 1,
                         float(loss.asscalar()))
    dt = time.perf_counter() - t0
    tokens = args.batch_size * args.seq_len * args.steps
    logging.info("%.1f tokens/sec", tokens / dt)


if __name__ == "__main__":
    main()
