"""SSD detection training — the reference's ``example/ssd/train.py``†
recipe: det-packed RecordIO in, ``ImageDetIter`` with box-aware
augmentation, MultiBox target assignment, VOC07 mAP evaluation out.

With no dataset in this environment the script writes a synthetic
det .rec first (colored rectangles on noise); point ``--rec`` at an
``im2rec``-packed file for real data.

  python examples/train_ssd.py --epochs 3 --batch-size 8
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu import autograd, gluon, nd
from mxtpu.image import ImageDetIter, pack_det_label
from mxtpu.metric import VOC07MApMetric
from mxtpu.models.ssd import SSDLoss, toy_ssd


def write_synthetic_det_rec(prefix, n=64, size=64, classes=2, seed=0):
    """Pack a synthetic detection dataset: class 0 = bright square,
    class 1 = bright wide rectangle."""
    from mxtpu import recordio as rio
    rng = np.random.RandomState(seed)
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        img = (rng.rand(size, size, 3) * 40).astype(np.uint8)
        cls = int(rng.randint(classes))
        w = int(rng.randint(size // 4, size // 2))
        h = w if cls == 0 else max(w // 2, 8)
        x0 = int(rng.randint(0, size - w))
        y0 = int(rng.randint(0, size - h))
        img[y0:y0 + h, x0:x0 + w] = (220, 40 + 160 * cls, 60)
        label = pack_det_label([[cls, x0 / size, y0 / size,
                                 (x0 + w) / size, (y0 + h) / size]])
        header = rio.IRHeader(0, label, i, 0)
        rec.write_idx(i, rio.pack_img(header, img, quality=95))
    rec.close()
    return prefix + ".rec", prefix + ".idx"


def evaluate(net, it, metric):
    metric.reset()
    it.reset()
    for batch in it:
        out = net.detect(batch.data[0])
        metric.update([batch.label[0]], [out])
    return metric.get()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rec", default=None,
                    help=".rec with det-packed labels (default: "
                         "synthesize one)")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--num-classes", type=int, default=2)
    ap.add_argument("--out", default="ssd_toy.params")
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    if args.rec is None:
        rec, idx = write_synthetic_det_rec(
            "/tmp/ssd_synth", n=64, size=args.image_size,
            classes=args.num_classes)
    else:
        rec = args.rec
        idx = os.path.splitext(rec)[0] + ".idx"

    train_it = ImageDetIter(
        rec, (3, args.image_size, args.image_size),
        batch_size=args.batch_size, path_imgidx=idx, shuffle=True,
        rand_mirror=True, scale=1.0 / 255)
    val_it = ImageDetIter(
        rec, (3, args.image_size, args.image_size),
        batch_size=args.batch_size, path_imgidx=idx,
        scale=1.0 / 255)

    net = toy_ssd(num_classes=args.num_classes)
    net.initialize(init="xavier")
    loss_fn = SSDLoss()
    trainer = None
    metric = VOC07MApMetric(iou_thresh=0.5)
    for epoch in range(args.epochs):
        train_it.reset()
        total, n = 0.0, 0
        for batch in train_it:
            x = batch.data[0]
            labels = batch.label[0]
            if trainer is None:
                net(x)  # deferred init
                trainer = gluon.Trainer(net.collect_params(), "adam",
                                        {"learning_rate": args.lr})
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                bt, bm, ct = nd.MultiBoxTarget(anchors, labels,
                                               cls_preds)
                l = nd.mean(loss_fn(cls_preds, box_preds, ct, bt, bm))
            l.backward()
            trainer.step(batch_size=x.shape[0])
            total += float(l.asscalar())
            n += 1
        name, value = evaluate(net, val_it, metric)
        logging.info("epoch %d: loss %.4f  %s %.4f", epoch, total / n,
                     name, value)
    net.save_parameters(args.out)
    logging.info("saved %s (reference dmlc binary)", args.out)


if __name__ == "__main__":
    main()
