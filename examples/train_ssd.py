"""SSD detection training — the reference's ``example/ssd/train.py``†
recipe on synthetic box data (no dataset download in this
environment; point --rec at an im2rec RecordIO file for real data).

  python examples/train_ssd.py --epochs 2 --batch-size 8
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu import autograd, gluon, nd
from mxtpu.models.ssd import SSDLoss, toy_ssd


def synthetic_batches(batch_size, size, steps, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        x = rng.rand(batch_size, 3, size, size).astype(np.float32) * .1
        labels = np.zeros((batch_size, 1, 5), np.float32)
        for i in range(batch_size):
            w = rng.randint(size // 4, size // 2)
            x0 = rng.randint(0, size - w)
            y0 = rng.randint(0, size - w)
            x[i, :, y0:y0 + w, x0:x0 + w] = 1.0
            labels[i, 0] = [0, x0 / size, y0 / size,
                            (x0 + w) / size, (y0 + w) / size]
        yield nd.array(x), nd.array(labels)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=5e-3)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    net = toy_ssd(num_classes=1)
    net.initialize(init="xavier")
    loss_fn = SSDLoss()
    trainer = None
    for epoch in range(args.epochs):
        total, n = 0.0, 0
        for x, labels in synthetic_batches(
                args.batch_size, args.image_size, args.steps,
                seed=epoch):
            if trainer is None:
                net(x)  # deferred init
                trainer = gluon.Trainer(net.collect_params(), "adam",
                                        {"learning_rate": args.lr})
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                bt, bm, ct = nd.MultiBoxTarget(anchors, labels,
                                               cls_preds)
                l = nd.mean(loss_fn(cls_preds, box_preds, ct, bt, bm))
            l.backward()
            trainer.step(batch_size=x.shape[0])
            total += float(l.asscalar())
            n += 1
        logging.info("epoch %d: loss %.4f", epoch, total / n)
    net.save_parameters("ssd_toy.params")
    logging.info("saved ssd_toy.params (reference dmlc binary)")


if __name__ == "__main__":
    main()
