"""LeNet on MNIST — north-star workload 1
(reference ``example/image-classification/train_mnist.py``†).

Uses the MNIST idx files under --data-dir if present, else synthetic
MNIST-shaped data (no network access in this environment).

  python examples/train_mnist.py --epochs 3 --batch-size 256
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxtpu as mx
from mxtpu import autograd, gluon, nd
from mxtpu.gluon import loss as gloss
from mxtpu.models import lenet


def load_data(data_dir, batch_size):
    from mxtpu.io import MNISTIter, NDArrayIter
    img = os.path.join(data_dir, "train-images-idx3-ubyte")
    lab = os.path.join(data_dir, "train-labels-idx1-ubyte")
    if os.path.exists(img):
        return MNISTIter(image=img, label=lab, batch_size=batch_size)
    logging.warning("MNIST files not found under %s — synthetic data",
                    data_dir)
    rng = np.random.RandomState(0)
    X = rng.rand(4096, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, 4096).astype(np.float32)
    return NDArrayIter(X, y, batch_size=batch_size, shuffle=True,
                       last_batch_handle="discard")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default=os.path.expanduser(
        "~/.mxnet/datasets/mnist"))
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--compiled", action="store_true",
                   help="use the fused SPMD train step")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    net = lenet()
    net.initialize(init="xavier")
    train = load_data(args.data_dir, args.batch_size)
    metric = mx.metric.Accuracy()
    speed = mx.callback.Speedometer(args.batch_size, 20)
    from mxtpu.module.base_module import BatchEndParam

    if args.compiled:
        from mxtpu import parallel
        step = parallel.build_train_step(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": args.lr, "momentum": 0.9})
        for epoch in range(args.epochs):
            train.reset()
            for i, batch in enumerate(train):
                loss = step(batch.data[0], batch.label[0])
                speed(BatchEndParam(epoch, i, None, None))
            logging.info("epoch %d loss %.4f", epoch,
                         float(loss.asscalar()))
        return

    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    L = gloss.SoftmaxCrossEntropyLoss()
    for epoch in range(args.epochs):
        train.reset()
        metric.reset()
        for i, batch in enumerate(train):
            x, y = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(x)
                loss = L(out, y)
            loss.backward()
            trainer.step(args.batch_size)
            metric.update([y], [out])
            speed(BatchEndParam(epoch, i, metric, None))
        logging.info("epoch %d train-acc %.4f", epoch,
                     metric.get()[1])
    net.save_parameters("lenet.params")


if __name__ == "__main__":
    main()
