"""Inference benchmark over the model zoo (reference
``example/image-classification/benchmark_score.py``†): images/sec per
(network, batch size) on the current device.

  python examples/benchmark_score.py --networks resnet18_v1 resnet50_v1
"""
import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxtpu import nd
from mxtpu.gluon.model_zoo import vision


def score(network, batch_size, image_size=224, dtype="float32",
          warmup=3, iters=10):
    net = getattr(vision, network)()
    net.initialize(init="xavier")
    net.hybridize()
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(batch_size, 3, image_size, image_size)
                 .astype(np.float32))
    if dtype == "bfloat16":
        net.cast("bfloat16")
        x = x.astype("bfloat16")
    for _ in range(warmup):
        out = net(x)
    float(out.asnumpy().ravel()[0])  # sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out = net(x)
    float(out.asnumpy().ravel()[0])
    dt = time.perf_counter() - t0
    return batch_size * iters / dt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--networks", nargs="+",
                   default=["alexnet", "resnet18_v1", "resnet50_v1",
                            "vgg11", "mobilenet1_0", "squeezenet1_0"])
    p.add_argument("--batch-sizes", nargs="+", type=int,
                   default=[1, 32])
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--dtype", default="float32",
                   choices=("float32", "bfloat16"))
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    for network in args.networks:
        if not hasattr(vision, network):
            logging.warning("skipping unknown network %s", network)
            continue
        for bs in args.batch_sizes:
            try:
                ips = score(network, bs, args.image_size, args.dtype)
                logging.info("network: %s, batch: %d, dtype: %s, "
                             "images/sec: %.1f", network, bs,
                             args.dtype, ips)
            except Exception as e:  # keep scoring the rest
                logging.error("%s batch %d failed: %s", network, bs,
                              str(e)[:200])


if __name__ == "__main__":
    main()
