"""WMT-shaped seq2seq transformer training — north-star workload 4
(BASELINE.md; the reference era ran this via ``example/nmt``-style
scripts and GluonNLP's ``train_transformer.py``).

The corpus is synthetic but translation-shaped: the "target language"
is a deterministic token-level transform of the source (reverse the
sentence and shift every token id), so the model has real structure to
learn and the loss curve means something — no dataset download, runs
anywhere.

Training goes through ``parallel.build_train_step`` — the full
fwd+bwd+Adam step as ONE compiled program, the same path bench.py
measures.  TrainStep feeds a single batch array, so src and the
teacher-forced decoder input ride concatenated on the time axis and a
thin wrapper block splits them (the idiom bench.py's transformer row
uses).

Single chip:
  python examples/train_transformer.py --steps 200
Multi-chip data parallel (virtual CPU mesh for testing):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/train_transformer.py --model tiny --dp 8
"""
import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxtpu import nd, parallel
from mxtpu.gluon import loss as gloss
from mxtpu.gluon.block import HybridBlock
from mxtpu.models.transformer import TransformerModel

CONFIGS = {
    "tiny": dict(units=64, hidden_size=256, num_layers=2, num_heads=4),
    "base": dict(units=512, hidden_size=2048, num_layers=6,
                 num_heads=8),
    "big": dict(units=1024, hidden_size=4096, num_layers=6,
                num_heads=16),
}
BOS = 1  # id 0 is reserved for padding


class Seq2SeqWrap(HybridBlock):
    """TrainStep feeds ONE batch array: src|tgt_in concatenated on the
    time axis, split here before the encoder/decoder call."""

    def __init__(self, model, src_len, **kw):
        super().__init__(**kw)
        self.model = model
        self._split = src_len

    def hybrid_forward(self, F, x):
        src = F.slice_axis(x, axis=1, begin=0, end=self._split)
        tgt = F.slice_axis(x, axis=1, begin=self._split, end=None)
        return self.model(src, tgt)


def make_batch(rng, batch_size, src_len, vocab):
    """Synthetic parallel corpus: tgt = reverse(src) with ids shifted
    by +7 (mod vocab, avoiding the pad/BOS ids)."""
    src = rng.randint(2, vocab, (batch_size, src_len))
    tgt = (src[:, ::-1] - 2 + 7) % (vocab - 2) + 2
    tgt_in = np.concatenate(
        [np.full((batch_size, 1), BOS), tgt[:, :-1]], axis=1)
    x = np.concatenate([src, tgt_in], axis=1).astype(np.float32)
    return nd.array(x), nd.array(tgt.astype(np.float32))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=CONFIGS, default="base")
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--src-len", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel mesh size (0 = single device)")
    p.add_argument("--dtype", default="bfloat16")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    model = TransformerModel(args.vocab, max_length=2 * args.src_len,
                             dropout=0.1, **CONFIGS[args.model])
    net = Seq2SeqWrap(model, args.src_len)
    net.initialize(init="xavier")

    def mt_loss(pred, y):
        return gloss.SoftmaxCrossEntropyLoss()(
            pred.reshape((-1, args.vocab)), y.reshape((-1,)))

    mesh = parallel.make_mesh({"dp": args.dp}) if args.dp else None
    # cast_batch=False: token ids must not be rounded through bf16
    step = parallel.build_train_step(
        net, mt_loss, "adam", {"learning_rate": args.lr}, mesh=mesh,
        compute_dtype=args.dtype or None, cast_batch=False)

    rng = np.random.RandomState(0)
    x, y = make_batch(rng, args.batch_size, args.src_len, args.vocab)
    first = float(step(x, y).asscalar())  # compile
    logging.info("step 0 loss %.4f", first)
    t0 = time.perf_counter()
    for i in range(args.steps):
        x, y = make_batch(rng, args.batch_size, args.src_len,
                          args.vocab)
        loss = step(x, y)
        if (i + 1) % 20 == 0:
            logging.info("step %d loss %.4f", i + 1,
                         float(loss.asscalar()))
    dt = time.perf_counter() - t0
    tokens = args.batch_size * 2 * args.src_len * args.steps
    logging.info("%.1f tokens/sec (src+tgt)", tokens / dt)
    final = float(loss.asscalar())
    if final >= first:
        logging.warning("loss did not improve (%.4f -> %.4f)",
                        first, final)


if __name__ == "__main__":
    main()
