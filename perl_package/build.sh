#!/bin/sh
# Build AI::MXTPU's XS glue against the training-tier C ABI.
# Usage: sh perl_package/build.sh [python]
# (the python that owns libmxtpu_ndarray's embedded interpreter)
set -e
cd "$(dirname "$0")"
PY="${1:-python3}"

# the C ABI library must exist first
make -C ../core ndarray "PYTHON=$PY"

ARCHLIB=$(perl -MConfig -e 'print $Config{archlibexp}')
CCFLAGS=$(perl -MConfig -e 'print $Config{ccflags}')

xsubpp -typemap "$(perl -MConfig -e \
  'print $Config{privlibexp}')/ExtUtils/typemap" MXTPU.xs > MXTPU.c

# DynaLoader looks for auto/AI/MXTPU/MXTPU.so under @INC, so the
# shared object lands inside lib/; rpath the core dir so it finds
# libmxtpu_ndarray at runtime
mkdir -p lib/auto/AI/MXTPU
gcc -O2 -shared -fPIC $CCFLAGS \
  -I"$ARCHLIB/CORE" -I../core \
  MXTPU.c -L../core -lmxtpu_ndarray \
  -Wl,-rpath,"$(cd ../core && pwd)" \
  -o lib/auto/AI/MXTPU/MXTPU.so
echo "built perl_package/lib/auto/AI/MXTPU/MXTPU.so"
