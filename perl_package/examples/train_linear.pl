#!/usr/bin/perl
# Train a linear model from Perl through AI::MXTPU (the same
# least-squares task as core/train_example.c, proving the C ABI
# serves a dynamic third language).
use strict;
use warnings;
use FindBin;
use lib "$FindBin::Bin/../lib";
use AI::MXTPU;

my ($N, $D) = (64, 4);
my @wstar = (1.0, 2.0, -1.0, 0.5);

# fixed LCG data, same as the C example
my ($s, @x, @y) = (12345);
for my $i (0 .. $N * $D - 1) {
    $s = ($s * 1103515245 + 12345) % (2**32);
    push @x, (($s >> 16) & 0x7fff) / 16384.0 - 1.0;
}
for my $i (0 .. $N - 1) {
    my $v = 0;
    $v += $x[$i * $D + $_] * $wstar[$_] for 0 .. $D - 1;
    push @y, $v;
}

my $X = AI::MXTPU::NDArray->from_list([$N, $D], \@x);
my $Y = AI::MXTPU::NDArray->from_list([$N, 1], \@y);
my $w = AI::MXTPU::NDArray->zeros([$D, 1]);
my ($Xt) = AI::MXTPU::invoke("transpose", [$X]);

my ($first, $loss);
for my $step (0 .. 9) {
    my ($pred) = AI::MXTPU::invoke("dot",          [$X, $w]);
    my ($diff) = AI::MXTPU::invoke("elemwise_sub", [$pred, $Y]);
    my ($sq)   = AI::MXTPU::invoke("square",       [$diff]);
    my ($ml)   = AI::MXTPU::invoke("mean",         [$sq]);
    $loss = $ml->asscalar;
    $first = $loss if $step == 0;
    my ($g0) = AI::MXTPU::invoke("dot", [$Xt, $diff]);
    my ($g)  = AI::MXTPU::invoke("_mul_scalar", [$g0],
                                 { scalar => 2.0 / $N });
    ($w) = AI::MXTPU::invoke("sgd_update", [$w, $g],
                             { lr => 0.5, wd => 0.0 });
    printf "step %d loss %.6f\n", $step, $loss;
}
die "loss did not converge ($first -> $loss)\n"
    unless $loss < $first * 0.05;

AI::MXTPU::save("/tmp/perl_train_w.params", [$w], ["w"]);
my ($arrs, $names) = AI::MXTPU::load("/tmp/perl_train_w.params");
die "load mismatch\n"
    unless @$arrs == 1 && $names->[0] eq "w";
my @wv = @{ $arrs->[0]->aslist };
printf "perl frontend OK: loss %.6f -> %.6f; w ~ [%s]\n",
    $first, $loss, join(" ", map { sprintf "%.2f", $_ } @wv);
