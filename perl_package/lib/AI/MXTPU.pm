package AI::MXTPU;
# AI::MXTPU — minimal Perl frontend (reference perl-package/
# AI::MXNet† analog) over the training-tier C ABI.  See
# AI::MXTPU::NDArray for the OO tensor surface; AI::MXTPU::invoke
# runs any registry operator imperatively.
use strict;
use warnings;
use DynaLoader ();

our $VERSION = '0.1';
our @ISA = ('DynaLoader');

# build.sh puts MXTPU.so next to this tree; let bootstrap find it
__PACKAGE__->bootstrap($VERSION);

package AI::MXTPU::NDArray;
use strict;
use warnings;

sub _wrap {
    my ($class, $handle) = @_;
    return bless { h => $handle }, $class;
}

# AI::MXTPU::NDArray->zeros([2,3])  (float32)
sub zeros {
    my ($class, $shape) = @_;
    return $class->_wrap(AI::MXTPU::_xs_create($shape, 0));
}

# AI::MXTPU::NDArray->from_list([2,3], [1..6])
sub from_list {
    my ($class, $shape, $data) = @_;
    my $self = $class->zeros($shape);
    AI::MXTPU::_xs_copy_from($self->{h}, $data);
    return $self;
}

sub shape {
    my ($self) = @_;
    return [AI::MXTPU::_xs_shape($self->{h})];
}

sub size {
    my ($self) = @_;
    my $n = 1;
    $n *= $_ for @{$self->shape};
    return $n;
}

sub aslist {
    my ($self) = @_;
    return [AI::MXTPU::_xs_copy_to($self->{h}, $self->size)];
}

sub asscalar {
    my ($self) = @_;
    return $self->aslist->[0];
}

sub DESTROY {
    my ($self) = @_;
    AI::MXTPU::_xs_free($self->{h}) if defined $self->{h};
}

package AI::MXTPU;

# AI::MXTPU::invoke("dot", [$a, $b], { transpose_b => 1 })
# -> list of NDArrays
sub invoke {
    my ($op, $inputs, $params) = @_;
    $params ||= {};
    my @keys = sort keys %$params;
    my @vals = map { "" . $params->{$_} } @keys;
    my @hs = map { $_->{h} } @$inputs;
    my @out = AI::MXTPU::_xs_invoke($op, \@hs, \@keys, \@vals);
    return map { AI::MXTPU::NDArray->_wrap($_) } @out;
}

sub save {
    my ($fname, $arrays, $names) = @_;
    my @hs = map { $_->{h} } @$arrays;
    AI::MXTPU::_xs_save($fname, \@hs, $names || []);
}

sub load {
    my ($fname) = @_;
    my ($hs, $names) = AI::MXTPU::_xs_load($fname);
    return ([map { AI::MXTPU::NDArray->_wrap($_) } @$hs], $names);
}

1;
