/*
 * AI::MXTPU — minimal Perl frontend (reference ``perl-package/``†
 * AI::MXNet analog) over the training-tier C ABI
 * (core/c_api_ndarray.h): NDArray create/copy/query, registry-op
 * invoke, save/load.  Built by perl_package/build.sh via xsubpp.
 *
 * Perl-side API (lib/AI/MXTPU.pm wraps these _xs functions in an OO
 * layer): handles are opaque IVs owned by AI::MXTPU::NDArray.
 */
#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include "c_api_ndarray.h"

static void croak_last(pTHX_ const char *what) {
  croak("%s: %s", what, MXNDGetLastError());
}

/* The Perl list marshalling is float32-only; copying another dtype
 * through a float-sized buffer would over/under-run it (r4 review). */
static void require_f32(pTHX_ IV h) {
  int dtype = -1;
  if (MXNDArrayGetDType(INT2PTR(NDArrayHandle, h), &dtype) != 0)
    croak_last(aTHX_ "MXNDArrayGetDType");
  if (dtype != 0)
    croak("AI::MXTPU list copies support float32 arrays only "
          "(got dtype code %d)", dtype);
}

MODULE = AI::MXTPU  PACKAGE = AI::MXTPU

PROTOTYPES: DISABLE

IV
_xs_create(shape_av, dtype)
    AV *shape_av
    int dtype
  CODE:
    {
      mx_uint shape[32];
      mx_uint ndim = (mx_uint)(av_len(shape_av) + 1);
      NDArrayHandle h;
      mx_uint i;
      if (ndim > 32) croak("too many dimensions");
      for (i = 0; i < ndim; ++i) {
        SV **e = av_fetch(shape_av, i, 0);
        shape[i] = (mx_uint)SvUV(e ? *e : &PL_sv_undef);
      }
      if (MXNDArrayCreate(shape, ndim, 1, 0, 0, dtype, &h) != 0)
        croak_last(aTHX_ "MXNDArrayCreate");
      RETVAL = PTR2IV(h);
    }
  OUTPUT:
    RETVAL

void
_xs_free(h)
    IV h
  CODE:
    MXNDArrayFree(INT2PTR(NDArrayHandle, h));

void
_xs_copy_from(h, data_av)
    IV h
    AV *data_av
  CODE:
    {
      size_t n = (size_t)(av_len(data_av) + 1);
      float *buf;
      size_t i;
      require_f32(aTHX_ h);
      Newx(buf, n, float);
      for (i = 0; i < n; ++i) {
        SV **e = av_fetch(data_av, i, 0);
        buf[i] = (float)SvNV(e ? *e : &PL_sv_undef);
      }
      if (MXNDArraySyncCopyFromCPU(INT2PTR(NDArrayHandle, h), buf, n)
          != 0) {
        Safefree(buf);
        croak_last(aTHX_ "MXNDArraySyncCopyFromCPU");
      }
      Safefree(buf);
    }

void
_xs_copy_to(h, n)
    IV h
    UV n
  PPCODE:
    {
      float *buf;
      UV i;
      require_f32(aTHX_ h);
      Newx(buf, n, float);
      if (MXNDArraySyncCopyToCPU(INT2PTR(NDArrayHandle, h), buf, n)
          != 0) {
        Safefree(buf);
        croak_last(aTHX_ "MXNDArraySyncCopyToCPU");
      }
      EXTEND(SP, (SSize_t)n);
      for (i = 0; i < n; ++i) PUSHs(sv_2mortal(newSVnv(buf[i])));
      Safefree(buf);
    }

void
_xs_shape(h)
    IV h
  PPCODE:
    {
      mx_uint ndim = 0, i;
      const mx_uint *shp = NULL;
      if (MXNDArrayGetShape(INT2PTR(NDArrayHandle, h), &ndim, &shp)
          != 0)
        croak_last(aTHX_ "MXNDArrayGetShape");
      EXTEND(SP, (SSize_t)ndim);
      for (i = 0; i < ndim; ++i)
        PUSHs(sv_2mortal(newSVuv(shp[i])));
    }

void
_xs_invoke(op_name, in_av, keys_av, vals_av)
    char *op_name
    AV *in_av
    AV *keys_av
    AV *vals_av
  PPCODE:
    {
      OpHandle op;
      NDArrayHandle ins[64];
      NDArrayHandle *outs = NULL;
      const char *keys[64];
      const char *vals[64];
      int n_in = (int)(av_len(in_av) + 1);
      int n_par = (int)(av_len(keys_av) + 1);
      int n_out = 0, i;
      if (n_in > 64 || n_par > 64) croak("too many inputs/params");
      if (NNGetOpHandle(op_name, &op) != 0)
        croak_last(aTHX_ "NNGetOpHandle");
      for (i = 0; i < n_in; ++i) {
        SV **e = av_fetch(in_av, i, 0);
        ins[i] = INT2PTR(NDArrayHandle, SvIV(e ? *e : &PL_sv_undef));
      }
      for (i = 0; i < n_par; ++i) {
        SV **k = av_fetch(keys_av, i, 0);
        SV **v = av_fetch(vals_av, i, 0);
        keys[i] = SvPV_nolen(k ? *k : &PL_sv_undef);
        vals[i] = SvPV_nolen(v ? *v : &PL_sv_undef);
      }
      if (MXImperativeInvoke(op, n_in, ins, &n_out, &outs, n_par,
                             keys, vals) != 0)
        croak_last(aTHX_ "MXImperativeInvoke");
      EXTEND(SP, (SSize_t)n_out);
      for (i = 0; i < n_out; ++i)
        PUSHs(sv_2mortal(newSViv(PTR2IV(outs[i]))));
    }

void
_xs_save(fname, handles_av, keys_av)
    char *fname
    AV *handles_av
    AV *keys_av
  CODE:
    {
      NDArrayHandle hs[256];
      const char *keys[256];
      mx_uint n = (mx_uint)(av_len(handles_av) + 1);
      int with_keys = av_len(keys_av) + 1 > 0;
      mx_uint i;
      if (n > 256) croak("too many arrays");
      for (i = 0; i < n; ++i) {
        SV **e = av_fetch(handles_av, i, 0);
        hs[i] = INT2PTR(NDArrayHandle, SvIV(e ? *e : &PL_sv_undef));
        if (with_keys) {
          SV **k = av_fetch(keys_av, i, 0);
          keys[i] = SvPV_nolen(k ? *k : &PL_sv_undef);
        }
      }
      if (MXNDArraySave(fname, n, hs, with_keys ? keys : NULL) != 0)
        croak_last(aTHX_ "MXNDArraySave");
    }

void
_xs_load(fname)
    char *fname
  PPCODE:
    {
      mx_uint n_arr = 0, n_names = 0, i;
      NDArrayHandle *arrs = NULL;
      const char **names = NULL;
      AV *h_av;
      AV *n_av;
      if (MXNDArrayLoad(fname, &n_arr, &arrs, &n_names, &names) != 0)
        croak_last(aTHX_ "MXNDArrayLoad");
      h_av = newAV();
      n_av = newAV();
      for (i = 0; i < n_arr; ++i)
        av_push(h_av, newSViv(PTR2IV(arrs[i])));
      for (i = 0; i < n_names; ++i)
        av_push(n_av, newSVpv(names[i], 0));
      EXTEND(SP, 2);
      PUSHs(sv_2mortal(newRV_noinc((SV *)h_av)));
      PUSHs(sv_2mortal(newRV_noinc((SV *)n_av)));
    }
