/*
 * Training-tier C ABI — minimal NDArray + imperative-invoke surface of
 * the reference's include/mxnet/c_api.h† (MXNDArray*,
 * MXImperativeInvoke), enough for a third-language binding to train a
 * model without reinventing the predictor (VERDICT r3 item 8).
 *
 * Implementation (c_api_ndarray.cc) embeds CPython and drives
 * mxtpu.c_ndarray; link with -lmxtpu_ndarray (build:
 * `make -C core ndarray`).  All functions return 0 on success, -1 on
 * failure with the message available via MXNDGetLastError().
 */
#ifndef MXTPU_C_API_NDARRAY_H_
#define MXTPU_C_API_NDARRAY_H_

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef void *NDArrayHandle;
typedef void *OpHandle;

/* Last error message for this thread (empty string if none). */
const char *MXNDGetLastError(void);

/* Zero-initialised array.  dtype codes are the reference's
 * (mshadow/base.h†): 0 f32, 1 f64, 2 f16, 3 u8, 4 i32, 5 i8, 6 i64.
 * dev_type/dev_id are accepted for ABI compatibility; placement is
 * the runtime's (XLA) concern.  delay_alloc degrades to zeros. */
int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, int dtype,
                    NDArrayHandle *out);

int MXNDArrayFree(NDArrayHandle handle);

/* Copy `size` ELEMENTS of host data into / out of the array. */
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data,
                           size_t size);

/* *out_pdata stays owned by the handle, valid until the next call on
 * it or MXNDArrayFree. */
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                      const mx_uint **out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype);

/* Resolve a registry operator by name (nnvm NNGetOpHandle†). */
int NNGetOpHandle(const char *op_name, OpHandle *out);

/* Run an operator imperatively (MXImperativeInvoke†).  Outputs are
 * library-allocated: *outputs receives a thread-local array of new
 * handles (valid until the next invoke on this thread; the HANDLES
 * stay valid until MXNDArrayFree) and *num_outputs its length.
 * Params are string key/value pairs, the reference's attr format. */
int MXImperativeInvoke(OpHandle op, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys,
                       const char **param_vals);

/* Save named (keys != NULL) or anonymous arrays to a .params file
 * (dmlc binary stream — byte-compatible with the reference). */
int MXNDArraySave(const char *fname, mx_uint num_args,
                  NDArrayHandle *args, const char **keys);

/* Load a .params file.  *out_arr / *out_names are thread-local
 * (valid until the next load on this thread); handles live until
 * MXNDArrayFree. */
int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_API_NDARRAY_H_ */
