"""Build the native core:  cd core && python setup.py build_ext --inplace
(installs mxtpu_core*.so next to this file; mxtpu.recordio picks it up
automatically — see mxtpu/recordio.py)."""
from setuptools import Extension, setup

setup(
    name="mxtpu_core",
    version="0.1.0",
    ext_modules=[
        Extension(
            "mxtpu_core",
            sources=["recordio_core.cc"],
            extra_compile_args=["-O3", "-std=c++17", "-pthread"],
            extra_link_args=["-pthread"],
        )
    ],
)
