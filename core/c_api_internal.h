/*
 * Internal (non-installed) shared definitions between the C ABI
 * translation units.  The NDArray handle layout lives here so the
 * symbolic tier (c_api_symbolic.cc) can wrap/unwrap handles created
 * by the imperative tier (c_api_ndarray.cc) — one struct definition,
 * not two that must be kept in sync.
 */
#ifndef MXTPU_C_API_INTERNAL_H_
#define MXTPU_C_API_INTERNAL_H_

#include <Python.h>

#include <vector>

#include "c_api_ndarray.h"

namespace mxtpu_capi {

struct Array {
  PyObject *obj = nullptr;          // mxtpu NDArray
  std::vector<mx_uint> shape_buf;   // backs MXNDArrayGetShape
};

inline Array *as_array(NDArrayHandle h) {
  return static_cast<Array *>(h);
}

// wraps a NEW reference (takes ownership)
inline NDArrayHandle wrap_array(PyObject *obj) {
  Array *a = new Array();
  a->obj = obj;
  return a;
}

}  // namespace mxtpu_capi

#endif  /* MXTPU_C_API_INTERNAL_H_ */
