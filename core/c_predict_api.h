/*
 * C predict ABI — drop-in surface of the reference's
 * include/mxnet/c_predict_api.h† over the TPU-native runtime.
 *
 * Implementation (c_predict_api.cc) embeds CPython and drives
 * mxtpu.c_predict.Predictor; link with -lmxtpu_predict (build:
 * `make -C core predict`).  All functions return 0 on success, -1 on
 * failure with the message available via MXGetLastError().
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *PredictorHandle;

/* Last error message for this thread (empty string if none). */
const char *MXGetLastError(void);

/* Create a predictor from a symbol JSON string and the contents of a
 * .params file (dmlc binary or MXTPU01 container).
 *   dev_type: 1 = cpu, 2 = gpu(= the TPU device in this build)
 *   input_keys / input_shape_indptr / input_shape_data describe the
 *   input shapes exactly as in the reference ABI: input i has shape
 *   input_shape_data[indptr[i] : indptr[i+1]].
 */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data,
                 PredictorHandle *out);

/* Shape of output out_index; *shape_data stays owned by the handle and
 * is valid until the next call on it. */
int MXPredGetOutputShape(PredictorHandle handle, mx_uint out_index,
                         mx_uint **shape_data, mx_uint *shape_ndim);

/* Copy `size` floats into the named input. */
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size);

/* Run the forward pass. */
int MXPredForward(PredictorHandle handle);

/* Copy output out_index into data (size = element count). */
int MXPredGetOutput(PredictorHandle handle, mx_uint out_index,
                    mx_float *data, mx_uint size);

/* New predictor for different input shapes, sharing the weights of
 * `handle` (reference MXPredReshape†).  The original handle stays
 * valid; free both. */
int MXPredReshape(mx_uint num_input_nodes, const char **input_keys,
                  const mx_uint *input_shape_indptr,
                  const mx_uint *input_shape_data,
                  PredictorHandle handle, PredictorHandle *out);

/* Release the predictor. */
int MXPredFree(PredictorHandle handle);

#ifdef __cplusplus
}
#endif
#endif /* MXTPU_C_PREDICT_API_H_ */
