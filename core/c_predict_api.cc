/*
 * C predict ABI implementation (reference src/c_api/c_predict_api.cc†
 * rebuilt over the TPU runtime): embeds CPython and drives
 * mxtpu.c_predict.  The C side stays numpy-free — tensors cross the
 * boundary as PyBytes, so the only link dependency is libpython.
 *
 * Works both embedded in a plain C program (initializes the
 * interpreter on first use) and loaded into an existing Python
 * process (detects the live interpreter and only takes the GIL).
 */
#include "c_predict_api.h"

#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

#include "pyembed.h"

using mxtpu_embed::GIL;

namespace {

thread_local std::string g_last_error;

struct Predictor {
  PyObject *obj = nullptr;              // mxtpu.c_predict.Predictor
  std::vector<mx_uint> shape_buf;       // backs MXPredGetOutputShape
};

void set_error_from_python() {
  mxtpu_embed::set_error_from_python(&g_last_error);
}

bool ensure_interpreter() {
  return mxtpu_embed::ensure_interpreter(&g_last_error);
}

}  // namespace

extern "C" {

const char *MXGetLastError(void) { return g_last_error.c_str(); }

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data,
                 PredictorHandle *out) {
  if (symbol_json_str == nullptr || out == nullptr) {
    g_last_error = "null argument";
    return -1;
  }
  if (param_bytes == nullptr && param_size > 0) {
    g_last_error = "param_bytes is null but param_size > 0";
    return -1;
  }
  if (param_size < 0) {
    g_last_error = "negative param_size";
    return -1;
  }
  if (num_input_nodes > 0 &&
      (input_keys == nullptr || input_shape_indptr == nullptr ||
       input_shape_data == nullptr)) {
    g_last_error = "null input key/shape arrays";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *mod = PyImport_ImportModule("mxtpu.c_predict");
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  // every alloc checked: a NULL stored via PyList_SET_ITEM would
  // crash later inside the call machinery instead of returning -1
  PyObject *keys = PyList_New(num_input_nodes);
  PyObject *shapes = PyList_New(num_input_nodes);
  PyObject *blob = nullptr;
  bool build_ok = keys != nullptr && shapes != nullptr;
  for (mx_uint i = 0; build_ok && i < num_input_nodes; ++i) {
    PyObject *key = PyUnicode_FromString(input_keys[i]);
    if (key == nullptr) {
      build_ok = false;
      break;
    }
    PyList_SET_ITEM(keys, i, key);
    mx_uint lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *shape = PyList_New(hi - lo);
    if (shape == nullptr) {
      build_ok = false;
      break;
    }
    PyList_SET_ITEM(shapes, i, shape);
    for (mx_uint j = lo; j < hi; ++j) {
      PyObject *dim = PyLong_FromUnsignedLong(input_shape_data[j]);
      if (dim == nullptr) {
        build_ok = false;
        break;
      }
      PyList_SET_ITEM(shape, j - lo, dim);
    }
  }
  if (build_ok) {
    blob = PyBytes_FromStringAndSize(
        static_cast<const char *>(param_bytes), param_size);
    build_ok = blob != nullptr;
  }
  if (!build_ok) {
    set_error_from_python();
    Py_XDECREF(keys);
    Py_XDECREF(shapes);
    Py_XDECREF(blob);
    Py_DECREF(mod);
    return -1;
  }
  PyObject *pred = PyObject_CallMethod(
      mod, "_create", "sOiiOO", symbol_json_str, blob, dev_type,
      dev_id, keys, shapes);
  Py_DECREF(blob);
  Py_DECREF(keys);
  Py_DECREF(shapes);
  Py_DECREF(mod);
  if (pred == nullptr) {
    set_error_from_python();
    return -1;
  }
  Predictor *h = new Predictor();
  h->obj = pred;
  *out = h;
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint out_index,
                         mx_uint **shape_data, mx_uint *shape_ndim) {
  Predictor *h = static_cast<Predictor *>(handle);
  if (h == nullptr) {
    g_last_error = "null handle";
    return -1;
  }
  GIL gil;
  PyObject *shape = PyObject_CallMethod(h->obj, "get_output_shape",
                                        "I", out_index);
  if (shape == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(shape);
  h->shape_buf.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    h->shape_buf[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shape, i)));
  }
  Py_DECREF(shape);
  *shape_data = h->shape_buf.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size) {
  Predictor *h = static_cast<Predictor *>(handle);
  if (h == nullptr || key == nullptr || data == nullptr) {
    g_last_error = "null argument";
    return -1;
  }
  GIL gil;
  PyObject *bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(size) * sizeof(mx_float));
  PyObject *r = PyObject_CallMethod(h->obj, "set_input", "sO", key,
                                    bytes);
  Py_DECREF(bytes);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  Predictor *h = static_cast<Predictor *>(handle);
  if (h == nullptr) {
    g_last_error = "null handle";
    return -1;
  }
  GIL gil;
  PyObject *r = PyObject_CallMethod(h->obj, "forward", nullptr);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint out_index,
                    mx_float *data, mx_uint size) {
  Predictor *h = static_cast<Predictor *>(handle);
  if (h == nullptr || data == nullptr) {
    g_last_error = "null argument";
    return -1;
  }
  GIL gil;
  PyObject *bytes = PyObject_CallMethod(h->obj, "get_output", "I",
                                        out_index);
  if (bytes == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_ssize_t n = PyBytes_Size(bytes);
  if (n != static_cast<Py_ssize_t>(size) *
               static_cast<Py_ssize_t>(sizeof(mx_float))) {
    g_last_error = "output size mismatch: have " + std::to_string(n) +
                   " bytes, caller asked for " +
                   std::to_string(size * sizeof(mx_float));
    Py_DECREF(bytes);
    return -1;
  }
  std::memcpy(data, PyBytes_AsString(bytes), static_cast<size_t>(n));
  Py_DECREF(bytes);
  return 0;
}

int MXPredReshape(mx_uint num_input_nodes, const char **input_keys,
                  const mx_uint *input_shape_indptr,
                  const mx_uint *input_shape_data,
                  PredictorHandle handle, PredictorHandle *out) {
  Predictor *h = static_cast<Predictor *>(handle);
  if (h == nullptr || out == nullptr) {
    g_last_error = "null argument";
    return -1;
  }
  if (num_input_nodes > 0 &&
      (input_keys == nullptr || input_shape_indptr == nullptr ||
       input_shape_data == nullptr)) {
    g_last_error = "null input key/shape arrays";
    return -1;
  }
  GIL gil;
  // build {key: shape} dict with checked allocations
  PyObject *shapes = PyDict_New();
  bool build_ok = shapes != nullptr;
  for (mx_uint i = 0; build_ok && i < num_input_nodes; ++i) {
    mx_uint lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *shape = PyList_New(hi - lo);
    if (shape == nullptr) {
      build_ok = false;
      break;
    }
    for (mx_uint j = lo; j < hi; ++j) {
      PyObject *dim = PyLong_FromUnsignedLong(input_shape_data[j]);
      if (dim == nullptr) {
        build_ok = false;
        break;
      }
      PyList_SET_ITEM(shape, j - lo, dim);
    }
    if (build_ok &&
        PyDict_SetItemString(shapes, input_keys[i], shape) != 0) {
      build_ok = false;
    }
    Py_DECREF(shape);
  }
  if (!build_ok) {
    set_error_from_python();
    Py_XDECREF(shapes);
    return -1;
  }
  PyObject *pred = PyObject_CallMethod(h->obj, "reshape", "O", shapes);
  Py_DECREF(shapes);
  if (pred == nullptr) {
    set_error_from_python();
    return -1;
  }
  Predictor *nh = new Predictor();
  nh->obj = pred;
  *out = nh;
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  Predictor *h = static_cast<Predictor *>(handle);
  if (h == nullptr) return 0;
  if (Py_IsInitialized()) {
    GIL gil;
    Py_XDECREF(h->obj);
  }
  delete h;
  return 0;
}

}  // extern "C"
