/*
 * Shared CPython-embedding layer for the C ABIs (predict + ndarray).
 * ONE once_flag in ONE translation unit: when both ABI surfaces live
 * in the same shared library (libmxtpu_c.so), two threads making
 * their first calls through different surfaces can no longer race
 * Py_InitializeEx (r4 review).
 */
#ifndef MXTPU_PYEMBED_H_
#define MXTPU_PYEMBED_H_

#include <Python.h>

#include <string>

namespace mxtpu_embed {

// Initialize (or adopt) the interpreter; promotes libpython to
// RTLD_GLOBAL first so Python's own extension modules resolve when
// this library was dlopen()ed by a non-Python host (perl XS, dlopen
// from C).  Thread-safe.  Returns false on failure and fills *err.
bool ensure_interpreter(std::string *err);

// Fetch the current Python exception into *err (normalized str()).
void set_error_from_python(std::string *err);

class GIL {
 public:
  GIL() : state_(PyGILState_Ensure()) {}
  ~GIL() { PyGILState_Release(state_); }
  GIL(const GIL &) = delete;
  GIL &operator=(const GIL &) = delete;

 private:
  PyGILState_STATE state_;
};

}  // namespace mxtpu_embed

#endif  /* MXTPU_PYEMBED_H_ */
