/*
 * Minimal C consumer of the predict ABI (reference
 * example/image-classification/predict-cpp†): loads an exported
 * model, feeds an input read from a raw float file, prints the
 * outputs.
 *
 *   gcc predict_example.c -L. -lmxtpu_predict -Wl,-rpath,'$ORIGIN'
 *   ./a.out model-symbol.json model-0000.params 1,8 input.f32
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "c_predict_api.h"

static char *read_file(const char *path, long *size) {
  FILE *f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  *size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char *buf = (char *)malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) {
    fclose(f);
    free(buf);
    return NULL;
  }
  buf[*size] = 0;
  fclose(f);
  return buf;
}

int main(int argc, char **argv) {
  if (argc < 5) {
    fprintf(stderr,
            "usage: %s symbol.json weights.params N,C[,H,W] in.f32\n",
            argv[0]);
    return 2;
  }
  long sym_size = 0, param_size = 0, in_size = 0;
  char *sym_json = read_file(argv[1], &sym_size);
  char *params = read_file(argv[2], &param_size);
  char *input = read_file(argv[4], &in_size);
  if (!sym_json || !params || !input) {
    fprintf(stderr, "failed to read model/input files\n");
    return 2;
  }

  mx_uint shape[8], ndim = 0;
  for (char *tok = strtok(argv[3], ","); tok && ndim < 8;
       tok = strtok(NULL, ","))
    shape[ndim++] = (mx_uint)atoi(tok);
  mx_uint indptr[2] = {0, ndim};
  const char *keys[1] = {"data"};

  PredictorHandle pred = NULL;
  if (MXPredCreate(sym_json, params, (int)param_size, 1, 0, 1, keys,
                   indptr, shape, &pred) != 0) {
    fprintf(stderr, "MXPredCreate: %s\n", MXGetLastError());
    return 1;
  }
  if (MXPredSetInput(pred, "data", (const mx_float *)input,
                     (mx_uint)(in_size / sizeof(mx_float))) != 0) {
    fprintf(stderr, "MXPredSetInput: %s\n", MXGetLastError());
    return 1;
  }
  if (MXPredForward(pred) != 0) {
    fprintf(stderr, "MXPredForward: %s\n", MXGetLastError());
    return 1;
  }
  mx_uint *oshape = NULL, ondim = 0;
  if (MXPredGetOutputShape(pred, 0, &oshape, &ondim) != 0) {
    fprintf(stderr, "MXPredGetOutputShape: %s\n", MXGetLastError());
    return 1;
  }
  mx_uint total = 1;
  printf("output shape:");
  for (mx_uint i = 0; i < ondim; ++i) {
    printf(" %u", oshape[i]);
    total *= oshape[i];
  }
  printf("\n");
  mx_float *out = (mx_float *)malloc(total * sizeof(mx_float));
  if (MXPredGetOutput(pred, 0, out, total) != 0) {
    fprintf(stderr, "MXPredGetOutput: %s\n", MXGetLastError());
    return 1;
  }
  printf("output:");
  for (mx_uint i = 0; i < total && i < 16; ++i)
    printf(" %.6f", out[i]);
  printf("\n");
  free(out);
  free(input);
  free(params);
  free(sym_json);
  MXPredFree(pred);
  return 0;
}
