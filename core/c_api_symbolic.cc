/*
 * Symbolic/executor-tier C ABI implementation (reference
 * src/c_api/c_api_symbolic.cc† + c_api_executor.cc† rebuilt over the
 * TPU runtime): embeds CPython and drives mxtpu.c_symbol.  Same
 * embedding discipline as the predict/ndarray tiers — one shared
 * interpreter (pyembed.cc), tensors cross as NDArray handles from the
 * imperative tier, strings/attrs as C strings.
 */
#include "c_api_symbolic.h"

#include <Python.h>

#include <string>
#include <vector>

#include "c_api_internal.h"
#include "pyembed.h"

using mxtpu_capi::as_array;
using mxtpu_capi::wrap_array;
using mxtpu_embed::GIL;

namespace {

thread_local std::string g_sym_last_error;

// thread-local result stores
thread_local std::string g_json_store;
thread_local std::vector<std::string> g_name_store;
thread_local std::vector<const char *> g_name_ptrs;
thread_local std::vector<NDArrayHandle> g_exec_out;

// CSR shape-result stores (one triple per category)
struct ShapeStore {
  std::vector<std::vector<mx_uint>> shapes;
  std::vector<mx_uint> ndims;
  std::vector<const mx_uint *> ptrs;
};
thread_local ShapeStore g_shape_store[3];

struct Sym {
  PyObject *obj = nullptr;  // mxtpu Symbol or c_symbol.AtomicSymbol
};

struct Exec {
  PyObject *obj = nullptr;  // mxtpu Executor
};

void set_error_from_python() {
  mxtpu_embed::set_error_from_python(&g_sym_last_error);
}

bool ensure_interpreter() {
  return mxtpu_embed::ensure_interpreter(&g_sym_last_error);
}

// call mxtpu.c_symbol.<fn>(*args); returns new ref or nullptr
PyObject *call_helper(const char *fn, PyObject *args) {
  PyObject *mod = PyImport_ImportModule("mxtpu.c_symbol");
  if (mod == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PyObject *r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  if (r == nullptr) set_error_from_python();
  return r;
}

Sym *as_sym(SymbolHandle h) { return static_cast<Sym *>(h); }
Exec *as_exec(ExecutorHandle h) { return static_cast<Exec *>(h); }

SymbolHandle wrap_sym(PyObject *obj) {
  Sym *s = new Sym();
  s->obj = obj;  // takes the reference
  return s;
}

// str-list helper call -> (out_size, out_names) via thread-local store
int list_call(const char *fn, SymbolHandle sym, mx_uint *out_size,
              const char ***out_names) {
  if (sym == nullptr || out_size == nullptr || out_names == nullptr) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", as_sym(sym)->obj);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper(fn, args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  g_name_store.clear();
  g_name_ptrs.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(r); ++i) {
    const char *s = PyUnicode_AsUTF8(PyList_GET_ITEM(r, i));
    g_name_store.emplace_back(s != nullptr ? s : "");
  }
  Py_DECREF(r);
  for (const std::string &s : g_name_store)
    g_name_ptrs.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(g_name_ptrs.size());
  *out_names = g_name_ptrs.data();
  return 0;
}

// build ([names...], [shape tuples...]) from the CSR triple
bool build_shape_args(mx_uint num_args, const char **names,
                      const mx_uint *ind, const mx_uint *data,
                      PyObject **out_names, PyObject **out_shapes) {
  PyObject *nl = PyList_New(num_args);
  PyObject *sl = PyList_New(num_args);
  if (nl == nullptr || sl == nullptr) {
    Py_XDECREF(nl);
    Py_XDECREF(sl);
    return false;
  }
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject *n = PyUnicode_FromString(names[i]);
    mx_uint lo = ind[i], hi = ind[i + 1];
    PyObject *t = PyTuple_New(hi - lo);
    if (n == nullptr || t == nullptr) {
      Py_XDECREF(n);
      Py_XDECREF(t);
      Py_DECREF(nl);
      Py_DECREF(sl);
      return false;
    }
    for (mx_uint j = lo; j < hi; ++j)
      PyTuple_SET_ITEM(t, j - lo, PyLong_FromUnsignedLong(data[j]));
    PyList_SET_ITEM(nl, i, n);
    PyList_SET_ITEM(sl, i, t);
  }
  *out_names = nl;
  *out_shapes = sl;
  return true;
}

// fill one CSR result category from a list of shape tuples
bool store_shapes(PyObject *shape_list, ShapeStore *st,
                  mx_uint *out_size, const mx_uint **out_ndim,
                  const mx_uint ***out_data) {
  st->shapes.clear();
  st->ndims.clear();
  st->ptrs.clear();
  Py_ssize_t n = PyList_Size(shape_list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *t = PyList_GET_ITEM(shape_list, i);
    std::vector<mx_uint> dims;
    for (Py_ssize_t j = 0; j < PyTuple_Size(t); ++j) {
      dims.push_back(static_cast<mx_uint>(
          PyLong_AsUnsignedLong(PyTuple_GET_ITEM(t, j))));
    }
    st->shapes.push_back(std::move(dims));
  }
  for (const auto &s : st->shapes) {
    st->ndims.push_back(static_cast<mx_uint>(s.size()));
    st->ptrs.push_back(s.data());
  }
  *out_size = static_cast<mx_uint>(st->shapes.size());
  *out_ndim = st->ndims.data();
  *out_data = st->ptrs.data();
  return true;
}

}  // namespace

extern "C" {

const char *MXSymGetLastError(void) { return g_sym_last_error.c_str(); }

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  if (json == nullptr || out == nullptr) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(s)", json);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("create_from_json", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  *out = wrap_sym(r);
  return 0;
}

int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out) {
  if (fname == nullptr || out == nullptr) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(s)", fname);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("create_from_file", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  *out = wrap_sym(r);
  return 0;
}

int MXSymbolSaveToJSON(SymbolHandle sym, const char **out_json) {
  if (sym == nullptr || out_json == nullptr) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", as_sym(sym)->obj);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("save_to_json", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  const char *s = PyUnicode_AsUTF8(r);
  g_json_store = s != nullptr ? s : "";
  Py_DECREF(r);
  *out_json = g_json_store.c_str();
  return 0;
}

int MXSymbolSaveToFile(SymbolHandle sym, const char *fname) {
  if (sym == nullptr || fname == nullptr) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(Os)", as_sym(sym)->obj, fname);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("save_to_file", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXSymbolCreateVariable(const char *name, SymbolHandle *out) {
  if (name == nullptr || out == nullptr) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(s)", name);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("create_variable", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  *out = wrap_sym(r);
  return 0;
}

int MXSymbolCreateAtomicSymbol(const char *op_name, mx_uint num_param,
                               const char **keys, const char **vals,
                               SymbolHandle *out) {
  if (op_name == nullptr || out == nullptr ||
      (num_param > 0 && (keys == nullptr || vals == nullptr))) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *kl = PyList_New(num_param);
  PyObject *vl = PyList_New(num_param);
  bool ok = kl != nullptr && vl != nullptr;
  for (mx_uint i = 0; ok && i < num_param; ++i) {
    PyObject *k = PyUnicode_FromString(keys[i]);
    PyObject *v = PyUnicode_FromString(vals[i]);
    if (k == nullptr || v == nullptr) {
      ok = false;
      Py_XDECREF(k);
      Py_XDECREF(v);
      break;
    }
    PyList_SET_ITEM(kl, i, k);
    PyList_SET_ITEM(vl, i, v);
  }
  PyObject *args = ok ? Py_BuildValue("(sOO)", op_name, kl, vl)
                      : nullptr;
  Py_XDECREF(kl);
  Py_XDECREF(vl);
  if (args == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *r = call_helper("create_atomic", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  *out = wrap_sym(r);
  return 0;
}

int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args_in) {
  if (sym == nullptr || (num_args > 0 && args_in == nullptr)) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *kl = PyList_New(keys != nullptr ? num_args : 0);
  PyObject *al = PyList_New(num_args);
  bool ok = kl != nullptr && al != nullptr;
  for (mx_uint i = 0; ok && keys != nullptr && i < num_args; ++i) {
    PyObject *k = PyUnicode_FromString(keys[i]);
    if (k == nullptr) { ok = false; break; }
    PyList_SET_ITEM(kl, i, k);
  }
  for (mx_uint i = 0; ok && i < num_args; ++i) {
    PyObject *o = as_sym(args_in[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(al, i, o);
  }
  PyObject *args = ok ? Py_BuildValue("(OsOO)", as_sym(sym)->obj,
                                      name != nullptr ? name : "",
                                      kl, al)
                      : nullptr;
  Py_XDECREF(kl);
  Py_XDECREF(al);
  if (args == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *r = call_helper("compose", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  // in-place semantics: rebind the handle to the composed symbol
  Sym *s = as_sym(sym);
  Py_XDECREF(s->obj);
  s->obj = r;
  return 0;
}

int MXSymbolFree(SymbolHandle sym) {
  if (sym == nullptr) return 0;
  Sym *s = as_sym(sym);
  if (Py_IsInitialized()) {
    GIL gil;
    Py_XDECREF(s->obj);
  }
  delete s;
  return 0;
}

int MXSymbolListArguments(SymbolHandle sym, mx_uint *out_size,
                          const char ***out_names) {
  return list_call("list_arguments", sym, out_size, out_names);
}

int MXSymbolListOutputs(SymbolHandle sym, mx_uint *out_size,
                        const char ***out_names) {
  return list_call("list_outputs", sym, out_size, out_names);
}

int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint *out_size,
                                const char ***out_names) {
  return list_call("list_auxiliary_states", sym, out_size, out_names);
}

int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char **arg_names, const mx_uint *arg_ind,
                       const mx_uint *arg_shape_data,
                       mx_uint *in_shape_size,
                       const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data,
                       mx_uint *out_shape_size,
                       const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data,
                       mx_uint *aux_shape_size,
                       const mx_uint **aux_shape_ndim,
                       const mx_uint ***aux_shape_data) {
  if (sym == nullptr ||
      (num_args > 0 && (arg_names == nullptr || arg_ind == nullptr ||
                        arg_shape_data == nullptr))) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *nl = nullptr, *sl = nullptr;
  if (!build_shape_args(num_args, arg_names, arg_ind, arg_shape_data,
                        &nl, &sl)) {
    set_error_from_python();
    return -1;
  }
  PyObject *args = Py_BuildValue("(OOO)", as_sym(sym)->obj, nl, sl);
  Py_DECREF(nl);
  Py_DECREF(sl);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("infer_shape", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  bool ok =
      store_shapes(PyTuple_GET_ITEM(r, 0), &g_shape_store[0],
                   in_shape_size, in_shape_ndim, in_shape_data) &&
      store_shapes(PyTuple_GET_ITEM(r, 1), &g_shape_store[1],
                   out_shape_size, out_shape_ndim, out_shape_data) &&
      store_shapes(PyTuple_GET_ITEM(r, 2), &g_shape_store[2],
                   aux_shape_size, aux_shape_ndim, aux_shape_data);
  Py_DECREF(r);
  return ok ? 0 : -1;
}

int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         const char *grad_req, mx_uint num_args,
                         const char **arg_names, const mx_uint *arg_ind,
                         const mx_uint *arg_shape_data,
                         ExecutorHandle *out) {
  (void)dev_type; (void)dev_id;
  if (sym == nullptr || grad_req == nullptr || out == nullptr ||
      (num_args > 0 && (arg_names == nullptr || arg_ind == nullptr ||
                        arg_shape_data == nullptr))) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *nl = nullptr, *sl = nullptr;
  if (!build_shape_args(num_args, arg_names, arg_ind, arg_shape_data,
                        &nl, &sl)) {
    set_error_from_python();
    return -1;
  }
  PyObject *args = Py_BuildValue("(OsOO)", as_sym(sym)->obj, grad_req,
                                 nl, sl);
  Py_DECREF(nl);
  Py_DECREF(sl);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("simple_bind", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  Exec *e = new Exec();
  e->obj = r;
  *out = e;
  return 0;
}

int MXExecutorSetArg(ExecutorHandle exec, const char *name,
                     NDArrayHandle arr) {
  if (exec == nullptr || name == nullptr || arr == nullptr) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(OsO)", as_exec(exec)->obj, name,
                                 as_array(arr)->obj);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("executor_set_arg", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

static int get_array_call(const char *fn, ExecutorHandle exec,
                          const char *name, NDArrayHandle *out) {
  if (exec == nullptr || name == nullptr || out == nullptr) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(Os)", as_exec(exec)->obj, name);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper(fn, args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  *out = wrap_array(r);
  return 0;
}

int MXExecutorGetArg(ExecutorHandle exec, const char *name,
                     NDArrayHandle *out) {
  return get_array_call("executor_get_arg", exec, name, out);
}

int MXExecutorGetGrad(ExecutorHandle exec, const char *name,
                      NDArrayHandle *out) {
  return get_array_call("executor_get_grad", exec, name, out);
}

int MXExecutorForward(ExecutorHandle exec, int is_train) {
  if (exec == nullptr) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(Oi)", as_exec(exec)->obj, is_train);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("executor_forward", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXExecutorBackward(ExecutorHandle exec, mx_uint len,
                       NDArrayHandle *head_grads) {
  if (exec == nullptr || (len > 0 && head_grads == nullptr)) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *hl = PyList_New(len);
  if (hl == nullptr) { set_error_from_python(); return -1; }
  for (mx_uint i = 0; i < len; ++i) {
    PyObject *o = as_array(head_grads[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(hl, i, o);
  }
  PyObject *args = Py_BuildValue("(ON)", as_exec(exec)->obj, hl);
  if (args == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *r = call_helper("executor_backward", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXExecutorOutputs(ExecutorHandle exec, mx_uint *out_size,
                      NDArrayHandle **out) {
  if (exec == nullptr || out_size == nullptr || out == nullptr) {
    g_sym_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", as_exec(exec)->obj);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("executor_outputs", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  g_exec_out.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(r); ++i) {
    PyObject *o = PyList_GET_ITEM(r, i);
    Py_INCREF(o);
    g_exec_out.push_back(wrap_array(o));
  }
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(g_exec_out.size());
  *out = g_exec_out.data();
  return 0;
}

int MXExecutorFree(ExecutorHandle exec) {
  if (exec == nullptr) return 0;
  Exec *e = as_exec(exec);
  if (Py_IsInitialized()) {
    GIL gil;
    Py_XDECREF(e->obj);
  }
  delete e;
  return 0;
}

}  // extern "C"
