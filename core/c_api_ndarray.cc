/*
 * Training-tier C ABI implementation (reference
 * src/c_api/c_api_ndarray.cc† rebuilt over the TPU runtime): embeds
 * CPython and drives mxtpu.c_ndarray.  Same embedding discipline as
 * c_predict_api.cc — numpy-free C side, tensors cross as PyBytes,
 * works embedded in a plain C program or loaded into a live Python
 * process.
 */
#include "c_api_ndarray.h"

#include <Python.h>

#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "c_api_internal.h"
#include "pyembed.h"

using mxtpu_embed::GIL;

namespace {

thread_local std::string g_nd_last_error;

// element size by reference dtype code; 0 = unknown (caller errors).
// bfloat16 (12) included — the esize tables previously defaulted
// unknown codes to 4 bytes, an OOB read for bf16 (r4 review)
size_t esize_of(long code) {
  switch (code) {
    case 0: return 4;   // float32
    case 1: return 8;   // float64
    case 2: return 2;   // float16
    case 3: return 1;   // uint8
    case 4: return 4;   // int32
    case 5: return 1;   // int8
    case 6: return 8;   // int64
    case 7: return 1;   // bool
    case 12: return 2;  // bfloat16
    default: return 0;
  }
}

using mxtpu_capi::Array;

// thread-local result stores backing MXImperativeInvoke/MXNDArrayLoad
thread_local std::vector<NDArrayHandle> g_invoke_out;
thread_local std::vector<NDArrayHandle> g_load_arrs;
thread_local std::vector<std::string> g_load_name_store;
thread_local std::vector<const char *> g_load_names;

void set_error_from_python() {
  mxtpu_embed::set_error_from_python(&g_nd_last_error);
}

bool ensure_interpreter() {
  return mxtpu_embed::ensure_interpreter(&g_nd_last_error);
}

PyObject *helper(const char *fn) {
  PyObject *mod = PyImport_ImportModule("mxtpu.c_ndarray");
  if (mod == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) set_error_from_python();
  return f;
}

// call mxtpu.c_ndarray.<fn>(*args); steals nothing, returns new ref
PyObject *call_helper(const char *fn, PyObject *args) {
  PyObject *f = helper(fn);
  if (f == nullptr) return nullptr;
  PyObject *r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  if (r == nullptr) set_error_from_python();
  return r;
}

PyObject *shape_tuple(const mx_uint *shape, mx_uint ndim) {
  PyObject *t = PyTuple_New(ndim);
  if (t == nullptr) return nullptr;
  for (mx_uint i = 0; i < ndim; ++i) {
    PyObject *v = PyLong_FromUnsignedLong(shape[i]);
    if (v == nullptr) {
      Py_DECREF(t);
      return nullptr;
    }
    PyTuple_SET_ITEM(t, i, v);
  }
  return t;
}

using mxtpu_capi::as_array;

NDArrayHandle wrap(PyObject *obj) {
  return mxtpu_capi::wrap_array(obj);  // takes the reference
}

}  // namespace

extern "C" {

const char *MXNDGetLastError(void) { return g_nd_last_error.c_str(); }

int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, int dtype,
                    NDArrayHandle *out) {
  (void)dev_type; (void)dev_id; (void)delay_alloc;
  if (out == nullptr || (shape == nullptr && ndim > 0)) {
    g_nd_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *st = shape_tuple(shape, ndim);
  if (st == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *args = Py_BuildValue("(Oi)", st, dtype);
  Py_DECREF(st);
  if (args == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *r = call_helper("create", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  *out = wrap(r);
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  if (handle == nullptr) return 0;
  Array *a = as_array(handle);
  if (Py_IsInitialized()) {
    GIL gil;
    Py_XDECREF(a->obj);
  }
  delete a;
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size) {
  if (handle == nullptr || (data == nullptr && size > 0)) {
    g_nd_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  Array *a = as_array(handle);
  // element size from the dtype code; shape/dtype via helpers
  PyObject *args1 = Py_BuildValue("(O)", a->obj);
  if (args1 == nullptr) { set_error_from_python(); return -1; }
  PyObject *code = call_helper("dtype_code_of", args1);
  PyObject *shp = call_helper("shape_of", args1);
  Py_DECREF(args1);
  if (code == nullptr || shp == nullptr) {
    Py_XDECREF(code);
    Py_XDECREF(shp);
    return -1;
  }
  long c = PyLong_AsLong(code);
  Py_DECREF(code);
  size_t es = esize_of(c);
  if (es == 0) {
    Py_DECREF(shp);
    g_nd_last_error = "unknown dtype code for host copy";
    return -1;
  }
  size_t nbytes = size * es;
  PyObject *blob = PyBytes_FromStringAndSize(
      static_cast<const char *>(data),
      static_cast<Py_ssize_t>(nbytes));
  PyObject *args = blob != nullptr
      ? Py_BuildValue("(OlN)", shp, c, blob) : nullptr;
  Py_DECREF(shp);
  if (args == nullptr) {
    Py_XDECREF(blob);
    set_error_from_python();
    return -1;
  }
  PyObject *r = call_helper("from_bytes", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  Py_XDECREF(a->obj);
  a->obj = r;  // rebinding IS the reference's write semantics here
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data,
                           size_t size) {
  if (handle == nullptr || data == nullptr) {
    g_nd_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  Array *a = as_array(handle);
  PyObject *args = Py_BuildValue("(O)", a->obj);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *blob = call_helper("to_bytes", args);
  PyObject *code = call_helper("dtype_code_of", args);
  Py_DECREF(args);
  if (blob == nullptr || code == nullptr) {
    Py_XDECREF(blob);
    Py_XDECREF(code);
    return -1;
  }
  long c = PyLong_AsLong(code);
  Py_DECREF(code);
  size_t es = esize_of(c);
  if (es == 0) {
    Py_DECREF(blob);
    g_nd_last_error = "unknown dtype code for host copy";
    return -1;
  }
  size_t want = size * es;
  char *buf = nullptr;
  Py_ssize_t blen = 0;
  if (PyBytes_AsStringAndSize(blob, &buf, &blen) != 0) {
    set_error_from_python();
    Py_DECREF(blob);
    return -1;
  }
  if (static_cast<size_t>(blen) < want) {
    g_nd_last_error = "copy size exceeds array size";
    Py_DECREF(blob);
    return -1;
  }
  std::memcpy(data, buf, want);
  Py_DECREF(blob);
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                      const mx_uint **out_pdata) {
  if (handle == nullptr || out_dim == nullptr || out_pdata == nullptr) {
    g_nd_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  Array *a = as_array(handle);
  PyObject *args = Py_BuildValue("(O)", a->obj);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *shp = call_helper("shape_of", args);
  Py_DECREF(args);
  if (shp == nullptr) return -1;
  a->shape_buf.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(shp); ++i) {
    a->shape_buf.push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shp, i))));
  }
  Py_DECREF(shp);
  *out_dim = static_cast<mx_uint>(a->shape_buf.size());
  *out_pdata = a->shape_buf.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype) {
  if (handle == nullptr || out_dtype == nullptr) {
    g_nd_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  Array *a = as_array(handle);
  PyObject *args = Py_BuildValue("(O)", a->obj);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *code = call_helper("dtype_code_of", args);
  Py_DECREF(args);
  if (code == nullptr) return -1;
  *out_dtype = static_cast<int>(PyLong_AsLong(code));
  Py_DECREF(code);
  return 0;
}

int NNGetOpHandle(const char *op_name, OpHandle *out) {
  if (op_name == nullptr || out == nullptr) {
    g_nd_last_error = "null argument";
    return -1;
  }
  // handles are INTERNED per name (bindings call this on every
  // invoke — a fresh allocation per call would leak unboundedly;
  // r4 review); validated lazily at invoke time so this stays
  // callable before the interpreter exists
  static std::mutex mu;
  static std::map<std::string, std::string *> *interned =
      new std::map<std::string, std::string *>();
  std::lock_guard<std::mutex> lock(mu);
  auto it = interned->find(op_name);
  if (it == interned->end()) {
    it = interned->emplace(op_name,
                           new std::string(op_name)).first;
  }
  *out = it->second;
  return 0;
}

int MXImperativeInvoke(OpHandle op, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys,
                       const char **param_vals) {
  if (op == nullptr || num_outputs == nullptr || outputs == nullptr ||
      (num_inputs > 0 && inputs == nullptr) ||
      (num_params > 0 &&
       (param_keys == nullptr || param_vals == nullptr))) {
    g_nd_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  const std::string *name = static_cast<std::string *>(op);
  PyObject *ins = PyList_New(num_inputs);
  PyObject *keys = PyList_New(num_params);
  PyObject *vals = PyList_New(num_params);
  bool ok = ins != nullptr && keys != nullptr && vals != nullptr;
  for (int i = 0; ok && i < num_inputs; ++i) {
    PyObject *o = as_array(inputs[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(ins, i, o);
  }
  for (int i = 0; ok && i < num_params; ++i) {
    PyObject *k = PyUnicode_FromString(param_keys[i]);
    PyObject *v = PyUnicode_FromString(param_vals[i]);
    if (k == nullptr || v == nullptr) {
      ok = false;
      Py_XDECREF(k);
      Py_XDECREF(v);
      break;
    }
    PyList_SET_ITEM(keys, i, k);
    PyList_SET_ITEM(vals, i, v);
  }
  PyObject *args = ok ? Py_BuildValue("(sOOO)", name->c_str(), ins,
                                      keys, vals)
                      : nullptr;
  Py_XDECREF(ins);
  Py_XDECREF(keys);
  Py_XDECREF(vals);
  if (args == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *r = call_helper("invoke", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  g_invoke_out.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(r); ++i) {
    PyObject *o = PyList_GET_ITEM(r, i);
    Py_INCREF(o);
    g_invoke_out.push_back(wrap(o));
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(g_invoke_out.size());
  *outputs = g_invoke_out.data();
  return 0;
}

int MXNDArraySave(const char *fname, mx_uint num_args,
                  NDArrayHandle *args_in, const char **keys) {
  if (fname == nullptr || (num_args > 0 && args_in == nullptr)) {
    g_nd_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *handles = PyList_New(num_args);
  PyObject *names = keys != nullptr ? PyList_New(num_args) : Py_None;
  bool ok = handles != nullptr && names != nullptr;
  if (names == Py_None) Py_INCREF(Py_None);
  for (mx_uint i = 0; ok && i < num_args; ++i) {
    PyObject *o = as_array(args_in[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(handles, i, o);
    if (keys != nullptr) {
      PyObject *k = PyUnicode_FromString(keys[i]);
      if (k == nullptr) { ok = false; break; }
      PyList_SET_ITEM(names, i, k);
    }
  }
  PyObject *args = ok ? Py_BuildValue("(sOO)", fname, handles, names)
                      : nullptr;
  Py_XDECREF(handles);
  Py_XDECREF(names);
  if (args == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *r = call_helper("save", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names) {
  if (fname == nullptr || out_size == nullptr || out_arr == nullptr ||
      out_name_size == nullptr || out_names == nullptr) {
    g_nd_last_error = "null argument";
    return -1;
  }
  if (!ensure_interpreter()) return -1;
  GIL gil;
  PyObject *args = Py_BuildValue("(s)", fname);
  if (args == nullptr) { set_error_from_python(); return -1; }
  PyObject *r = call_helper("load", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  PyObject *arrs = PyTuple_GET_ITEM(r, 0);
  PyObject *names = PyTuple_GET_ITEM(r, 1);
  g_load_arrs.clear();
  g_load_name_store.clear();
  g_load_names.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(arrs); ++i) {
    PyObject *o = PyList_GET_ITEM(arrs, i);
    Py_INCREF(o);
    g_load_arrs.push_back(wrap(o));
  }
  for (Py_ssize_t i = 0; i < PyList_Size(names); ++i) {
    const char *s = PyUnicode_AsUTF8(PyList_GET_ITEM(names, i));
    g_load_name_store.emplace_back(s != nullptr ? s : "");
  }
  for (const std::string &s : g_load_name_store)
    g_load_names.push_back(s.c_str());
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(g_load_arrs.size());
  *out_arr = g_load_arrs.data();
  *out_name_size = static_cast<mx_uint>(g_load_names.size());
  *out_names = g_load_names.data();
  return 0;
}

}  // extern "C"
