/*
 * Train a linear model end-to-end through the training-tier C ABI —
 * the VERDICT r3 item-8 acceptance program: 10 SGD steps of
 * least-squares regression using only MXNDArray* +
 * MXImperativeInvoke, then save/load the weights and verify.
 *
 * Build & run (tests/test_c_train_abi.py drives this):
 *   make -C core ndarray
 *   gcc core/train_example.c -Lcore -lmxtpu_ndarray \
 *       -Wl,-rpath,core -o /tmp/train_example && /tmp/train_example
 */
#include <stdio.h>
#include <stdlib.h>

#include "c_api_ndarray.h"

#define N 64
#define D 4

#define CHECK(call)                                            \
  do {                                                         \
    if ((call) != 0) {                                         \
      fprintf(stderr, "FAIL %s: %s\n", #call,                  \
              MXNDGetLastError());                             \
      return 1;                                                \
    }                                                          \
  } while (0)

static int invoke1(OpHandle op, int n_in, NDArrayHandle *in,
                   int n_par, const char **pk, const char **pv,
                   NDArrayHandle *out) {
  int n_out = 0;
  NDArrayHandle *outs = NULL;
  if (MXImperativeInvoke(op, n_in, in, &n_out, &outs, n_par, pk, pv)
      != 0 || n_out < 1)
    return -1;
  *out = outs[0];
  return 0;
}

int main(void) {
  /* synthetic data: y = X w* with fixed pseudo-random X */
  float xbuf[N * D], ybuf[N];
  const float wstar[D] = {1.0f, 2.0f, -1.0f, 0.5f};
  unsigned s = 12345u;
  for (int i = 0; i < N * D; ++i) {
    s = s * 1103515245u + 12345u;
    xbuf[i] = ((float)(s >> 16 & 0x7fff) / 16384.0f) - 1.0f;
  }
  for (int i = 0; i < N; ++i) {
    ybuf[i] = 0.0f;
    for (int j = 0; j < D; ++j) ybuf[i] += xbuf[i * D + j] * wstar[j];
  }

  mx_uint xshape[2] = {N, D}, yshape[2] = {N, 1}, wshape[2] = {D, 1};
  NDArrayHandle X, y, w;
  CHECK(MXNDArrayCreate(xshape, 2, 1, 0, 0, 0, &X));
  CHECK(MXNDArrayCreate(yshape, 2, 1, 0, 0, 0, &y));
  CHECK(MXNDArrayCreate(wshape, 2, 1, 0, 0, 0, &w));
  CHECK(MXNDArraySyncCopyFromCPU(X, xbuf, N * D));
  CHECK(MXNDArraySyncCopyFromCPU(y, ybuf, N));

  OpHandle op_dot, op_sub, op_mul_s, op_sq, op_mean, op_transpose,
      op_sgd;
  CHECK(NNGetOpHandle("dot", &op_dot));
  CHECK(NNGetOpHandle("elemwise_sub", &op_sub));
  CHECK(NNGetOpHandle("_mul_scalar", &op_mul_s));
  CHECK(NNGetOpHandle("square", &op_sq));
  CHECK(NNGetOpHandle("mean", &op_mean));
  CHECK(NNGetOpHandle("transpose", &op_transpose));
  CHECK(NNGetOpHandle("sgd_update", &op_sgd));

  NDArrayHandle Xt;
  CHECK(invoke1(op_transpose, 1, &X, 0, NULL, NULL, &Xt));

  const char *lr_k[] = {"lr", "wd"};
  const char *lr_v[] = {"0.5", "0.0"};
  const char *sc_k[] = {"scalar"};
  const char *sc_v[] = {"0.03125"}; /* 2/N */

  float first_loss = -1.0f, loss = -1.0f;
  for (int step = 0; step < 10; ++step) {
    NDArrayHandle pred, diff, sq, mloss, grad_unscaled, grad;
    NDArrayHandle dot_in[2] = {X, w};
    CHECK(invoke1(op_dot, 2, dot_in, 0, NULL, NULL, &pred));
    NDArrayHandle sub_in[2] = {pred, y};
    CHECK(invoke1(op_sub, 2, sub_in, 0, NULL, NULL, &diff));
    CHECK(invoke1(op_sq, 1, &diff, 0, NULL, NULL, &sq));
    CHECK(invoke1(op_mean, 1, &sq, 0, NULL, NULL, &mloss));
    CHECK(MXNDArraySyncCopyToCPU(mloss, &loss, 1));
    if (step == 0) first_loss = loss;

    NDArrayHandle g_in[2] = {Xt, diff};
    CHECK(invoke1(op_dot, 2, g_in, 0, NULL, NULL, &grad_unscaled));
    CHECK(invoke1(op_mul_s, 1, &grad_unscaled, 1, sc_k, sc_v, &grad));
    NDArrayHandle sgd_in[2] = {w, grad};
    NDArrayHandle w_new;
    CHECK(invoke1(op_sgd, 2, sgd_in, 2, lr_k, lr_v, &w_new));
    MXNDArrayFree(w);
    w = w_new;
    printf("step %d loss %.6f\n", step, (double)loss);
    MXNDArrayFree(pred);
    MXNDArrayFree(diff);
    MXNDArrayFree(sq);
    MXNDArrayFree(mloss);
    MXNDArrayFree(grad_unscaled);
    MXNDArrayFree(grad);
  }
  if (!(loss < first_loss * 0.05f)) {
    fprintf(stderr, "FAIL: loss did not converge (%f -> %f)\n",
            (double)first_loss, (double)loss);
    return 1;
  }

  /* save -> load roundtrip of the trained weights */
  const char *keys[] = {"w"};
  CHECK(MXNDArraySave("/tmp/c_train_w.params", 1, &w, keys));
  mx_uint n_arr = 0, n_names = 0;
  NDArrayHandle *arrs = NULL;
  const char **names = NULL;
  CHECK(MXNDArrayLoad("/tmp/c_train_w.params", &n_arr, &arrs,
                      &n_names, &names));
  if (n_arr != 1 || n_names != 1) {
    fprintf(stderr, "FAIL: load returned %u arrays %u names\n",
            n_arr, n_names);
    return 1;
  }
  float wback[D], wnow[D];
  CHECK(MXNDArraySyncCopyToCPU(arrs[0], wback, D));
  CHECK(MXNDArraySyncCopyToCPU(w, wnow, D));
  for (int i = 0; i < D; ++i) {
    float d = wback[i] - wnow[i];
    if (d < 0) d = -d;
    if (d > 1e-6f) {
      fprintf(stderr, "FAIL: save/load mismatch at %d\n", i);
      return 1;
    }
  }
  mx_uint ndim = 0;
  const mx_uint *shp = NULL;
  int dtype = -1;
  CHECK(MXNDArrayGetShape(w, &ndim, &shp));
  CHECK(MXNDArrayGetDType(w, &dtype));
  if (ndim != 2 || shp[0] != D || shp[1] != 1 || dtype != 0) {
    fprintf(stderr, "FAIL: shape/dtype query\n");
    return 1;
  }
  printf("C-ABI training OK: loss %.6f -> %.6f; w ~ [%.2f %.2f %.2f "
         "%.2f]\n", (double)first_loss, (double)loss, (double)wnow[0],
         (double)wnow[1], (double)wnow[2], (double)wnow[3]);
  return 0;
}
