#include "pyembed.h"

#ifndef _GNU_SOURCE
#define _GNU_SOURCE
#endif
#include <dlfcn.h>

#include <mutex>

namespace mxtpu_embed {

namespace {
std::once_flag g_init_once;
}

bool ensure_interpreter(std::string *err) {
  std::call_once(g_init_once, []() {
    if (Py_IsInitialized()) return;
    // When this library is dlopen()ed by a non-Python host, libpython
    // arrives RTLD_LOCAL and Python's own extension modules (math,
    // numpy) fail with undefined PyFloat_Type etc.  Find libpython
    // via a symbol we link against and promote it to RTLD_GLOBAL.
    Dl_info info;
    if (dladdr(reinterpret_cast<void *>(&Py_IsInitialized), &info)
        != 0 && info.dli_fname != nullptr) {
      dlopen(info.dli_fname, RTLD_NOW | RTLD_GLOBAL | RTLD_NOLOAD);
    }
    Py_InitializeEx(0);
    if (Py_IsInitialized()) {
      // the embedding thread owns the GIL after Py_Initialize;
      // release it so every ABI call can use the uniform
      // PyGILState path
      PyEval_SaveThread();
    }
  });
  if (!Py_IsInitialized()) {
    if (err != nullptr) *err = "failed to initialize embedded Python";
    return false;
  }
  return true;
}

void set_error_from_python(std::string *err) {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  *err = "python error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *msg = PyUnicode_AsUTF8(s);
      if (msg != nullptr) *err = msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
}

}  // namespace mxtpu_embed
