/*
 * mxtpu_core — native RecordIO codec + parallel reader.
 *
 * The TPU-native counterpart of the reference's dmlc-core C++ RecordIO
 * (3rdparty/dmlc-core/src/recordio.cc†) and the threaded reader under
 * src/io/†: the input pipeline must feed TPU-host CPUs at full
 * bandwidth (SURVEY §2.1-N12), which a Python byte-scanner cannot.
 *
 * Exposed to Python through the CPython C API (no pybind11 in this
 * environment):
 *   scan(path)                      -> (offsets, lengths) numpy-free
 *                                      Python lists of ints; walks the
 *                                      record chain at C speed and
 *                                      validates magics (recovery scan)
 *   read_batch(path, offsets, lengths, n_threads=4)
 *                                   -> list of bytes; parallel pread()
 *   read_batch_into(path, offsets, lengths, out, header_bytes,
 *                   n_threads=4)    -> bytes (N*header_bytes of headers);
 *                                      reads N EQUAL-PAYLOAD records,
 *                                      writing payload[header_bytes:]
 *                                      into row i of the writable
 *                                      buffer `out` — the ImageRecordIter
 *                                      raw-record fast path: framing,
 *                                      header split, and batch assembly
 *                                      all leave Python (one call per
 *                                      batch, GIL released, parallel
 *                                      pread)
 *   pack_header(flag,label,id,id2)  -> bytes (IRHeader wire format)
 *
 * Wire format (must match mxtpu/recordio.py): u32 magic 0xced7230a,
 * u32 lrec (upper 3 bits continuation flag, lower 29 length), payload,
 * pad to 4 bytes.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <thread>
#include <vector>

static const uint32_t kMagic = 0xced7230a;

struct Rec {
  int64_t payload_off;  /* offset of (possibly multi-chunk) record start */
  int64_t length;       /* total payload length across chunks */
};

/* Walk the file once, collecting logical records (handling dmlc
 * continuation chunks).  Returns 0 on success. */
static int scan_file(const char *path, std::vector<Rec> *out,
                     std::string *err) {
  FILE *f = fopen(path, "rb");
  if (!f) {
    *err = "cannot open file";
    return -1;
  }
  fseeko(f, 0, SEEK_END);
  int64_t size = ftello(f);
  fseeko(f, 0, SEEK_SET);
  int64_t pos = 0;
  bool in_record = false;
  Rec cur{0, 0};
  unsigned char header[8];
  while (pos + 8 <= size) {
    if (fread(header, 1, 8, f) != 8) break;
    uint32_t magic, lrec;
    memcpy(&magic, header, 4);
    memcpy(&lrec, header + 4, 4);
    if (magic != kMagic) {
      *err = "bad magic (corrupt record stream)";
      fclose(f);
      return -1;
    }
    uint32_t cflag = lrec >> 29;
    int64_t len = lrec & ((1u << 29) - 1);
    if (!in_record) {
      cur.payload_off = pos;
      cur.length = 0;
    }
    cur.length += len;
    int64_t padded = (len + 3) & ~3ll;
    pos += 8 + padded;
    fseeko(f, pos, SEEK_SET);
    /* cflag: 0 complete, 1 first, 2 middle, 3 last */
    if (cflag == 0 || cflag == 3) {
      out->push_back(cur);
      in_record = false;
    } else {
      in_record = true;
    }
  }
  fclose(f);
  return 0;
}

static PyObject *py_scan(PyObject *, PyObject *args) {
  const char *path;
  if (!PyArg_ParseTuple(args, "s", &path)) return nullptr;
  std::vector<Rec> recs;
  std::string err;
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = scan_file(path, &recs, &err);
  Py_END_ALLOW_THREADS
  if (rc != 0) {
    PyErr_SetString(PyExc_IOError, err.c_str());
    return nullptr;
  }
  PyObject *offs = PyList_New((Py_ssize_t)recs.size());
  PyObject *lens = PyList_New((Py_ssize_t)recs.size());
  for (size_t i = 0; i < recs.size(); ++i) {
    PyList_SET_ITEM(offs, (Py_ssize_t)i,
                    PyLong_FromLongLong(recs[i].payload_off));
    PyList_SET_ITEM(lens, (Py_ssize_t)i,
                    PyLong_FromLongLong(recs[i].length));
  }
  PyObject *tup = PyTuple_Pack(2, offs, lens);
  Py_DECREF(offs);
  Py_DECREF(lens);
  return tup;
}

/* Read one logical record starting at `off` (header offset) from an
 * open fd, reassembling continuation chunks into buf. */
static int read_record(int fd, int64_t off, int64_t total,
                       char *buf) {
  int64_t written = 0;
  int64_t pos = off;
  while (written < total) {
    unsigned char header[8];
    if (pread(fd, header, 8, pos) != 8) return -1;
    uint32_t magic, lrec;
    memcpy(&magic, header, 4);
    memcpy(&lrec, header + 4, 4);
    if (magic != kMagic) return -1;
    int64_t len = lrec & ((1u << 29) - 1);
    if (written + len > total) return -1;
    if (pread(fd, buf + written, (size_t)len, pos + 8) != (ssize_t)len)
      return -1;
    written += len;
    pos += 8 + ((len + 3) & ~3ll);
  }
  return 0;
}

static PyObject *py_read_batch(PyObject *, PyObject *args) {
  const char *path;
  PyObject *offs_obj, *lens_obj;
  int n_threads = 4;
  if (!PyArg_ParseTuple(args, "sOO|i", &path, &offs_obj, &lens_obj,
                        &n_threads))
    return nullptr;
  Py_ssize_t n = PySequence_Size(offs_obj);
  if (n < 0 || PySequence_Size(lens_obj) != n) {
    PyErr_SetString(PyExc_ValueError, "offsets/lengths mismatch");
    return nullptr;
  }
  std::vector<int64_t> offs(n), lens(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PySequence_GetItem(offs_obj, i);
    PyObject *l = PySequence_GetItem(lens_obj, i);
    offs[i] = PyLong_AsLongLong(o);
    lens[i] = PyLong_AsLongLong(l);
    Py_XDECREF(o);
    Py_XDECREF(l);
    if (PyErr_Occurred()) return nullptr;
  }
  /* allocate result bytes objects up front (GIL held) */
  PyObject *result = PyList_New(n);
  std::vector<char *> bufs(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *b = PyBytes_FromStringAndSize(nullptr, lens[i]);
    if (!b) {
      Py_DECREF(result);
      return nullptr;
    }
    bufs[i] = PyBytes_AS_STRING(b);
    PyList_SET_ITEM(result, i, b);
  }
  int failed = 0;
  Py_BEGIN_ALLOW_THREADS {
    if (n_threads < 1) n_threads = 1;
    if (n_threads > 64) n_threads = 64;
    std::vector<std::thread> workers;
    std::vector<int> fails((size_t)n_threads, 0);
    for (int t = 0; t < n_threads; ++t) {
      workers.emplace_back([&, t]() {
        int fd = open(path, O_RDONLY);
        if (fd < 0) {
          fails[t] = 1;
          return;
        }
        for (Py_ssize_t i = t; i < n; i += n_threads) {
          if (read_record(fd, offs[i], lens[i], bufs[i]) != 0) {
            fails[t] = 1;
            break;
          }
        }
        close(fd);
      });
    }
    for (auto &w : workers) w.join();
    for (int t = 0; t < n_threads; ++t) failed |= fails[t];
  }
  Py_END_ALLOW_THREADS
  if (failed) {
    Py_DECREF(result);
    PyErr_SetString(PyExc_IOError, "read_batch failed (corrupt record "
                                   "or unreadable file)");
    return nullptr;
  }
  return result;
}

/* Read one logical record, routing the first `hdr_len` payload bytes
 * into `hdr` and the remaining `row_len` bytes into `row`.  The
 * record's total payload must be exactly hdr_len + row_len. */
static int read_record_split(int fd, int64_t off, int64_t hdr_len,
                             char *hdr, int64_t row_len, char *row) {
  int64_t written = 0;
  int64_t total = hdr_len + row_len;
  int64_t pos = off;
  while (written < total) {
    unsigned char header[8];
    if (pread(fd, header, 8, pos) != 8) return -1;
    uint32_t magic, lrec;
    memcpy(&magic, header, 4);
    memcpy(&lrec, header + 4, 4);
    if (magic != kMagic) return -1;
    int64_t len = lrec & ((1u << 29) - 1);
    if (written + len > total) return -1;
    int64_t src = pos + 8;
    int64_t remain = len;
    if (written < hdr_len) {
      int64_t take = hdr_len - written < remain ? hdr_len - written
                                                : remain;
      if (pread(fd, hdr + written, (size_t)take, src) != (ssize_t)take)
        return -1;
      written += take;
      src += take;
      remain -= take;
    }
    if (remain > 0) {
      if (pread(fd, row + (written - hdr_len), (size_t)remain, src) !=
          (ssize_t)remain)
        return -1;
      written += remain;
    }
    pos += 8 + ((len + 3) & ~3ll);
  }
  return 0;
}

static PyObject *py_read_batch_into(PyObject *, PyObject *args) {
  const char *path;
  PyObject *offs_obj, *lens_obj;
  Py_buffer out;
  int header_bytes;
  int n_threads = 4;
  if (!PyArg_ParseTuple(args, "sOOw*i|i", &path, &offs_obj, &lens_obj,
                        &out, &header_bytes, &n_threads))
    return nullptr;
  Py_ssize_t n = PySequence_Size(offs_obj);
  if (n <= 0 || PySequence_Size(lens_obj) != n || header_bytes < 0) {
    PyBuffer_Release(&out);
    PyErr_SetString(PyExc_ValueError,
                    "offsets/lengths mismatch or empty batch");
    return nullptr;
  }
  std::vector<int64_t> offs(n);
  int64_t payload = -1;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PySequence_GetItem(offs_obj, i);
    PyObject *l = PySequence_GetItem(lens_obj, i);
    offs[i] = PyLong_AsLongLong(o);
    int64_t li = PyLong_AsLongLong(l);
    Py_XDECREF(o);
    Py_XDECREF(l);
    if (PyErr_Occurred()) {
      PyBuffer_Release(&out);
      return nullptr;
    }
    if (payload < 0) payload = li;
    if (li != payload) {
      PyBuffer_Release(&out);
      PyErr_SetString(PyExc_ValueError,
                      "read_batch_into needs equal record lengths");
      return nullptr;
    }
  }
  int64_t row = payload - header_bytes;
  if (row < 0 || !PyBuffer_IsContiguous(&out, 'C') ||
      (int64_t)out.len != row * n) {
    PyBuffer_Release(&out);
    PyErr_Format(PyExc_ValueError,
                 "out buffer must be C-contiguous with %lld bytes "
                 "(%lld records x %lld row bytes)",
                 (long long)(row * n), (long long)n, (long long)row);
    return nullptr;
  }
  PyObject *hdrs = PyBytes_FromStringAndSize(
      nullptr, (Py_ssize_t)(n * (int64_t)header_bytes));
  if (!hdrs) {
    PyBuffer_Release(&out);
    return nullptr;
  }
  char *hdr_base = PyBytes_AS_STRING(hdrs);
  char *row_base = (char *)out.buf;
  int failed = 0;
  Py_BEGIN_ALLOW_THREADS {
    if (n_threads < 1) n_threads = 1;
    if (n_threads > 64) n_threads = 64;
    if ((Py_ssize_t)n_threads > n) n_threads = (int)n;
    std::vector<std::thread> workers;
    std::vector<int> fails((size_t)n_threads, 0);
    for (int t = 0; t < n_threads; ++t) {
      workers.emplace_back([&, t]() {
        int fd = open(path, O_RDONLY);
        if (fd < 0) {
          fails[t] = 1;
          return;
        }
        for (Py_ssize_t i = t; i < n; i += n_threads) {
          if (read_record_split(fd, offs[i], header_bytes,
                                hdr_base + i * (int64_t)header_bytes,
                                row, row_base + i * row) != 0) {
            fails[t] = 1;
            break;
          }
        }
        close(fd);
      });
    }
    for (auto &w : workers) w.join();
    for (int t = 0; t < n_threads; ++t) failed |= fails[t];
  }
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&out);
  if (failed) {
    Py_DECREF(hdrs);
    PyErr_SetString(PyExc_IOError,
                    "read_batch_into failed (record length mismatch, "
                    "corrupt record, or unreadable file)");
    return nullptr;
  }
  return hdrs;
}

static PyObject *py_pack_header(PyObject *, PyObject *args) {
  unsigned int flag;
  float label;
  unsigned long long id, id2;
  if (!PyArg_ParseTuple(args, "IfKK", &flag, &label, &id, &id2))
    return nullptr;
  char buf[4 + 4 + 8 + 8];
  memcpy(buf, &flag, 4);
  memcpy(buf + 4, &label, 4);
  memcpy(buf + 8, &id, 8);
  memcpy(buf + 16, &id2, 8);
  return PyBytes_FromStringAndSize(buf, sizeof(buf));
}

static PyMethodDef Methods[] = {
    {"scan", py_scan, METH_VARARGS,
     "scan(path) -> (offsets, lengths): index all records at C speed"},
    {"read_batch", py_read_batch, METH_VARARGS,
     "read_batch(path, offsets, lengths, n_threads=4) -> list[bytes]"},
    {"read_batch_into", py_read_batch_into, METH_VARARGS,
     "read_batch_into(path, offsets, lengths, out, header_bytes, "
     "n_threads=4) -> headers bytes; payloads land in rows of `out`"},
    {"pack_header", py_pack_header, METH_VARARGS,
     "pack_header(flag, label, id, id2) -> IRHeader bytes"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "mxtpu_core",
    "native RecordIO codec + parallel reader", -1, Methods};

PyMODINIT_FUNC PyInit_mxtpu_core(void) {
  return PyModule_Create(&moduledef);
}
