/*
 * Symbolic/executor-tier C ABI — the MXSymbol* / MXExecutor* surface
 * of the reference's include/mxnet/c_api.h† (implemented upstream in
 * src/c_api/c_api_symbolic.cc† and c_api_executor.cc†), enough for a
 * third-language frontend to load a -symbol.json, bind it, and train
 * without embedding Python logic of its own (VERDICT r4 item 6).
 *
 * Implementation (c_api_symbolic.cc) embeds CPython and drives
 * mxtpu.c_symbol; it shares the single embedded interpreter with the
 * predict and ndarray tiers (link -lmxtpu_c).  All functions return 0
 * on success, -1 on failure; message via MXSymGetLastError().
 *
 * Documented divergence from the reference ABI: upstream frontends
 * mutate executor argument buffers in place (aliased device memory).
 * XLA arrays are immutable, so argument updates use explicit
 * MXExecutorSetArg rebinds — the same rebinding discipline
 * MXNDArraySyncCopyFromCPU already uses at the imperative tier.
 */
#ifndef MXTPU_C_API_SYMBOLIC_H_
#define MXTPU_C_API_SYMBOLIC_H_

#include <stddef.h>

#include "c_api_ndarray.h"

#ifdef __cplusplus
extern "C" {
#endif

typedef void *SymbolHandle;
typedef void *ExecutorHandle;

const char *MXSymGetLastError(void);

/* ---- symbol construction / serialization ------------------------- */

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out);
/* *out_json is thread-local, valid until the next call. */
int MXSymbolSaveToJSON(SymbolHandle sym, const char **out_json);
int MXSymbolSaveToFile(SymbolHandle sym, const char *fname);
int MXSymbolCreateVariable(const char *name, SymbolHandle *out);
/* Create an operator node awaiting inputs (reference
 * MXSymbolCreateAtomicSymbol† takes an AtomicSymbolCreator; here the
 * operator is resolved by registry name). */
int MXSymbolCreateAtomicSymbol(const char *op_name, mx_uint num_param,
                               const char **keys, const char **vals,
                               SymbolHandle *out);
/* Supply inputs to an atomic symbol (positional when keys == NULL,
 * by argument name otherwise).  Mutates `sym` in place, exactly like
 * the reference's MXSymbolCompose†. */
int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args);
int MXSymbolFree(SymbolHandle sym);

/* ---- introspection ----------------------------------------------- */

/* String lists are thread-local, valid until the next MXSymbolList*
 * call on this thread. */
int MXSymbolListArguments(SymbolHandle sym, mx_uint *out_size,
                          const char ***out_names);
int MXSymbolListOutputs(SymbolHandle sym, mx_uint *out_size,
                        const char ***out_names);
int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint *out_size,
                                const char ***out_names);

/* Shape inference.  Provided shapes are named (arg_names) with a CSR
 * layout: ind[i]..ind[i+1] indexes into shape_data.  Results are
 * thread-local CSR triples, valid until the next call. */
int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char **arg_names, const mx_uint *arg_ind,
                       const mx_uint *arg_shape_data,
                       mx_uint *in_shape_size,
                       const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data,
                       mx_uint *out_shape_size,
                       const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data,
                       mx_uint *aux_shape_size,
                       const mx_uint **aux_shape_ndim,
                       const mx_uint ***aux_shape_data);

/* ---- executor tier ----------------------------------------------- */

/* Infer shapes from the named input shapes (CSR layout as above),
 * allocate zero-initialised argument/aux arrays, return an executor.
 * grad_req: "write", "add" or "null" (applies to every argument,
 * the reference's common case). */
int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         const char *grad_req, mx_uint num_args,
                         const char **arg_names, const mx_uint *arg_ind,
                         const mx_uint *arg_shape_data,
                         ExecutorHandle *out);

/* Rebind a named argument (or aux state) to a new array.  The
 * executor takes its own reference; the caller keeps the handle. */
int MXExecutorSetArg(ExecutorHandle exec, const char *name,
                     NDArrayHandle arr);
/* Get the current array bound to a named argument or aux state as a
 * NEW handle (caller frees with MXNDArrayFree). */
int MXExecutorGetArg(ExecutorHandle exec, const char *name,
                     NDArrayHandle *out);
/* Gradient of a named argument as a new handle; errors if grad_req
 * was "null" for it or backward has not run. */
int MXExecutorGetGrad(ExecutorHandle exec, const char *name,
                      NDArrayHandle *out);

int MXExecutorForward(ExecutorHandle exec, int is_train);
/* head_grads: one per output, or NULL/len 0 for the implicit
 * ones-like head gradient (reference backward()† semantics). */
int MXExecutorBackward(ExecutorHandle exec, mx_uint len,
                       NDArrayHandle *head_grads);
/* *out receives a thread-local array of new handles (valid until the
 * next call on this thread; handles live until MXNDArrayFree). */
int MXExecutorOutputs(ExecutorHandle exec, mx_uint *out_size,
                      NDArrayHandle **out);
int MXExecutorFree(ExecutorHandle exec);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_API_SYMBOLIC_H_ */
