// C++ inference example over the mxtpu-cpp frontend (the reference's
// cpp-package predict example†): load an exported model, run a batch,
// print the argmax per row.
//
//   g++ -std=c++17 predict.cc -I../include -L../../core \
//       -lmxtpu_predict -Wl,-rpath,$PWD/../../core -o predict
//   ./predict model-symbol.json model-0000.params 2 8
#include <cstdlib>
#include <iostream>
#include <vector>

#include <mxtpu-cpp/predictor.hpp>

int main(int argc, char **argv) {
  if (argc < 5) {
    std::cerr << "usage: predict SYMBOL PARAMS BATCH FEATURES\n";
    return 2;
  }
  const std::string symbol_file = argv[1];
  const std::string param_file = argv[2];
  const mx_uint batch = static_cast<mx_uint>(std::atoi(argv[3]));
  const mx_uint feat = static_cast<mx_uint>(std::atoi(argv[4]));
  try {
    auto pred = mxtpu::Predictor::FromFiles(
        symbol_file, param_file, {{"data", {batch, feat}}});
    std::vector<mx_float> x(batch * feat);
    for (std::size_t i = 0; i < x.size(); ++i) {
      x[i] = static_cast<mx_float>((i % 7) - 3) * 0.25f;
    }
    pred.SetInput("data", x);
    pred.Forward();
    auto shape = pred.GetOutputShape(0);
    auto out = pred.GetOutput(0);
    std::cout << "output shape:";
    for (auto d : shape) std::cout << " " << d;
    std::cout << "\n";
    const std::size_t classes = shape.back();
    for (mx_uint b = 0; b < batch; ++b) {
      std::size_t best = 0;
      for (std::size_t c = 1; c < classes; ++c) {
        if (out[b * classes + c] > out[b * classes + best]) best = c;
      }
      std::cout << "row " << b << " -> class " << best << "\n";
    }
    // reshape to a different batch and run again (MXPredReshape)
    auto pred2 = pred.Reshape({{"data", {2 * batch, feat}}});
    std::vector<mx_float> x2(2 * batch * feat, 0.5f);
    pred2.SetInput("data", x2);
    pred2.Forward();
    std::cout << "reshaped batch " << 2 * batch << " ok\n";
  } catch (const mxtpu::Error &e) {
    std::cerr << "mxtpu error: " << e.what() << "\n";
    return 1;
  }
  return 0;
}
