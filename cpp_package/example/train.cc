/*
 * Train a linear model from C++ through the training-tier frontend
 * (mxtpu-cpp/ndarray.hpp) — the reference cpp-package's† training
 * capability, same task as core/train_example.c but RAII/STL.
 *
 * Build & run (tests/test_cpp_frontend.py drives this):
 *   make -C core ndarray
 *   g++ -std=c++17 cpp_package/example/train.cc -Lcore \
 *       -lmxtpu_ndarray -Wl,-rpath,core -o /tmp/cpp_train
 */
#include <cstdio>
#include <vector>

#include "../include/mxtpu-cpp/ndarray.hpp"

using mxtpu::nd::NDArray;
using mxtpu::nd::invoke;

int main() {
  const int N = 64, D = 4;
  const float wstar[D] = {1.0f, 2.0f, -1.0f, 0.5f};
  std::vector<float> xbuf(N * D), ybuf(N);
  unsigned s = 12345u;
  for (auto &v : xbuf) {
    s = s * 1103515245u + 12345u;
    v = ((float)(s >> 16 & 0x7fff) / 16384.0f) - 1.0f;
  }
  for (int i = 0; i < N; ++i)
    for (int j = 0; j < D; ++j)
      ybuf[i] += xbuf[i * D + j] * wstar[j];

  NDArray X({N, D}, xbuf), Y({N, 1}, ybuf), w({D, 1});
  NDArray Xt = invoke("transpose", {X})[0];

  float first = -1.0f, loss = -1.0f;
  for (int step = 0; step < 10; ++step) {
    NDArray pred = invoke("dot", {X, w})[0];
    NDArray diff = invoke("elemwise_sub", {pred, Y})[0];
    loss = invoke("mean", {invoke("square", {diff})[0]})[0].scalar();
    if (step == 0) first = loss;
    NDArray g0 = invoke("dot", {Xt, diff})[0];
    NDArray g = invoke("_mul_scalar", {g0},
                       {{"scalar", "0.03125"}})[0];  /* 2/N */
    w = invoke("sgd_update", {w, g},
               {{"lr", "0.5"}, {"wd", "0.0"}})[0];
    std::printf("step %d loss %.6f\n", step, (double)loss);
  }
  if (!(loss < first * 0.05f)) {
    std::fprintf(stderr, "FAIL: no convergence (%f -> %f)\n",
                 (double)first, (double)loss);
    return 1;
  }

  mxtpu::nd::save("/tmp/cpp_train_w.params", {w}, {"w"});
  auto loaded = mxtpu::nd::load("/tmp/cpp_train_w.params");
  if (loaded.first.size() != 1 || loaded.second.size() != 1 ||
      loaded.second[0] != "w") {
    std::fprintf(stderr, "FAIL: load mismatch\n");
    return 1;
  }
  auto wv = loaded.first[0].to_vector();
  std::printf("C++ training frontend OK: loss %.6f -> %.6f; "
              "w ~ [%.2f %.2f %.2f %.2f]\n",
              (double)first, (double)loss, (double)wv[0],
              (double)wv[1], (double)wv[2], (double)wv[3]);
  return 0;
}
