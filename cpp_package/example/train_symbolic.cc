/*
 * Symbolic-tier C ABI acceptance program (VERDICT r4 item 6): a C++
 * frontend that loads a -symbol.json + .params checkpoint, binds the
 * graph, and trains 10 SGD steps — entirely through the C ABI
 * (MXSymbol* / MXExecutor* / MXNDArray* / MXImperativeInvoke), no
 * Python logic on this side of the boundary.
 *
 * Reference workflow parity: src/c_api/c_api_symbolic.cc† +
 * c_api_executor.cc† as driven by cpp-package/include/mxnet-cpp/†.
 *
 * Usage: train_symbolic <symbol.json> <init.params> <out.params>
 * (tests/test_c_symbolic_abi.py generates the inputs and drives it.)
 */
#include <cmath>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "c_api_ndarray.h"
#include "c_api_symbolic.h"

#define N 64
#define D 4

#define CHECK(call)                                               \
  do {                                                            \
    if ((call) != 0) {                                            \
      std::fprintf(stderr, "FAIL %s: %s / %s\n", #call,           \
                   MXSymGetLastError(), MXNDGetLastError());      \
      return 1;                                                   \
    }                                                             \
  } while (0)

static int invoke1(OpHandle op, int n_in, NDArrayHandle *in,
                   int n_par, const char **pk, const char **pv,
                   NDArrayHandle *out) {
  int n_out = 0;
  NDArrayHandle *outs = nullptr;
  if (MXImperativeInvoke(op, n_in, in, &n_out, &outs, n_par, pk, pv)
      != 0 || n_out < 1)
    return -1;
  *out = outs[0];
  return 0;
}

int main(int argc, char **argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <symbol.json> <init.params> <out.params>\n",
                 argv[0]);
    return 2;
  }

  /* ---- load the graph ------------------------------------------- */
  SymbolHandle sym;
  CHECK(MXSymbolCreateFromFile(argv[1], &sym));

  mx_uint n_args = 0;
  const char **arg_names = nullptr;
  CHECK(MXSymbolListArguments(sym, &n_args, &arg_names));
  std::vector<std::string> args(arg_names, arg_names + n_args);
  std::printf("arguments:");
  for (const std::string &a : args) std::printf(" %s", a.c_str());
  std::printf("\n");

  /* ---- bind: provide data/label shapes, infer the rest ---------- */
  const char *in_names[] = {"data", "label"};
  mx_uint ind[] = {0, 2, 4};
  mx_uint shape_data[] = {N, D, N, 1};
  ExecutorHandle exec;
  CHECK(MXExecutorSimpleBind(sym, 1, 0, "write", 2, in_names, ind,
                             shape_data, &exec));

  /* ---- load the checkpoint into the executor -------------------- */
  mx_uint n_loaded = 0, n_names = 0;
  NDArrayHandle *loaded = nullptr;
  const char **loaded_names = nullptr;
  CHECK(MXNDArrayLoad(argv[2], &n_loaded, &loaded, &n_names,
                      &loaded_names));
  std::vector<std::string> param_names;
  for (mx_uint i = 0; i < n_loaded; ++i) {
    /* checkpoint convention: "arg:<name>" / "aux:<name>" prefixes */
    std::string nm = loaded_names[i];
    if (nm.rfind("arg:", 0) == 0 || nm.rfind("aux:", 0) == 0)
      nm = nm.substr(4);
    CHECK(MXExecutorSetArg(exec, nm.c_str(), loaded[i]));
    param_names.push_back(nm);
    CHECK(MXNDArrayFree(loaded[i]));  /* executor holds its own ref */
  }
  std::printf("loaded %u params\n", n_loaded);

  /* ---- synthetic dataset: y = X w* ------------------------------ */
  float xbuf[N * D], ybuf[N];
  const float wstar[D] = {1.0f, 2.0f, -1.0f, 0.5f};
  unsigned s = 12345u;
  for (int i = 0; i < N * D; ++i) {
    s = s * 1103515245u + 12345u;
    xbuf[i] = ((float)(s >> 16 & 0x7fff) / 16384.0f) - 1.0f;
  }
  for (int i = 0; i < N; ++i) {
    ybuf[i] = 0.0f;
    for (int j = 0; j < D; ++j) ybuf[i] += xbuf[i * D + j] * wstar[j];
  }
  mx_uint xshape[2] = {N, D}, yshape[2] = {N, 1};
  NDArrayHandle X, y;
  CHECK(MXNDArrayCreate(xshape, 2, 1, 0, 0, 0, &X));
  CHECK(MXNDArrayCreate(yshape, 2, 1, 0, 0, 0, &y));
  CHECK(MXNDArraySyncCopyFromCPU(X, xbuf, N * D));
  CHECK(MXNDArraySyncCopyFromCPU(y, ybuf, N));
  CHECK(MXExecutorSetArg(exec, "data", X));
  CHECK(MXExecutorSetArg(exec, "label", y));

  OpHandle op_sgd;
  CHECK(NNGetOpHandle("sgd_update", &op_sgd));
  /* LinearRegressionOutput's head gradient is per-sample but SUMMED
   * over the batch by the executor (reference semantics — no implicit
   * 1/N), so the stable lr scales with 1/N. */
  const char *lr_k[] = {"lr", "wd"};
  const char *lr_v[] = {"0.008", "0.0"};

  /* ---- 10 training steps ---------------------------------------- */
  float first_loss = 0.0f, loss = 0.0f;
  for (int step = 0; step < 10; ++step) {
    CHECK(MXExecutorForward(exec, 1));
    mx_uint n_out = 0;
    NDArrayHandle *outs = nullptr;
    CHECK(MXExecutorOutputs(exec, &n_out, &outs));
    float pred[N];
    CHECK(MXNDArraySyncCopyToCPU(outs[0], pred, N));
    for (mx_uint i = 0; i < n_out; ++i) CHECK(MXNDArrayFree(outs[i]));
    loss = 0.0f;
    for (int i = 0; i < N; ++i) {
      float d = pred[i] - ybuf[i];
      loss += d * d;
    }
    loss /= N;
    if (step == 0) first_loss = loss;
    std::printf("step %d mse %.6f\n", step, loss);

    CHECK(MXExecutorBackward(exec, 0, nullptr));
    for (const std::string &nm : param_names) {
      if (nm == "data" || nm == "label") continue;
      NDArrayHandle wcur, grad, wnew;
      CHECK(MXExecutorGetArg(exec, nm.c_str(), &wcur));
      CHECK(MXExecutorGetGrad(exec, nm.c_str(), &grad));
      NDArrayHandle in2[2] = {wcur, grad};
      if (invoke1(op_sgd, 2, in2, 2, lr_k, lr_v, &wnew) != 0) {
        std::fprintf(stderr, "sgd_update failed: %s\n",
                     MXNDGetLastError());
        return 1;
      }
      CHECK(MXExecutorSetArg(exec, nm.c_str(), wnew));
      /* the executor holds its own references; drop ours (wnew's
       * backing slot is thread-local to the invoke, but the wrapper
       * must still be freed once the executor has rebound) */
      CHECK(MXNDArrayFree(wcur));
      CHECK(MXNDArrayFree(grad));
      CHECK(MXNDArrayFree(wnew));
    }
  }
  if (!(loss < first_loss * 0.5f) || !std::isfinite(loss)) {
    std::fprintf(stderr, "loss did not converge: %f -> %f\n",
                 first_loss, loss);
    return 1;
  }

  /* ---- save the trained weights through the ABI ----------------- */
  std::vector<NDArrayHandle> save_arrs;
  std::vector<std::string> save_names_store;
  std::vector<const char *> save_names;
  for (const std::string &nm : param_names) {
    if (nm == "data" || nm == "label") continue;
    NDArrayHandle h;
    CHECK(MXExecutorGetArg(exec, nm.c_str(), &h));
    save_arrs.push_back(h);
    save_names_store.push_back("arg:" + nm);
  }
  for (const std::string &nm : save_names_store)
    save_names.push_back(nm.c_str());
  CHECK(MXNDArraySave(argv[3], (mx_uint)save_arrs.size(),
                      save_arrs.data(), save_names.data()));
  for (NDArrayHandle h : save_arrs) CHECK(MXNDArrayFree(h));
  CHECK(MXNDArrayFree(X));
  CHECK(MXNDArrayFree(y));

  /* round-trip the symbol JSON through the ABI as well */
  const char *json = nullptr;
  CHECK(MXSymbolSaveToJSON(sym, &json));
  if (json == nullptr || std::strlen(json) < 10) {
    std::fprintf(stderr, "symbol JSON round-trip failed\n");
    return 1;
  }

  CHECK(MXExecutorFree(exec));
  CHECK(MXSymbolFree(sym));
  std::printf("C-ABI symbolic training OK (mse %.6f -> %.6f)\n",
              first_loss, loss);
  return 0;
}
