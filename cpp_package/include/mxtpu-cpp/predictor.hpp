/*
 * C++ inference frontend over the C predict ABI (the role of the
 * reference's cpp-package† generated op.h / predictor surface, scoped
 * to deployment: RAII + std::vector in, std::vector out).
 *
 * Header-only; link with -lmxtpu_predict (build: `make -C core
 * predict`).  Throws mxtpu::Error on any ABI failure, carrying
 * MXGetLastError().
 */
#ifndef MXTPU_CPP_PREDICTOR_HPP_
#define MXTPU_CPP_PREDICTOR_HPP_

#include <cstddef>
#include <fstream>
#include <map>
#include <memory>
#include <numeric>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "../../../core/c_predict_api.h"

namespace mxtpu {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string &what) : std::runtime_error(what) {}
};

inline void check(int rc, const char *call) {
  if (rc != 0) {
    throw Error(std::string(call) + ": " + MXGetLastError());
  }
}

inline std::string read_file(const std::string &path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw Error("cannot open " + path);
  return std::string((std::istreambuf_iterator<char>(f)),
                     std::istreambuf_iterator<char>());
}

using Shape = std::vector<mx_uint>;

/* RAII predictor: symbol JSON + params blob + named input shapes. */
class Predictor {
 public:
  Predictor(const std::string &symbol_json, const std::string &params,
            const std::map<std::string, Shape> &input_shapes,
            int dev_type = 1, int dev_id = 0) {
    std::vector<const char *> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> data;
    for (const auto &kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      data.insert(data.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<mx_uint>(data.size()));
    }
    check(MXPredCreate(symbol_json.c_str(), params.data(),
                       static_cast<int>(params.size()), dev_type,
                       dev_id,
                       static_cast<mx_uint>(keys.size()), keys.data(),
                       indptr.data(), data.data(), &handle_),
          "MXPredCreate");
  }

  /* Load from exported files: prefix-symbol.json + prefix-0000.params
   * (HybridBlock.export / Module.save_checkpoint layout). */
  static Predictor FromFiles(
      const std::string &symbol_file, const std::string &param_file,
      const std::map<std::string, Shape> &input_shapes,
      int dev_type = 1, int dev_id = 0) {
    return Predictor(read_file(symbol_file), read_file(param_file),
                     input_shapes, dev_type, dev_id);
  }

  ~Predictor() {
    if (handle_ != nullptr) MXPredFree(handle_);
  }

  Predictor(Predictor &&other) noexcept : handle_(other.handle_) {
    other.handle_ = nullptr;
  }
  Predictor &operator=(Predictor &&other) noexcept {
    if (this != &other) {
      if (handle_ != nullptr) MXPredFree(handle_);
      handle_ = other.handle_;
      other.handle_ = nullptr;
    }
    return *this;
  }
  Predictor(const Predictor &) = delete;
  Predictor &operator=(const Predictor &) = delete;

  void SetInput(const std::string &key,
                const std::vector<mx_float> &values) {
    check(MXPredSetInput(handle_, key.c_str(), values.data(),
                         static_cast<mx_uint>(values.size())),
          "MXPredSetInput");
  }

  void Forward() { check(MXPredForward(handle_), "MXPredForward"); }

  Shape GetOutputShape(mx_uint index = 0) const {
    mx_uint *shape = nullptr;
    mx_uint ndim = 0;
    check(MXPredGetOutputShape(handle_, index, &shape, &ndim),
          "MXPredGetOutputShape");
    return Shape(shape, shape + ndim);
  }

  std::vector<mx_float> GetOutput(mx_uint index = 0) const {
    Shape shape = GetOutputShape(index);
    std::size_t size = std::accumulate(shape.begin(), shape.end(),
                                       std::size_t{1},
                                       std::multiplies<std::size_t>());
    std::vector<mx_float> out(size);
    check(MXPredGetOutput(handle_, index, out.data(),
                          static_cast<mx_uint>(size)),
          "MXPredGetOutput");
    return out;
  }

  /* New predictor for other input shapes, sharing weights
   * (MXPredReshape). */
  Predictor Reshape(
      const std::map<std::string, Shape> &input_shapes) const {
    std::vector<const char *> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> data;
    for (const auto &kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      data.insert(data.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<mx_uint>(data.size()));
    }
    PredictorHandle out = nullptr;
    check(MXPredReshape(static_cast<mx_uint>(keys.size()), keys.data(),
                        indptr.data(), data.data(), handle_, &out),
          "MXPredReshape");
    return Predictor(out);
  }

 private:
  explicit Predictor(PredictorHandle h) : handle_(h) {}
  PredictorHandle handle_ = nullptr;
};

}  // namespace mxtpu

#endif  // MXTPU_CPP_PREDICTOR_HPP_
