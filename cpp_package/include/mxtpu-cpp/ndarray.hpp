/*
 * C++ training frontend over the training-tier C ABI (the role of the
 * reference's cpp-package† NDArray/Operator surface): RAII NDArray,
 * imperative operator invocation over the full registry, save/load.
 *
 * Header-only; link with -lmxtpu_ndarray (build: `make -C core
 * ndarray`).  Throws mxtpu::NDError on any ABI failure, carrying
 * MXNDGetLastError().
 */
#ifndef MXTPU_CPP_NDARRAY_HPP_
#define MXTPU_CPP_NDARRAY_HPP_

#include <cstddef>
#include <map>
#include <memory>
#include <numeric>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "../../../core/c_api_ndarray.h"

namespace mxtpu {
namespace nd {

class NDError : public std::runtime_error {
 public:
  explicit NDError(const std::string &what)
      : std::runtime_error(what) {}
};

inline void ndcheck(int rc, const char *call) {
  if (rc != 0) {
    throw NDError(std::string(call) + ": " + MXNDGetLastError());
  }
}

/* RAII float32 NDArray handle (the reference cpp-package NDArray,
 * scoped to the training tier). */
class NDArray {
 public:
  NDArray() = default;

  /* zeros of the given shape */
  explicit NDArray(const std::vector<mx_uint> &shape) {
    NDArrayHandle h = nullptr;
    ndcheck(MXNDArrayCreate(shape.data(),
                            static_cast<mx_uint>(shape.size()),
                            1, 0, 0, /*dtype=f32*/ 0, &h),
            "MXNDArrayCreate");
    reset(h);
  }

  NDArray(const std::vector<mx_uint> &shape,
          const std::vector<float> &data)
      : NDArray(shape) {
    copy_from(data);
  }

  static NDArray adopt(NDArrayHandle h) {
    NDArray a;
    a.reset(h);
    return a;
  }

  NDArrayHandle get() const { return h_ ? h_.get() : nullptr; }
  explicit operator bool() const { return static_cast<bool>(h_); }

  std::vector<mx_uint> shape() const {
    mx_uint ndim = 0;
    const mx_uint *data = nullptr;
    ndcheck(MXNDArrayGetShape(get(), &ndim, &data),
            "MXNDArrayGetShape");
    return std::vector<mx_uint>(data, data + ndim);
  }

  std::size_t size() const {
    auto s = shape();
    return std::accumulate(s.begin(), s.end(),
                           static_cast<std::size_t>(1),
                           std::multiplies<std::size_t>());
  }

  void copy_from(const std::vector<float> &data) {
    ndcheck(MXNDArraySyncCopyFromCPU(get(), data.data(), data.size()),
            "MXNDArraySyncCopyFromCPU");
  }

  std::vector<float> to_vector() const {
    std::vector<float> out(size());
    ndcheck(MXNDArraySyncCopyToCPU(get(), out.data(), out.size()),
            "MXNDArraySyncCopyToCPU");
    return out;
  }

  float scalar() const {
    auto v = to_vector();
    if (v.empty()) throw NDError("scalar() on empty array");
    return v[0];
  }

 private:
  void reset(NDArrayHandle h) {
    h_ = std::shared_ptr<void>(h, [](NDArrayHandle p) {
      if (p != nullptr) MXNDArrayFree(p);
    });
  }
  std::shared_ptr<void> h_;
};

/* Imperative operator invocation over the registry (the reference's
 * generated op.h, collapsed to one variadic call). */
inline std::vector<NDArray> invoke(
    const std::string &op_name, const std::vector<NDArray> &inputs,
    const std::map<std::string, std::string> &params = {}) {
  OpHandle op = nullptr;
  ndcheck(NNGetOpHandle(op_name.c_str(), &op), "NNGetOpHandle");
  std::vector<NDArrayHandle> in;
  in.reserve(inputs.size());
  for (const auto &a : inputs) in.push_back(a.get());
  std::vector<const char *> keys, vals;
  for (const auto &kv : params) {
    keys.push_back(kv.first.c_str());
    vals.push_back(kv.second.c_str());
  }
  int n_out = 0;
  NDArrayHandle *outs = nullptr;
  ndcheck(MXImperativeInvoke(op, static_cast<int>(in.size()),
                             in.data(), &n_out, &outs,
                             static_cast<int>(keys.size()),
                             keys.data(), vals.data()),
          "MXImperativeInvoke");
  std::vector<NDArray> result;
  result.reserve(n_out);
  for (int i = 0; i < n_out; ++i)
    result.push_back(NDArray::adopt(outs[i]));
  return result;
}

inline void save(const std::string &fname,
                 const std::vector<NDArray> &arrays,
                 const std::vector<std::string> &names = {}) {
  if (!names.empty() && names.size() != arrays.size()) {
    throw NDError("save(): names/arrays size mismatch ("
                  + std::to_string(names.size()) + " vs "
                  + std::to_string(arrays.size()) + ")");
  }
  std::vector<NDArrayHandle> hs;
  hs.reserve(arrays.size());
  for (const auto &a : arrays) hs.push_back(a.get());
  std::vector<const char *> keys;
  for (const auto &n : names) keys.push_back(n.c_str());
  ndcheck(MXNDArraySave(fname.c_str(),
                        static_cast<mx_uint>(hs.size()), hs.data(),
                        names.empty() ? nullptr : keys.data()),
          "MXNDArraySave");
}

inline std::pair<std::vector<NDArray>, std::vector<std::string>>
load(const std::string &fname) {
  mx_uint n_arr = 0, n_names = 0;
  NDArrayHandle *arrs = nullptr;
  const char **names = nullptr;
  ndcheck(MXNDArrayLoad(fname.c_str(), &n_arr, &arrs, &n_names,
                        &names),
          "MXNDArrayLoad");
  std::vector<NDArray> out;
  out.reserve(n_arr);
  for (mx_uint i = 0; i < n_arr; ++i)
    out.push_back(NDArray::adopt(arrs[i]));
  std::vector<std::string> nm;
  nm.reserve(n_names);
  for (mx_uint i = 0; i < n_names; ++i) nm.emplace_back(names[i]);
  return {std::move(out), std::move(nm)};
}

}  // namespace nd
}  // namespace mxtpu

#endif  /* MXTPU_CPP_NDARRAY_HPP_ */
