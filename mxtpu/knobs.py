"""Central registry of every ``MXTPU_*`` environment knob (ISSUE 5).

One declaration per knob — name, type, default, one-line doc — and one
accessor, :func:`get`, that every call site in ``mxtpu/``, ``tools/``
and ``bench.py`` goes through.  The registry is the single source of
truth three consumers share:

* runtime reads (:func:`get` — live ``os.environ`` lookup, typed,
  with the reference's ``MXNET_*`` spelling accepted as a fallback
  exactly like ``base.get_env`` always did);
* the README knob table (:func:`readme_table` generates it;
  ``python -m tools.mxlint --fix-readme`` writes it between the
  ``<!-- mxlint:knob-table -->`` markers, and the lint's
  ``knob-readme-drift`` check fails when it goes stale);
* ``tools/mxlint``'s ``knob-unregistered`` / ``knob-raw-env`` rules —
  reading an ``MXTPU_*`` name that is not declared here, or reading
  one through raw ``os.environ`` instead of :func:`get`, is a lint
  violation.

This module must stay importable WITHOUT jax and WITHOUT the mxtpu
package (tools/mxlint loads it by file path so linting never pays a
jax import); keep it free of framework imports.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, NamedTuple, Optional

try:  # normal package import
    from .base import MXNetError as _Err
except ImportError:  # standalone import by path (tools/mxlint)
    _Err = RuntimeError  # type: ignore[assignment,misc]

__all__ = ["Knob", "register", "get", "registered", "readme_table"]

_TRUTHY = {"1", "true", "yes", "on"}
_FALSY = {"0", "false", "no", "off", ""}


class Knob(NamedTuple):
    name: str
    default: Any
    kind: str          # "bool" | "int" | "float" | "str"
    doc: str
    group: str         # README table grouping


_REGISTRY: Dict[str, Knob] = {}
_MISSING = object()


def register(name: str, default: Any, kind: str = "str", doc: str = "",
             group: str = "misc") -> Knob:
    if kind not in ("bool", "int", "float", "str"):
        raise _Err(f"knob {name}: unknown kind {kind!r}")
    if not name.startswith("MXTPU_"):
        raise _Err(f"knob {name!r} must be MXTPU_-prefixed")
    if name in _REGISTRY:
        raise _Err(f"knob {name} registered twice")
    knob = Knob(name, default, kind, doc, group)
    _REGISTRY[name] = knob
    return knob


def _coerce(knob: Knob, raw: str) -> Any:
    if knob.kind == "bool":
        low = raw.strip().lower()
        if low in _TRUTHY:
            return True
        if low in _FALSY:
            return False
        raise _Err(f"invalid boolean value {knob.name}={raw!r}")
    if knob.kind == "int":
        return int(raw)
    if knob.kind == "float":
        return float(raw)
    return raw


def get(name: str, default: Any = _MISSING) -> Any:
    """Typed live read of a registered knob.  The environment always
    wins; otherwise ``default`` (when given) overrides the registered
    default.  ``MXNET_<suffix>`` is consulted as a fallback spelling so
    reference-era scripts keep working."""
    knob = _REGISTRY.get(name)
    if knob is None:
        raise _Err(
            f"unregistered knob {name!r} — declare it in mxtpu/knobs.py "
            f"(tools/mxlint enforces this)")
    raw = os.environ.get(name)
    if raw is None:
        raw = os.environ.get("MXNET_" + name[len("MXTPU_"):])
    if raw is None:
        return knob.default if default is _MISSING else default
    return _coerce(knob, raw)


def registered() -> Dict[str, Knob]:
    return dict(_REGISTRY)


# ----------------------------------------------------------------------
# The registry.  Every MXTPU_* name read anywhere in the tree (and the
# coordination names tools/launch.py exports to workers) is declared
# here; keep defaults in sync with the consuming module's docs.
# NOTE: first argument must stay a string literal — tools/mxlint
# cross-references these declarations.
# ----------------------------------------------------------------------

# -- performance kill switches (each =0 restores the pre-optimization
#    behaviour exactly; README "Performance kill switches & knobs") ----
register("MXTPU_ZERO", "", "str",
         "ZeRO-1 sharded optimizer states (reduce-scatter/all-gather). "
         "Auto: on for single-process dp>1 meshes; `0` reverts to "
         "replicated states + gradient all-reduce.", "kill-switch")
register("MXTPU_BATCHED_OPT", True, "bool",
         "(shape, dtype)-bucketed stacked optimizer updates; `0` "
         "reverts to one update chain per parameter (ignored under "
         "ZeRO-1, whose exchange is inherently bucketed).",
         "kill-switch")
register("MXTPU_FUSED_LN_EPILOGUE", True, "bool",
         "Fused bias+dropout+add+LayerNorm Pallas epilogue; `0` "
         "reverts to the unfused lax composite.", "kill-switch")
register("MXTPU_FUSED_BN", False, "bool",
         "Opt-in one-HBM-pass Pallas BatchNorm(Add)Relu kernel; the "
         "default composite keeps XLA-fused epilogues (BASELINE.md "
         "\"Fused-BN verdict\").", "kill-switch")
register("MXTPU_FLASH_BWD", "auto", "str",
         "Flash-attention backward: `auto` (length-based pick), "
         "`pallas` (blockwise kernel), `ref` (recompute composite).",
         "kill-switch")
register("MXTPU_PALLAS", "auto", "str",
         "Pallas kernel dispatch: `auto` (on TPU), `interpret` "
         "(interpreter mode for CPU testing), `0` (disable).",
         "kill-switch")
register("MXTPU_EXECUTOR_JIT", True, "bool",
         "Symbolic Executor compiles the bound graph under a "
         "shape-keyed jax.jit; `0` falls back to eager per-op "
         "interpretation.", "kill-switch")
register("MXTPU_AMP", "", "str",
         "Policy-driven bf16 autocast (mxtpu.amp, consumes "
         "contracts/amp_policy.json): `0` is the kill switch — forces "
         "AMP off everywhere and the trained/served programs are "
         "bit-identical to pre-AMP; `1` force-enables it for every "
         "TrainStep/ModelRunner; unset defers to the per-call "
         "`amp=` argument.", "kill-switch")
register("MXTPU_AMP_LOSS_SCALE", 65536.0, "float",
         "Initial dynamic loss scale for AMP training (power of two; "
         "grows x2 per stable window, halves on non-finite grads).  "
         "`0` disables loss scaling entirely (pure autocast, no "
         "skipped-step logic).", "kill-switch")
register("MXTPU_AMP_SCALE_WINDOW", 2000, "int",
         "Consecutive finite-grad steps before the AMP loss scale "
         "doubles (the grow window; backoff on a non-finite step is "
         "immediate).", "kill-switch")
register("MXTPU_QUANT", "", "str",
         "Policy-driven INT8 post-training quantization (mxtpu.quant, "
         "consumes contracts/quant_policy.json): `0` is the kill "
         "switch — forces quantization off everywhere and the served "
         "programs are bit-identical to the unquantized path; `1` "
         "force-enables it for every ModelRunner; unset defers to the "
         "per-call `quant=` argument.", "kill-switch")
register("MXTPU_QUANT_CALIB", "entropy", "str",
         "Calibration collector for mxtpu.quant activation "
         "thresholds: `entropy` (KL-minimizing threshold, the "
         "reference's TensorRT-style search) or `minmax` (abs-max).",
         "kill-switch")
register("MXTPU_QUANT_CALIB_BATCHES", 10, "int",
         "Maximum representative batches a ModelRunner.calibrate() "
         "pass consumes when the caller does not say otherwise.",
         "kill-switch")

# -- guards (this PR) --------------------------------------------------
register("MXTPU_GUARDS", "", "str",
         "Runtime guard rails (mxtpu.guards): `1` warn on recompile "
         "churn and pin TrainStep/ModelRunner dispatch transfer-clean "
         "via jax.transfer_guard; `2` raise instead of warn; "
         "unset/`0` = off with zero overhead.", "guards")
register("MXTPU_GUARDS_CHURN_LIMIT", 10, "int",
         "Compiles tolerated per guarded jit entry before the "
         "recompile-churn guard fires (ModelRunner adds its bucket-"
         "ladder size).", "guards")
register("MXTPU_RACE", False, "bool",
         "Rerun the test suite under the mxrace lockset sanitizer "
         "(mxtpu/analysis/lockset.py): threading.Lock/RLock are "
         "traced and the serving/obs classes are instrumented per "
         "their `# guarded-by:` annotations — empty candidate "
         "locksets, guarded-by violations, and runtime lock-order "
         "inversions fail the test with the access sites named.  "
         "Test-time only (`MXTPU_RACE=1 pytest tests/`); unset = "
         "zero overhead, the sanitizer is never imported.  The "
         "static half lives in `python -m tools.mxrace`.", "guards")

register("MXTPU_HLO_AUDIT", "", "str",
         "Static HLO audit (mxtpu.analysis) of every program "
         "TrainStep / serving ModelRunner compiles: `1` warn when "
         "the compiled step contains host transfers, f64 creep, or "
         "custom calls bracketed by transpose/copy; `2` raise; "
         "unset/`0` = off with zero overhead.  Contract checks "
         "against committed lockfiles live in `python -m "
         "tools.hlocheck`.", "guards")

register("MXTPU_PREC_AUDIT", "", "str",
         "Precision audit (mxtpu.analysis.dtypeflow) of every program "
         "TrainStep / serving ModelRunner compiles: `1` warn when the "
         "compiled step contains bf16 accumulating reductions, "
         "matmuls missing preferred_element_type=f32, or f64 creep; "
         "`2` raise; unset/`0` = off with zero overhead.  Ledger "
         "checks against contracts/prec/ live in `python -m "
         "tools.mxprec`.", "guards")

register("MXTPU_MEM_AUDIT", "", "str",
         "Memory audit (mxtpu.analysis.memflow) of every program "
         "TrainStep / serving ModelRunner / GenerateRunner compiles: "
         "`1` warn when the program's peak HBM per device (temp + "
         "argument bytes) exceeds the device-class budget; `2` "
         "raise; unset/`0` = off with zero overhead.  Ledger checks "
         "against contracts/mem/ live in `python -m tools.mxmem`.",
         "guards")

register("MXTPU_MEM_BUDGET", 0, "int",
         "Per-device HBM byte budget the MXTPU_MEM_AUDIT runtime "
         "check enforces.  `0` (default) = use the default device "
         "class from contracts/mem/budgets.json; any other value "
         "overrides the limit in bytes (tests and constrained "
         "deploys).", "guards")

# -- observability (mxtpu.obs) -----------------------------------------
register("MXTPU_OBS", True, "bool",
         "Unified observability layer (mxtpu.obs): metrics registry, "
         "per-request trace ids, flight recorders.  `0` = off: the "
         "factories hand back shared no-op instruments, so hot paths "
         "pay nothing (asserted by `obs.self_check()` at bench "
         "import).", "obs")
register("MXTPU_OBS_FLIGHT_CAPACITY", 256, "int",
         "Flight-recorder ring size — structured events kept per "
         "worker (oldest evicted first).", "obs")
register("MXTPU_OBS_DUMP_ON_ERROR", "", "str",
         "Extra flight-recorder postmortems: unset = dump only on "
         "worker death; `1` also dumps every recorder when a fleet "
         "request fails terminally; a directory path additionally "
         "writes each postmortem there as JSON.", "obs")
register("MXTPU_OBS_SAMPLE_PERIOD_US", 1000000, "int",
         "Time-series sampler period (obs.sampler): how often "
         "maybe_sample() snapshots the metrics registry into the "
         "bounded per-series rings that back windowed rates, "
         "p50/p95/p99 and SLO burn windows.", "obs")
register("MXTPU_OBS_HTTP_PORT", -1, "int",
         "Debug HTTP server (obs.debug_server): /metrics /varz "
         "/healthz /statusz /tracez on loopback.  -1 = never serve "
         "(default); 0 = ephemeral port (tests read it back from "
         "server.port); >0 = fixed port.", "obs")
register("MXTPU_SLO_CLASSES", "", "str",
         "Declarative latency SLOs, comma-separated "
         "`name:endpoint:target_ms:objective[:percentile]` (e.g. "
         "`interactive:fleet:50:0.95`), parsed by "
         "obs.parse_slo_classes into LatencySLO objects next to the "
         "built-in availability SLO.", "obs")

# -- numerics / engine -------------------------------------------------
register("MXTPU_ENGINE_TYPE", "ThreadedEnginePerDevice", "str",
         "`NaiveEngine` forces synchronous execution for debugging "
         "(reference MXNET_ENGINE_TYPE).", "engine")
register("MXTPU_ENGINE_SYNC", False, "bool",
         "`1` forces a blocking wait after every engine op (pairs "
         "with MXTPU_ENGINE_TYPE=NaiveEngine).", "engine")
register("MXTPU_EXEC_BULK_EXEC_TRAIN", True, "bool",
         "Allow bulked (scanned) multi-step training execution.",
         "engine")
register("MXTPU_DEFAULT_DTYPE", "float32", "str",
         "Default NDArray dtype.", "engine")
register("MXTPU_BN_VMEM_CAP_MB", 120, "int",
         "Scoped-VMEM budget for the Pallas BN kernel's channel-block "
         "selection.", "engine")
register("MXTPU_BN_LAYOUT", "auto", "str",
         "Fused-BN kernel operand layout: `auto` picks channels-minor "
         "(C on lanes, one (rows, C) block) when the whole stage fits "
         "the VMEM cap, else channels-major; `cm`/`major` force a "
         "variant.", "engine")
register("MXTPU_KVSTORE_BIGARRAY_BOUND", 1048576, "int",
         "Arrays >= this many elements use the big-array kvstore "
         "path.", "engine")
register("MXTPU_SAVE_FORMAT", "", "str",
         "Checkpoint container: `legacy` (reference dmlc stream) or "
         "`mxtpu` (MXTPU01 npz); unset picks by file extension.",
         "engine")
register("MXTPU_PROFILER_AUTOSTART", False, "bool",
         "Start the chrome-trace profiler at import.", "engine")

# -- serving -----------------------------------------------------------
register("MXTPU_SERVING_MAX_BATCH", 32, "int",
         "ModelRunner bucket-ladder cap (pow2 rungs up to this).",
         "serving")
register("MXTPU_SERVING_MAX_DELAY_US", 2000.0, "float",
         "DynamicBatcher assembly window in microseconds.", "serving")
register("MXTPU_SERVING_MAX_QUEUE", 0, "int",
         "Bound on queued requests before ServerBusy shedding "
         "(0/unset = 8x max batch).", "serving")
register("MXTPU_SERVING_DONATE", True, "bool",
         "Donate padded input buffers to the serving executable on "
         "accelerator backends.", "serving")
register("MXTPU_GEN_MAX_LANES", 8, "int",
         "KV-cache lanes per GenerateRunner: the continuous-batching "
         "decode width (one in-flight generation per lane).",
         "serving")
register("MXTPU_GEN_MAX_TOKENS", 64, "int",
         "Default per-request generation cap when submit passes no "
         "max_tokens.", "serving")
register("MXTPU_GEN_STREAM", True, "bool",
         "Stream tokens through the incremental result channel as "
         "they decode (off = deliver only the final sequence).",
         "serving")

# -- serving fleet (router / health / retry) ---------------------------
register("MXTPU_FLEET_LIVENESS_S", 2.0, "float",
         "Liveness deadline on a dispatched batch: in-flight past "
         "this is SUSPECT, past 2x is a hang (DEAD).", "fleet")
register("MXTPU_FLEET_DEAD_AFTER", 3, "int",
         "Consecutive canary failures on a SUSPECT worker before it "
         "is declared DEAD.", "fleet")
register("MXTPU_FLEET_CANARY_INTERVAL_S", 5.0, "float",
         "Seconds between canary inferences per worker (0 disables "
         "active health checks).", "fleet")
register("MXTPU_FLEET_CANARY_TIMEOUT_S", 1.0, "float",
         "Deadline on each canary inference.", "fleet")
register("MXTPU_FLEET_RETRY_MAX", 3, "int",
         "Router-level re-dispatch cap per request (retriable "
         "failures only).", "fleet")
register("MXTPU_FLEET_BACKOFF_BASE_US", 1000, "int",
         "Retry backoff base: min(cap, base * 2^(n-1)) + jitter.",
         "fleet")
register("MXTPU_FLEET_BACKOFF_CAP_US", 64000, "int",
         "Retry backoff cap in microseconds.", "fleet")
register("MXTPU_FLEET_JITTER", 0.2, "float",
         "Backoff jitter fraction (deterministic seeded RNG).",
         "fleet")
register("MXTPU_FLEET_HEDGE_AFTER_US", 0, "int",
         "Hedge a still-in-flight request onto a second worker after "
         "this many microseconds (0 disables hedging).", "fleet")
register("MXTPU_FLEET_MAX_PENDING", 1024, "int",
         "Bound on the router's parked-retry buffer before "
         "ServerBusy shedding.", "fleet")
register("MXTPU_FLEET_TICK_S", 0.005, "float",
         "Router ticker period in threaded mode.", "fleet")

# -- fleet control plane (autoscaler / admission / priority) -----------
register("MXTPU_FLEET_AUTOSCALE_MIN", 1, "int",
         "Autoscaler floor: never drain below this many healthy "
         "workers.", "controlplane")
register("MXTPU_FLEET_AUTOSCALE_MAX", 4, "int",
         "Autoscaler ceiling on live (non-dead) workers.",
         "controlplane")
register("MXTPU_FLEET_AUTOSCALE_UP_DEPTH", 4.0, "float",
         "Scale-up band: mean outstanding requests per healthy worker "
         "(router backlog included) above this counts as an overload "
         "tick.", "controlplane")
register("MXTPU_FLEET_AUTOSCALE_DOWN_DEPTH", 0.5, "float",
         "Scale-down band: mean outstanding per healthy worker below "
         "this (with an empty router backlog) counts as an underload "
         "tick.", "controlplane")
register("MXTPU_FLEET_AUTOSCALE_UP_ETA_US", 0.0, "float",
         "Additional scale-up trigger: predicted queue ETA "
         "(ServingStats.queue_eta_us) above this many microseconds "
         "counts as overload (0 disables the ETA signal).",
         "controlplane")
register("MXTPU_FLEET_AUTOSCALE_BURN", False, "bool",
         "Let an attached SLO engine's firing burn-rate alerts count "
         "as autoscaler overload ticks (scale up while the error "
         "budget is burning even if queue depth looks fine).  Off by "
         "default: scaling behaviour is bit-identical to the "
         "pre-SLO autoscaler unless explicitly enabled.",
         "controlplane")
register("MXTPU_FLEET_AUTOSCALE_BREACH_TICKS", 3, "int",
         "Hysteresis: consecutive over/under-band evaluations before "
         "the autoscaler acts (bands reset each action).",
         "controlplane")
register("MXTPU_FLEET_AUTOSCALE_COOLDOWN_S", 5.0, "float",
         "Minimum seconds between autoscaler actions (either "
         "direction).", "controlplane")
register("MXTPU_FLEET_ADMISSION", False, "bool",
         "Predictive admission control: shed a deadline-carrying "
         "request at submit with ServerBusy (+retry_after_us) when "
         "the class-aware queue ETA says it cannot finish in time.",
         "controlplane")
register("MXTPU_FLEET_ADMISSION_MARGIN", 1.0, "float",
         "Admission safety factor: shed when margin x predicted ETA "
         "exceeds the deadline budget (>1 sheds earlier, <1 gambles).",
         "controlplane")
register("MXTPU_FLEET_CLASSES", "", "str",
         "Priority/fairness classes as `name:weight[:quota],...` "
         "(e.g. `gold:8,bulk:1:64`): weight sets the weighted-round-"
         "robin dispatch share, quota bounds in-system requests per "
         "class.  Unset = one `default` class.", "controlplane")

# -- persistent compile cache (mxtpu/cache.py) -------------------------
register("MXTPU_CACHE", True, "bool",
         "Master switch for the persistent AOT executable cache: "
         "`0` = always compile, never touch disk.  The disk layer is "
         "also inert while MXTPU_CACHE_DIR is unset.", "cache")
register("MXTPU_CACHE_DIR", "", "str",
         "Root directory of the on-disk compiled-executable cache "
         "(crash-safe writes, checksum-verified loads).  ModelRunner "
         "buckets and AOT TrainStep programs load-or-compile through "
         "it; unset disables persistence.", "cache")
register("MXTPU_CACHE_SALT", "", "str",
         "Extra cache-key component: bump it to invalidate every "
         "cached executable (rollout epoch, config generation).",
         "cache")

# -- bench / tools -----------------------------------------------------
register("MXTPU_BENCH_MODEL", "all", "str",
         "bench.py workload selector (lenet|resnet50|bert|transformer|"
         "moe_ffn|ssd|bert_zero|serving_bert|... or `all`).", "bench")
register("MXTPU_BENCH_BATCH", 256, "int",
         "bench.py ResNet-50 global batch size.", "bench")
register("MXTPU_BENCH_DTYPE", "bfloat16", "str",
         "bench.py compute dtype (empty = model default).", "bench")
register("MXTPU_BENCH_WALL_BUDGET", 780.0, "float",
         "bench.py global wall-clock budget in seconds; over-budget "
         "rows are recorded as skipped.", "bench")
register("MXTPU_BENCH_ROW_BUDGET", 90.0, "float",
         "bench.py conservative per-row wall estimate used by the "
         "budget gate.", "bench")
register("MXTPU_PROFILE_BERT_MODEL", "large", "str",
         "tools/profile_bert.py model tier (tiny|base|large).",
         "bench")
register("MXTPU_PROBE_CONV", True, "bool",
         "tools/probe_bn_fusion.py: `0` skips the in-context conv "
         "probe.", "bench")

# -- distributed launch (written by tools/launch.py for workers) -------
register("MXTPU_COORDINATOR", "", "str",
         "Coordinator address exported to launched worker processes.",
         "launch")
register("MXTPU_NUM_PROCESSES", 1, "int",
         "World size exported to launched worker processes.", "launch")
register("MXTPU_PROCESS_ID", 0, "int",
         "Process rank exported to launched worker processes.",
         "launch")

# -- test harness ------------------------------------------------------
register("MXTPU_TEST_PLATFORM", "cpu", "str",
         "Test platform: `cpu` (virtual 8-device mesh) or `tpu`.",
         "test")
register("MXTPU_TEST_SEED", 42, "int",
         "Deterministic per-test seed (reference MXNET_TEST_SEED).",
         "test")
register("MXTPU_TEST_SLOW", False, "bool",
         "Enable heavy model-zoo test variants.", "test")


# ----------------------------------------------------------------------
# README generation
# ----------------------------------------------------------------------
_GROUP_TITLES = [
    ("kill-switch", "Performance kill switches"),
    ("guards", "Runtime guards"),
    ("obs", "Observability"),
    ("engine", "Engine / numerics"),
    ("serving", "Serving"),
    ("fleet", "Serving fleet"),
    ("controlplane", "Fleet control plane"),
    ("cache", "Persistent compile cache"),
    ("bench", "Bench & profiling tools"),
    ("launch", "Distributed launch"),
    ("test", "Test harness"),
]

TABLE_BEGIN = "<!-- mxlint:knob-table:begin (generated by " \
    "`python -m tools.mxlint --fix-readme`; do not edit by hand) -->"
TABLE_END = "<!-- mxlint:knob-table:end -->"


def _fmt_default(knob: Knob) -> str:
    if knob.kind == "bool":
        return "on" if knob.default else "off"
    if knob.default == "":
        return "unset"
    return f"`{knob.default}`"


def readme_table() -> str:
    """The README knob table, generated from the registry (checked
    for drift by tools/mxlint's knob-readme-drift rule)."""
    out: List[str] = [TABLE_BEGIN, ""]
    for group, title in _GROUP_TITLES:
        knobs = [k for k in _REGISTRY.values() if k.group == group]
        if not knobs:
            continue
        out.append(f"**{title}**")
        out.append("")
        out.append("| knob | type | default | effect |")
        out.append("|---|---|---|---|")
        for k in sorted(knobs, key=lambda k: k.name):
            doc = " ".join(k.doc.split())
            out.append(f"| `{k.name}` | {k.kind} | {_fmt_default(k)} "
                       f"| {doc} |")
        out.append("")
    out.append(f"({len(_REGISTRY)} knobs registered in "
               f"`mxtpu/knobs.py`.)")
    out.append("")
    out.append(TABLE_END)
    return "\n".join(out)
