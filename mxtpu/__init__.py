"""mxtpu — a TPU-native deep-learning framework with the capabilities of
Apache MXNet v1.x (reference: abhinavs95/incubator-mxnet).

Not a port: the compute path is jax/XLA (ops are HLO lowering rules, the
``hybridize()`` JIT traces into single XLA executables, distribution is
SPMD sharding with XLA collectives over ICI/DCN), with Pallas kernels for
fused hot ops.  See SURVEY.md for the reference structural analysis and
the layer-by-layer mapping.

Top-level namespace parity with ``import mxnet as mx``:
  mx.nd, mx.sym, mx.autograd, mx.gluon, mx.context/cpu/gpu/tpu, mx.random,
  mx.optimizer, mx.metric, mx.init(ializer), mx.io, mx.kvstore, mx.mod,
  mx.profiler, mx.test_utils …
"""
__version__ = "0.1.0"

from . import base
from .base import MXNetError
from .context import (Context, cpu, gpu, tpu, cpu_pinned, cpu_shared,
                      current_context, num_gpus, num_tpus)
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from .ndarray.random import seed  # noqa: F401  (mx.random.seed alias)

# Subsystems are imported lazily where heavy; these are light.
from . import ops


def __getattr__(name):
    # Lazy imports so `import mxtpu` stays fast and circular imports are
    # avoided while the package grows.
    import importlib
    lazy = {
        "sym": ".symbol", "symbol": ".symbol",
        "gluon": ".gluon",
        "optimizer": ".optimizer",
        "metric": ".metric",
        "init": ".initializer", "initializer": ".initializer",
        "io": ".io",
        "image": ".image",
        "kvstore": ".kvstore", "kv": ".kvstore",
        "mod": ".module", "module": ".module",
        "profiler": ".profiler",
        "test_utils": ".test_utils",
        "recordio": ".recordio",
        "callback": ".callback",
        "monitor": ".monitor",
        "visualization": ".visualization", "viz": ".visualization",
        "lr_scheduler": ".optimizer.lr_scheduler",
        "executor": ".executor",
        "engine": ".engine",
        "model": ".model",
        "parallel": ".parallel",
        "kernels": ".kernels",
        "models": ".models",
        "serving": ".serving",
        "operator": ".operator",
        "rtc": ".rtc",
        "contrib": ".contrib",
        "util": ".utils",
        "utils": ".utils",
        "rnn": ".rnn",
    }
    if name in lazy:
        mod = importlib.import_module(lazy[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxtpu' has no attribute {name!r}")
