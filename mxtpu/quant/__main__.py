"""``python -m mxtpu.quant --self-check`` — the ci_static quant stage.

Probes the contracts the INT8 pass rests on: the committed
``contracts/quant_policy.json`` parses and keeps its class invariants
(allow has the contractions, deny carries the transcendentals,
calibration evidence present), and a calibrate→quantize round trip on
a tiny two-layer net produces tagged s8×s8→s32 contractions with zero
dtype-flow hazards, deterministic scales, accuracy within tolerance of
the f32 reference, and no int8 leak outside the scope.
"""
from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m mxtpu.quant")
    parser.add_argument("--self-check", action="store_true",
                        help="probe policy parse + calibrate->quantize "
                             "round trip + scale bookkeeping")
    args = parser.parse_args(argv)
    if not args.self_check:
        parser.print_help()
        return 2
    # the round-trip lowers a program; stay off any attached
    # accelerator.  CLI-entry env pinning, before jax loads — not a
    # calibration-path impurity.
    os.environ.setdefault(  # mxlint: disable=retrace-impure-call
        "JAX_PLATFORMS", "cpu")
    from . import self_check
    return self_check(verbose=True)


if __name__ == "__main__":
    sys.exit(main())
