"""``mxtpu.quant`` — INT8 post-training quantization (calibrate →
policy → serve), the sibling of :mod:`mxtpu.amp` one dtype tier down.

Reference: ``src/operator/quantization/``† (quantize/dequantize/
requantize + quantized conv/fc) driven by ``python/mxnet/contrib/
quantization.py``†'s two calibration algorithms (minmax and
KL-entropy).  The reference rewrites the symbol graph into
``_contrib_quantized_*`` nodes; here the rewrite is a *trace-time
interposition* at the same eager/symbolic dispatch choke point AMP
uses (``ndarray._invoke_op_inner``), consuming a machine-derived
policy (``contracts/quant_policy.json``, written by ``python -m
tools.mxprec --quant``) instead of hand-curated op lists.

Two scopes share the interposition:

* :func:`calibrating` — run representative batches *eagerly* through
  the deployed graph; every candidate contraction's float input is
  observed by a collector (:class:`MinMaxCollector` or
  :class:`EntropyCollector`, the reference's two algorithms) under a
  deterministic per-dispatch key (``FullyConnected_3`` = the 4th
  candidate in topological dispatch order).  Deterministic given
  fixed batches: no RNG, no time — tools/mxlint's retrace rule scans
  this whole module for impure calls.
* :func:`quantize` — inside a trace, a candidate op whose key has a
  recorded activation threshold is replaced by the int8 form:
  quantize-on-entry (symmetric per-tensor activation scale, the
  calibrated |x| threshold), **per-channel weight scales computed
  in-graph** (abs-max over the non-output axes — weights are runtime
  inputs, so one compiled bucket serves every checkpoint), an
  **int8×int8 contraction accumulating in i32 via
  ``preferred_element_type=int32``**, and a float dequantize epilogue
  (+ float bias).  Between two adjacent quantized ops the epilogue
  and the next op's entry quantize are adjacent elementwise chains —
  XLA fuses them into the single rescale a hand-written requantize
  would be.  Anything outside the policy's allow class (or with no
  recorded scale) falls back to the bf16/f32 path untouched.

Every quantized contraction is emitted under
``jax.named_scope("q8_<key>")`` so its HLO metadata carries the scale
key; :mod:`mxtpu.analysis.dtypeflow` turns that into two machine
checks: an int8 contraction accumulating below i32 is an
``int8-accum-matmul`` hazard, and an int8 contraction with no ``q8_``
tag is a ``quant-missing-scale`` hazard (tag presence ⟺ a recorded
scale, because :func:`wrap_op` only tags ops it holds a threshold
for).  The committed ``contracts/prec/serving_bert_int8.json`` ledger
and ``contracts/serving_bert_int8.json`` hlocheck contract pin the
quantized serving ladder hazard-free with the s8×s8→s32 dot
signature inventoried.

Kill switch: ``MXTPU_QUANT=0`` forces quantization off everywhere and
the lowered programs are bit-identical to the unquantized path
(asserted by ``tests/test_quant.py``, the MXTPU_AMP=0 contract one
tier down).  ``python -m mxtpu.quant --self-check`` probes the policy
parse, a calibrate→quantize round trip on a tiny net (zero hazards,
correct scale bookkeeping) and the kill-switch precedence (wired as a
``tools/ci_static.py`` stage).
"""
from __future__ import annotations

import contextlib
import functools
import json
import os
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import knobs
from ..base import MXNetError

__all__ = [
    "POLICY_PATH", "load_policy", "policy_sets", "resolve",
    "calib_config", "make_collector", "MinMaxCollector",
    "EntropyCollector", "calibrating", "quantize", "active",
    "wrap_op", "QUANT_READY", "self_check",
]

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
POLICY_PATH = os.path.join(_REPO_ROOT, "contracts", "quant_policy.json")

_F32 = jnp.float32
_I8 = jnp.int8
_I32 = jnp.int32
_QMAX = 127.0  # symmetric int8: [-127, 127], -128 unused (reference)


# ----------------------------------------------------------------------
# policy file
# ----------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def load_policy(path: Optional[str] = None) -> Dict[str, Any]:
    """Parse ``contracts/quant_policy.json`` (cached)."""
    p = path or POLICY_PATH
    try:
        with open(p, "r", encoding="utf-8") as f:
            policy = json.load(f)
    except (OSError, ValueError) as e:
        raise MXNetError(
            f"mxtpu.quant: cannot load quant policy {p!r}: {e}")
    for key in ("allow", "deny", "calibration"):
        if not isinstance(policy.get(key), dict):
            raise MXNetError(
                f"mxtpu.quant: policy {p!r} missing section {key!r} — "
                f"regenerate with `python -m tools.mxprec --quant "
                f"--update`")
    return policy


@functools.lru_cache(maxsize=None)
def policy_sets(path: Optional[str] = None
                ) -> Tuple[FrozenSet[str], FrozenSet[str]]:
    """(allow, deny) opcode sets from the policy file."""
    policy = load_policy(path)
    return frozenset(policy["allow"]), frozenset(policy["deny"])


def resolve(flag: Optional[bool] = None) -> bool:
    """Resolve the effective quantization switch: ``MXTPU_QUANT=0``
    kills it everywhere, ``MXTPU_QUANT=1`` forces it on, otherwise the
    per-call ``quant=`` argument decides (default off) — the same
    precedence ladder as ``mxtpu.amp.resolve``."""
    env = str(knobs.get("MXTPU_QUANT")).strip().lower()
    if env in ("0", "off", "false", "no"):
        return False
    if flag is not None:
        return bool(flag)
    return env in ("1", "on", "true", "yes")


def calib_config() -> Tuple[str, int]:
    """(collector mode, max batches) for calibration runs."""
    mode = str(knobs.get("MXTPU_QUANT_CALIB")).strip().lower()
    if mode not in ("minmax", "entropy"):
        raise MXNetError(
            f"mxtpu.quant: MXTPU_QUANT_CALIB={mode!r} — use "
            f"`minmax` or `entropy`")
    batches = max(1, int(knobs.get("MXTPU_QUANT_CALIB_BATCHES")))
    return mode, batches


# ----------------------------------------------------------------------
# calibration collectors (the reference's two algorithms).  Both are
# pure functions of the observed values — byte-identical thresholds
# for identical batches; mxtpu/quant/ sits in mxlint's deterministic
# scope so an RNG or clock call here is a lint failure, not a review
# comment.
# ----------------------------------------------------------------------
def _round6(x: float) -> float:
    """6-significant-figure rounding: thresholds land in committed
    JSON (quant_policy.json evidence), so pin a byte-stable decimal
    form well above f32 noise."""
    return float(f"{float(x):.6g}")


def _observed_np(value):
    import numpy as np
    try:
        # mxlint: sync-point — calibration is an offline host pass
        return np.asarray(value, np.float32)
    except Exception as e:
        raise MXNetError(
            "mxtpu.quant: calibration observed a non-concrete value "
            "(tracer?) — run calibration batches eagerly, outside "
            f"jit: {e}")


class MinMaxCollector:
    """Per-key symmetric |x| threshold = running abs-max (the
    reference's ``calib_mode='naive'``)."""

    mode = "minmax"

    def __init__(self):
        self._absmax: Dict[str, float] = {}

    def observe(self, key: str, value) -> None:
        arr = _observed_np(value)
        m = float(abs(arr).max()) if arr.size else 0.0
        prev = self._absmax.get(key, 0.0)
        if m > prev:
            self._absmax[key] = m
        else:
            self._absmax.setdefault(key, prev)

    def thresholds(self) -> Dict[str, float]:
        return {k: _round6(max(v, 1e-6))
                for k, v in sorted(self._absmax.items())}


class EntropyCollector:
    """Per-key KL-minimizing |x| threshold over every observed batch
    (the reference's ``calib_mode='entropy'``, via
    :func:`mxtpu.contrib.quantization.optimal_threshold` — a
    deterministic histogram search, no sampling)."""

    mode = "entropy"

    def __init__(self, num_bins: int = 2001,
                 num_quantized_bins: int = 255):
        self._chunks: Dict[str, List] = {}
        self._num_bins = num_bins
        self._num_quantized_bins = num_quantized_bins

    def observe(self, key: str, value) -> None:
        self._chunks.setdefault(key, []).append(
            _observed_np(value).ravel())

    def thresholds(self) -> Dict[str, float]:
        import numpy as np
        from ..contrib.quantization import optimal_threshold
        out = {}
        for key in sorted(self._chunks):
            arr = np.concatenate(self._chunks[key])
            out[key] = _round6(max(optimal_threshold(
                arr, self._num_bins, self._num_quantized_bins), 1e-6))
        return out


def make_collector(mode: Optional[str] = None):
    """Collector for ``mode`` (default: the MXTPU_QUANT_CALIB knob)."""
    if mode is None:
        mode, _ = calib_config()
    if mode == "minmax":
        return MinMaxCollector()
    if mode == "entropy":
        return EntropyCollector()
    raise MXNetError(f"mxtpu.quant: unknown collector mode {mode!r}")


# ----------------------------------------------------------------------
# calibration / quantization scopes (trace-time module globals — the
# same zero-overhead-off shape as amp._ACTIVE: one attribute read on
# the off path of _invoke_op_inner).  The per-scope dispatch counter
# gives every candidate op a stable key; eager calibration and the
# traced quantized program both interpret the SAME symbol in the same
# topological order, so key <-> op instance is a bijection across the
# two passes.
# ----------------------------------------------------------------------
_ACTIVE = False
_MODE = None        # "calib" | "quant" while a scope is live
_COLLECT = None     # live collector (calib scope)
_SCALES = None      # {key: activation |x| threshold} (quant scope)
_COUNTER = 0        # candidate ops seen since scope entry


@contextlib.contextmanager
def calibrating(collector):
    """Scope under which candidate contractions dispatched through the
    nd op registry have their float data input OBSERVED (host-side)
    by ``collector`` instead of being rewritten.  Eager-only."""
    global _ACTIVE, _MODE, _COLLECT, _COUNTER
    prev = (_ACTIVE, _MODE, _COLLECT, _COUNTER)
    _ACTIVE, _MODE, _COLLECT, _COUNTER = True, "calib", collector, 0
    try:
        yield collector
    finally:
        _ACTIVE, _MODE, _COLLECT, _COUNTER = prev


@contextlib.contextmanager
def quantize(scales: Dict[str, Any], enabled: bool = True):
    """Scope under which candidate contractions with a recorded
    activation threshold run as int8×int8 GEMMs with i32
    accumulation.  ``scales`` maps dispatch keys to thresholds (float,
    or a ``{"threshold": ...}`` dict as stored in policy evidence)."""
    norm = {}
    for k, v in (scales or {}).items():
        t = v.get("threshold") if isinstance(v, dict) else v
        if t is not None and float(t) > 0.0:
            norm[k] = float(t)
    global _ACTIVE, _MODE, _SCALES, _COUNTER
    prev = (_ACTIVE, _MODE, _SCALES, _COUNTER)
    if enabled:
        _ACTIVE, _MODE, _SCALES, _COUNTER = True, "quant", norm, 0
    try:
        yield
    finally:
        _ACTIVE, _MODE, _SCALES, _COUNTER = prev


def active() -> bool:
    return _ACTIVE


# ----------------------------------------------------------------------
# quantization decision + int8 replacements
# ----------------------------------------------------------------------
# Contraction ops with an int8 serving form (the reference quantizes
# quantized_fully_connected / quantized_conv; attention batch_dots are
# activation×activation — no weight-side per-channel scale — and stay
# on the bf16/f32 path, like the reference's FP32 fallback ops).
QUANT_READY = frozenset({
    "FullyConnected", "fully_connected",
    "Convolution", "convolution", "Convolution_v1",
})

_DECISION_CACHE: Dict[Any, bool] = {}


def _param_key(resolved: Dict[str, Any]) -> str:
    try:
        return repr(sorted(resolved.items(), key=lambda kv: kv[0]))
    except Exception:
        return "<unkeyable>"


def _quant_decision(name: str, op, arrays, resolved) -> bool:
    """The policy drives the rewrite, exactly like amp._cast_decision:
    the op's function is abstractly traced and the decision is
    ``opcodes ⊆ allow`` — a deny-class transcendental anywhere inside
    vetoes the int8 form.  Cached per (op, avals, params)."""
    from .. import amp as _amp
    key = (name,
           tuple((tuple(a.shape), str(a.dtype)) for a in arrays),
           _param_key(resolved))
    hit = _DECISION_CACHE.get(key)
    if hit is not None:
        return hit
    allow, deny = policy_sets()
    structs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays]
    try:
        closed = jax.make_jaxpr(
            lambda *xs: op.fn(*xs, **resolved))(*structs)
        opcodes: set = set()
        _amp._walk_opcodes(closed.jaxpr, opcodes)
        decision = bool(opcodes) and opcodes <= allow
        assert not (opcodes & deny) or not decision
    except Exception:
        decision = False
    _DECISION_CACHE[key] = decision
    return decision


def _quantize_tensor(x, threshold: float):
    """f32 -> int8, symmetric per-tensor: round(x * 127/t) clipped to
    [-127, 127] (``detection_impl._quantize``'s math, inlined so XLA
    fuses it into the GEMM's prologue)."""
    scaled = x * jnp.float32(_QMAX / threshold)
    return jnp.clip(jnp.round(scaled), -_QMAX, _QMAX).astype(_I8)


def _channel_thresholds(w, out_axis: int = 0):
    """Per-output-channel |w| thresholds, computed IN-GRAPH: weights
    are runtime inputs to the compiled bucket, so the per-channel
    scales ride the trace and one executable serves every checkpoint
    of the architecture."""
    red = tuple(d for d in range(w.ndim) if d != out_axis)
    return jnp.maximum(jnp.max(jnp.abs(w), axis=red),
                       jnp.float32(1e-12))


def _q_fully_connected(key: str, t_act: float, resolved):
    no_bias = bool(resolved.get("no_bias", False))
    flatten = bool(resolved.get("flatten", True))

    def fn(*arrs):
        x, w = arrs[0], arrs[1]
        b = arrs[2] if len(arrs) > 2 else None
        with jax.named_scope(f"q8_{key}"):
            if flatten and x.ndim > 2:
                x = x.reshape(x.shape[0], -1)
            qx = _quantize_tensor(x, t_act)
            t_w = _channel_thresholds(w)           # (num_hidden,)
            qw = jnp.clip(jnp.round(w * (_QMAX / t_w)[:, None]),
                          -_QMAX, _QMAX).astype(_I8)
            acc = lax.dot_general(
                qx, qw, (((qx.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=_I32)
            # dequantize epilogue: t_w broadcasts on the output
            # channel (last) axis; adjacent to a downstream quantized
            # op's entry quantize this pair IS the requantize, fused
            # by XLA into one rescale
            y = acc.astype(_F32) * (jnp.float32(t_act / _QMAX)
                                    * (t_w / _QMAX))
            if b is not None and not no_bias:
                y = y + b
        return y
    return fn


def _q_convolution(key: str, t_act: float, resolved):
    kernel = tuple(resolved.get("kernel") or ())
    ndim = len(kernel)
    layout = resolved.get("layout") or \
        {1: "NCW", 2: "NCHW", 3: "NCDHW"}.get(ndim)
    if layout not in ("NCW", "NCHW", "NCDHW"):
        return None  # channels-last stays on the float path
    no_bias = bool(resolved.get("no_bias", False))
    groups = int(resolved.get("num_group") or 1)

    def fn(*arrs):
        from ..ndarray import ops_impl
        x, w = arrs[0], arrs[1]
        b = arrs[2] if len(arrs) > 2 else None
        stride = ops_impl._tuple(resolved.get("stride"), ndim)
        dilate = ops_impl._tuple(resolved.get("dilate"), ndim)
        pad = resolved.get("pad")
        pad = ops_impl._tuple(pad, ndim) if pad is not None \
            else (0,) * ndim
        with jax.named_scope(f"q8_{key}"):
            qx = _quantize_tensor(x, t_act)
            t_w = _channel_thresholds(w)           # (O,) of OI<sp>
            qw = jnp.clip(
                jnp.round(w * (_QMAX / t_w).reshape(
                    (-1,) + (1,) * (w.ndim - 1))),
                -_QMAX, _QMAX).astype(_I8)
            acc = lax.conv_general_dilated(
                qx, qw, window_strides=stride,
                padding=[(p, p) for p in pad],
                rhs_dilation=dilate,
                dimension_numbers=ops_impl._CONV_DN[layout],
                feature_group_count=groups,
                preferred_element_type=_I32)
            y = acc.astype(_F32) * (
                jnp.float32(t_act / _QMAX)
                * (t_w / _QMAX).reshape((1, -1) + (1,) * ndim))
            if b is not None and not no_bias:
                y = y + b.reshape((1, -1) + (1,) * ndim)
        return y
    return fn


def wrap_op(name: str, op, arrays, resolved):
    """Inside a quant scope, either OBSERVE a candidate op's data
    input (calibration) or return its int8 replacement (quantized
    serving) — or None to leave the op on the float path.  Called
    from ``ndarray._invoke_op_inner``; key assignment (the per-scope
    dispatch counter) is identical in both modes, so calibration keys
    line up with trace-time lookups by construction."""
    if name not in QUANT_READY or len(arrays) < 2:
        return None
    data, weight = arrays[0], arrays[1]
    if getattr(data, "dtype", None) != _F32 or \
            getattr(weight, "dtype", None) != _F32:
        return None
    global _COUNTER
    key = f"{name}_{_COUNTER}"
    _COUNTER += 1
    if _MODE == "calib":
        _COLLECT.observe(key, data)
        # evidence collectors (tools/mxprec --quant) also record the
        # per-channel weight scales the quantized trace will compute
        # in-graph; plain collectors don't implement the hook
        ow = getattr(_COLLECT, "observe_weight", None)
        if ow is not None:
            ow(key, weight)
        return None
    t_act = _SCALES.get(key) if _SCALES else None
    if t_act is None:
        return None  # no recorded scale -> bf16/f32 fallback
    if not _quant_decision(name, op, arrays, resolved):
        return None
    if name in ("Convolution", "convolution", "Convolution_v1"):
        return _q_convolution(key, t_act, resolved)
    return _q_fully_connected(key, t_act, resolved)


# ----------------------------------------------------------------------
# self-check (ci_static stage): policy parse + calibrate->quantize
# round trip on a tiny net + scale bookkeeping + kill-switch shape
# ----------------------------------------------------------------------
def _check_policy() -> None:
    policy = load_policy()
    allow, deny = policy_sets()
    if "dot" not in allow:
        raise MXNetError(
            "quant self-check: policy allow class lost `dot`")
    if not deny:
        raise MXNetError("quant self-check: policy deny class empty")
    if allow & deny:
        raise MXNetError("quant self-check: policy classes overlap")
    calib = policy.get("calibration", {})
    for key in ("activation_thresholds", "weight_scales",
                "int8_contractions"):
        if not calib.get(key):
            raise MXNetError(
                f"quant self-check: policy calibration evidence lost "
                f"{key!r} — regenerate with `python -m tools.mxprec "
                f"--quant --update`")


def _tiny_net_arrays():
    import numpy as np
    x = np.linspace(-1.5, 1.5, 48, dtype=np.float32).reshape(8, 6)
    w1 = np.linspace(1, -1, 24, dtype=np.float32).reshape(4, 6)
    b1 = np.linspace(-0.2, 0.2, 4, dtype=np.float32)
    w2 = np.linspace(-0.8, 0.8, 12, dtype=np.float32).reshape(3, 4)
    return x, w1, b1, w2


def _tiny_forward(nd, x, w1, b1, w2):
    h = nd.FullyConnected(x, w1, b1, num_hidden=4)
    h = nd.relu(h)
    return nd.FullyConnected(h, w2, num_hidden=3, no_bias=True)


def _check_roundtrip(verbose: bool = False) -> None:
    import numpy as np
    from .. import nd
    from ..analysis import dtypeflow, lowered_text
    from ..ndarray.ndarray import NDArray

    xh, w1h, b1h, w2h = _tiny_net_arrays()
    args = [nd.array(a) for a in (xh, w1h, b1h, w2h)]

    # eager calibration: both collectors see the same dispatch keys
    scales = {}
    for collector in (MinMaxCollector(), EntropyCollector()):
        with calibrating(collector):
            ref = _tiny_forward(nd, *args)
        scales[collector.mode] = collector.thresholds()
    for mode, sc in scales.items():
        if sorted(sc) != ["FullyConnected_0", "FullyConnected_1"]:
            raise MXNetError(
                f"quant self-check: {mode} collector keyed "
                f"{sorted(sc)} — expected one key per candidate "
                f"dispatch (scale bookkeeping broken)")

    # determinism: a second calibration pass is byte-identical
    again = MinMaxCollector()
    with calibrating(again):
        _tiny_forward(nd, *args)
    if again.thresholds() != scales["minmax"]:
        raise MXNetError(
            "quant self-check: calibration is not deterministic "
            "across identical passes")

    # traced quantized program: int8 dots, i32 accumulation, tagged,
    # zero hazards — and numerically close to the float reference
    table = scales["minmax"]

    def prog(x, w1, b1, w2):
        wrapped = [NDArray(a, None, _placed=True)
                   for a in (x, w1, b1, w2)]
        with quantize(table):
            return _tiny_forward(nd, *wrapped)._data

    jargs = [a._data for a in args]
    text = lowered_text(prog, *jargs)
    ledger = dtypeflow.program_ledger(text)
    if ledger["hazards"]:
        raise MXNetError(
            f"quant self-check: quantized round-trip produced "
            f"hazards: {ledger['hazards']}")
    census = dtypeflow.int8_contraction_census(text)
    if census.get("s8xs8->s32") != 2:
        raise MXNetError(
            f"quant self-check: expected 2 s8xs8->s32 contractions, "
            f"census={census}")
    if "q8_FullyConnected_0" not in text or \
            "q8_FullyConnected_1" not in text:
        raise MXNetError(
            "quant self-check: quantized dots lost their q8_<key> "
            "scale tags")
    run = jax.jit(prog)
    got = np.asarray(run(*jargs))
    want = ref.asnumpy()
    err = float(np.abs(got - want).max())
    tol = 0.05 * max(1.0, float(np.abs(want).max()))
    if err > tol:
        raise MXNetError(
            f"quant self-check: int8 output drifted {err:.4f} from "
            f"f32 (tol {tol:.4f})")

    # kill-switch shape: outside a scope (and under quantize(...,
    # enabled=False)) the same program carries no int8 at all
    def prog_off(x, w1, b1, w2):
        wrapped = [NDArray(a, None, _placed=True)
                   for a in (x, w1, b1, w2)]
        with quantize(table, enabled=False):
            return _tiny_forward(nd, *wrapped)._data
    off = lowered_text(prog_off, *jargs)
    if "s8[" in off or "q8_" in off:
        raise MXNetError(
            "quant self-check: int8 leaked outside the quantize scope")
    if verbose:
        print(f"quant self-check: round trip OK ({census} tagged, "
              f"zero hazards, |err|={err:.4f} <= {tol:.4f})")


def self_check(verbose: bool = False) -> int:
    """Probe the quantization contracts; returns 0 on success (raises
    on failure).  Run as a ci_static stage: ``python -m mxtpu.quant
    --self-check``."""
    _check_policy()
    if verbose:
        print(f"quant self-check: policy parse OK ({POLICY_PATH})")
    _check_roundtrip(verbose)
    if verbose:
        print("quant self-check: calibrate->quantize round trip OK "
              "(deterministic scales, i32 accumulation, no leak "
              "outside the scope)")
    return 0
