"""Python half of the C predict ABI (reference
``include/mxnet/c_predict_api.h``† / ``src/c_api/c_predict_api.cc``†).

``core/c_predict_api.cc`` embeds CPython and drives this module: a
:class:`Predictor` wraps a symbol JSON + ``.params`` blob into a bound
:class:`mxtpu.executor.Executor`; data crosses the ABI as raw bytes
(the C side owns plain ``float*`` buffers, this side wraps/unwraps via
numpy) so the C library needs no numpy C-API coupling.

Wire dtypes: floating inputs/outputs cross as float32 (the reference
ABI's format — back-compat), but integer/bool bindings are honoured
exactly: an input bound int32 (via ``input_dtypes`` or a ``__dtype__``
var attr) reads its bytes as int32, and integer outputs serialize as
their own type (``get_output_dtype`` tells the caller which).
Previously both ends hardcoded ``np.float32``, silently corrupting
int32 token ids above 2^24.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import MXNetError
from . import nd
from . import symbol as sym_mod
from .context import cpu, gpu
from .ndarray import legacy_format
from .ndarray.ndarray import NDArray


def _params_from_bytes(blob: bytes) -> Dict[str, np.ndarray]:
    """Parse a .params payload into name → array with arg:/aux:
    prefixes stripped (format detection shared with ``nd.load``)."""
    from .ndarray.ndarray import loads
    loaded = loads(blob)
    if not isinstance(loaded, dict):
        raise MXNetError(
            "c_predict: anonymous .params blob has no names to bind by")
    out = {}
    for name, arr in loaded.items():
        key = name.split(":", 1)[1] if name.startswith(("arg:",
                                                        "aux:")) \
            else name
        out[key] = arr.asnumpy()
    return out


class Predictor:
    """One bound inference executor (reference ``MXAPIPredictor``†)."""

    def __init__(self, symbol_json: str, param_blob: bytes,
                 dev_type: int, dev_id: int,
                 input_shapes: Dict[str, Tuple[int, ...]],
                 input_dtypes: Optional[Dict[str, Any]] = None):
        symbol = sym_mod.load_json(symbol_json)
        params = _params_from_bytes(param_blob)
        self._init_from_parts(symbol, params, dev_type, dev_id,
                              input_shapes, input_dtypes)

    @staticmethod
    def _wire_dtype(bound_dtype) -> np.dtype:
        """The dtype bytes cross the ABI as, derived from the BOUND
        array: integer/bool inputs keep their exact type (int32 token
        ids must not round-trip through float32 — that silently
        corrupted ids above 2^24); everything floating stays the
        reference's float32 wire format for ABI back-compat (the C side
        owns plain ``float*`` buffers)."""
        dt = np.dtype(bound_dtype)
        if dt.kind in "iub":
            return dt
        return np.dtype(np.float32)

    # -- ABI surface ----------------------------------------------------
    def set_input(self, key: str, data: bytes) -> None:
        # only DECLARED inputs are writable — a typo'd key must not
        # silently overwrite a trained weight (reference semantics)
        if key not in self._input_names:
            raise MXNetError(
                f"c_predict: {key!r} is not a declared input "
                f"(inputs: {self._input_names})")
        cur = self._executor.arg_dict[key]
        wire = self._wire_dtype(cur.dtype)
        arr = np.frombuffer(data, wire)
        if arr.size != int(np.prod(cur.shape)):
            raise MXNetError(
                f"c_predict: input {key!r} size {arr.size} != bound "
                f"shape {tuple(cur.shape)} (wire dtype {wire})")
        self._executor.arg_dict[key] = nd.array(
            arr.reshape(cur.shape).astype(cur.dtype, copy=False))

    def forward(self) -> None:
        self._outputs = self._executor.forward(is_train=False)

    def num_outputs(self) -> int:
        return len(self._out_shapes)

    def get_output_shape(self, index: int) -> Tuple[int, ...]:
        if not 0 <= index < len(self._out_shapes):
            raise MXNetError(f"c_predict: output index {index} out of "
                             f"range ({len(self._out_shapes)} outputs)")
        return self._out_shapes[index]

    def get_output(self, index: int) -> bytes:
        if not self._outputs:
            raise MXNetError("c_predict: forward() has not run")
        if not 0 <= index < len(self._outputs):
            raise MXNetError(f"c_predict: output index {index} out of "
                             f"range ({len(self._outputs)} outputs)")
        out = self._outputs[index].asnumpy()
        return out.astype(self._wire_dtype(out.dtype),
                          copy=False).tobytes()

    def get_output_dtype(self, index: int) -> str:
        """Wire dtype of ``get_output(index)`` — lets a caller decode
        non-float32 (e.g. argmax int) outputs correctly."""
        if not self._outputs:
            raise MXNetError("c_predict: forward() has not run")
        return str(self._wire_dtype(self._outputs[index].dtype))


    def reshape(self, input_shapes: Dict[str, Tuple[int, ...]]
                ) -> "Predictor":
        """New predictor for different input shapes sharing this one's
        weights (``MXPredReshape``†).  With XLA there is no memory pool
        to re-plan: a rebind (compile-cache hit per shape) is the whole
        story."""
        symbol, params, dev_type, dev_id, input_dtypes = self._parts
        clone = Predictor.__new__(Predictor)
        clone._init_from_parts(symbol, params, dev_type, dev_id,
                               {k: tuple(int(d) for d in v)
                                for k, v in input_shapes.items()},
                               input_dtypes)
        return clone

    def _init_from_parts(self, symbol, params,
                         dev_type, dev_id, input_shapes,
                         input_dtypes=None):
        # params may be host numpy (first create) or NDArray (reshape
        # clones): device buffers upload once and are SHARED across
        # reshapes — the reference MXPredReshape's zero-copy contract
        params = {k: v if isinstance(v, NDArray) else nd.array(v)
                  for k, v in params.items()}
        # input dtype resolution: explicit input_dtypes beats a
        # ``__dtype__`` attr on the symbol's var, beats float32 — so
        # int32 token-id inputs bind (and cross the wire) as int32
        var_dtypes = symbol.attr_dict()
        self._input_dtypes = {}
        for name in input_shapes:
            dt = (input_dtypes or {}).get(name) \
                or (var_dtypes.get(name, {}) or {}).get("__dtype__")
            self._input_dtypes[name] = np.dtype(dt) if dt \
                else np.dtype(np.float32)
        self._parts = (symbol, params, dev_type, dev_id,
                       dict(self._input_dtypes))
        ctx = cpu(dev_id) if dev_type == 1 else gpu(dev_id)
        self._input_names = list(input_shapes)
        args = dict(params)
        for name, shape in input_shapes.items():
            args[name] = nd.zeros(tuple(int(s) for s in shape),
                                  dtype=self._input_dtypes[name])
        known = set(symbol.list_inputs())
        args = {k: v for k, v in args.items() if k in known}
        missing = known - set(args)
        if missing:
            raise MXNetError(
                f"c_predict: inputs/params missing for "
                f"{sorted(missing)}")
        self._executor = symbol.bind(ctx, args=args, grad_req="null")
        self._outputs = []
        _, out_shapes, _ = symbol.infer_shape(
            **{k: tuple(v.shape) for k, v in args.items()})
        self._out_shapes = [tuple(int(d) for d in s)
                            for s in out_shapes]


def _create(symbol_json: str, param_blob: bytes, dev_type: int,
            dev_id: int, keys: Sequence[str],
            shapes: Sequence[Sequence[int]]) -> Predictor:
    """Entry point the embedded-C side calls."""
    return Predictor(symbol_json, param_blob, dev_type, dev_id,
                     {k: tuple(s) for k, s in zip(keys, shapes)})
