"""Python half of the C predict ABI (reference
``include/mxnet/c_predict_api.h``† / ``src/c_api/c_predict_api.cc``†).

``core/c_predict_api.cc`` embeds CPython and drives this module: a
:class:`Predictor` wraps a symbol JSON + ``.params`` blob into a bound
:class:`mxtpu.executor.Executor`; data crosses the ABI as raw bytes
(the C side owns plain ``float*`` buffers, this side wraps/unwraps via
numpy) so the C library needs no numpy C-API coupling.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from .base import MXNetError
from . import nd
from . import symbol as sym_mod
from .context import cpu, gpu
from .ndarray import legacy_format
from .ndarray.ndarray import NDArray


def _params_from_bytes(blob: bytes) -> Dict[str, np.ndarray]:
    """Parse a .params payload into name → array with arg:/aux:
    prefixes stripped (format detection shared with ``nd.load``)."""
    from .ndarray.ndarray import loads
    loaded = loads(blob)
    if not isinstance(loaded, dict):
        raise MXNetError(
            "c_predict: anonymous .params blob has no names to bind by")
    out = {}
    for name, arr in loaded.items():
        key = name.split(":", 1)[1] if name.startswith(("arg:",
                                                        "aux:")) \
            else name
        out[key] = arr.asnumpy()
    return out


class Predictor:
    """One bound inference executor (reference ``MXAPIPredictor``†)."""

    def __init__(self, symbol_json: str, param_blob: bytes,
                 dev_type: int, dev_id: int,
                 input_shapes: Dict[str, Tuple[int, ...]]):
        symbol = sym_mod.load_json(symbol_json)
        params = _params_from_bytes(param_blob)
        self._init_from_parts(symbol, params, dev_type, dev_id,
                              input_shapes)

    # -- ABI surface ----------------------------------------------------
    def set_input(self, key: str, data: bytes) -> None:
        # only DECLARED inputs are writable — a typo'd key must not
        # silently overwrite a trained weight (reference semantics)
        if key not in self._input_names:
            raise MXNetError(
                f"c_predict: {key!r} is not a declared input "
                f"(inputs: {self._input_names})")
        cur = self._executor.arg_dict[key]
        arr = np.frombuffer(data, np.float32)
        if arr.size != int(np.prod(cur.shape)):
            raise MXNetError(
                f"c_predict: input {key!r} size {arr.size} != bound "
                f"shape {tuple(cur.shape)}")
        self._executor.arg_dict[key] = nd.array(
            arr.reshape(cur.shape))

    def forward(self) -> None:
        self._outputs = self._executor.forward(is_train=False)

    def num_outputs(self) -> int:
        return len(self._out_shapes)

    def get_output_shape(self, index: int) -> Tuple[int, ...]:
        if not 0 <= index < len(self._out_shapes):
            raise MXNetError(f"c_predict: output index {index} out of "
                             f"range ({len(self._out_shapes)} outputs)")
        return self._out_shapes[index]

    def get_output(self, index: int) -> bytes:
        if not self._outputs:
            raise MXNetError("c_predict: forward() has not run")
        if not 0 <= index < len(self._outputs):
            raise MXNetError(f"c_predict: output index {index} out of "
                             f"range ({len(self._outputs)} outputs)")
        return self._outputs[index].asnumpy() \
            .astype(np.float32).tobytes()


    def reshape(self, input_shapes: Dict[str, Tuple[int, ...]]
                ) -> "Predictor":
        """New predictor for different input shapes sharing this one's
        weights (``MXPredReshape``†).  With XLA there is no memory pool
        to re-plan: a rebind (compile-cache hit per shape) is the whole
        story."""
        symbol, params, dev_type, dev_id = self._parts
        clone = Predictor.__new__(Predictor)
        clone._init_from_parts(symbol, params, dev_type, dev_id,
                               {k: tuple(int(d) for d in v)
                                for k, v in input_shapes.items()})
        return clone

    def _init_from_parts(self, symbol, params,
                         dev_type, dev_id, input_shapes):
        # params may be host numpy (first create) or NDArray (reshape
        # clones): device buffers upload once and are SHARED across
        # reshapes — the reference MXPredReshape's zero-copy contract
        params = {k: v if isinstance(v, NDArray) else nd.array(v)
                  for k, v in params.items()}
        self._parts = (symbol, params, dev_type, dev_id)
        ctx = cpu(dev_id) if dev_type == 1 else gpu(dev_id)
        self._input_names = list(input_shapes)
        args = dict(params)
        for name, shape in input_shapes.items():
            args[name] = nd.zeros(tuple(int(s) for s in shape))
        known = set(symbol.list_inputs())
        args = {k: v for k, v in args.items() if k in known}
        missing = known - set(args)
        if missing:
            raise MXNetError(
                f"c_predict: inputs/params missing for "
                f"{sorted(missing)}")
        self._executor = symbol.bind(ctx, args=args, grad_req="null")
        self._outputs = []
        _, out_shapes, _ = symbol.infer_shape(
            **{k: tuple(v.shape) for k, v in args.items()})
        self._out_shapes = [tuple(int(d) for d in s)
                            for s in out_shapes]


def _create(symbol_json: str, param_blob: bytes, dev_type: int,
            dev_id: int, keys: Sequence[str],
            shapes: Sequence[Sequence[int]]) -> Predictor:
    """Entry point the embedded-C side calls."""
    return Predictor(symbol_json, param_blob, dev_type, dev_id,
                     {k: tuple(s) for k, s in zip(keys, shapes)})
