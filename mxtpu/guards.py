"""Runtime guard rails (ISSUE 5) — the dynamic half of tools/mxlint.

Static analysis catches what an AST can see; this module catches the
two TPU-stack failure modes that only manifest at runtime:

* **Recompile churn** — a jitted entry whose cache keeps missing
  (shape-unstable batches, Python scalars flowing into traced
  signatures) silently turns a ~ms step into a ~seconds step.
  :class:`ChurnDetector` counts compiles per entry; past the limit
  (``MXTPU_GUARDS_CHURN_LIMIT``) it warns, or raises under
  ``MXTPU_GUARDS=2``.
* **Implicit host↔device transfers** — the ``asnumpy()`` trap the
  reference's threaded engine existed to avoid (SURVEY §0/§2).
  :func:`no_implicit_transfers` wraps a dispatch in
  ``jax.transfer_guard("disallow")`` so an un-committed numpy array
  sneaking into a hot path raises instead of quietly stalling the
  device.  Wired into ``TrainStep.__call__``/``run_steps`` and
  ``ModelRunner.run_raw``/``warmup`` under ``MXTPU_GUARDS=1``; tests
  use it to pin those paths transfer-clean.

Zero-overhead contract (asserted by ``bench.py`` at import): with
``MXTPU_GUARDS`` unset, :func:`no_implicit_transfers` returns one
shared ``nullcontext`` and the hot-path wiring is behind a cached
boolean — disabled guards add no per-step work.  Enabled guards add
only a context-manager flip around dispatch: the compiled program is
untouched, so bench row semantics cannot change (``self_check``
verifies a guarded computation is bit-identical to an unguarded one).
"""
from __future__ import annotations

import contextlib
import logging
import threading
import warnings
from typing import Any, Dict, Optional

from . import knobs
from . import obs
from .base import MXNetError

__all__ = ["enabled", "strict", "ChurnDetector", "RecompileChurn",
           "no_implicit_transfers", "self_check"]

logger = logging.getLogger("mxtpu.guards")

_NULL = contextlib.nullcontext()


def enabled() -> bool:
    """Guards on?  ``MXTPU_GUARDS=1`` (warn) or ``2`` (raise)."""
    return knobs.get("MXTPU_GUARDS").strip().lower() \
        in ("1", "2", "true", "yes", "on")


def strict() -> bool:
    """``MXTPU_GUARDS=2``: guard trips raise instead of warn."""
    return knobs.get("MXTPU_GUARDS").strip() == "2"


class RecompileChurn(MXNetError):
    """A guarded jit entry recompiled more times than its limit."""


class ChurnDetector:
    """Per-entry jit cache-miss counter.

    ``note_compile(key)`` on every cache miss, ``note_call()`` on every
    dispatch; once compiles exceed ``limit`` the detector warns ONCE
    (or raises, ``strict=True`` / ``MXTPU_GUARDS=2``) with the
    compiles-per-call ratio — the signature of an entry that keeps
    retracing instead of reusing its cache.
    """

    def __init__(self, name: str, limit: Optional[int] = None,
                 strict: Optional[bool] = None):
        self.name = name
        self._limit = limit
        self._strict = strict
        self._lock = threading.Lock()
        self.compiles = 0        # guarded-by: _lock
        self.calls = 0           # guarded-by: _lock
        self._last_keys = []     # guarded-by: _lock
        self._tripped = False    # guarded-by: _lock
        # ISSUE 8: cache misses also land in the process-wide metrics
        # registry so churn across every entry shows up in one scrape
        self._obs = obs.enabled()
        self._m_miss = obs.counter(
            "mxtpu_compile_cache_miss_total",
            "jit cache misses per guarded entry (ChurnDetector).",
            labels=("entry",)).labels(entry=name)

    @property
    def limit(self) -> int:
        if self._limit is not None:
            return self._limit
        return int(knobs.get("MXTPU_GUARDS_CHURN_LIMIT"))

    def note_call(self) -> None:
        with self._lock:
            self.calls += 1

    def note_compile(self, key: Any = None) -> None:
        """Record one jit cache miss; trips the guard past the limit."""
        if self._obs:
            self._m_miss.inc()
        with self._lock:
            self.compiles += 1
            self._last_keys.append(key)
            del self._last_keys[:-4]  # keep the most recent few
            over = self.compiles > self.limit and not self._tripped
            if not over:
                return
            self._tripped = True
            msg = (f"mxtpu.guards: recompile churn on {self.name!r} — "
                   f"{self.compiles} compiles over {self.calls} calls "
                   f"(limit {self.limit}). Recent signatures: "
                   f"{self._last_keys}. Unstable shapes/dtypes or "
                   f"Python values flowing into the traced signature "
                   f"keep missing the jit cache; make them static or "
                   f"bucket them.")
        be_strict = self._strict if self._strict is not None else strict()
        if be_strict:
            raise RecompileChurn(msg)
        logger.warning(msg)
        warnings.warn(msg, RuntimeWarning, stacklevel=3)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"name": self.name, "compiles": self.compiles,
                    "calls": self.calls, "limit": self.limit,
                    "tripped": self._tripped}


def no_implicit_transfers(enabled_override: Optional[bool] = None):
    """Context manager: inside it, implicit host↔device transfers
    raise (``jax.transfer_guard("disallow")``); explicit
    ``jax.device_put`` stays allowed.  Disabled (the default with
    ``MXTPU_GUARDS`` unset) it returns a shared ``nullcontext`` —
    zero overhead.  Pass ``enabled_override`` to force either way
    (hot paths pass their cached flag so the knob is not re-read per
    step)."""
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return _NULL
    import jax
    return jax.transfer_guard("disallow")


def self_check() -> Dict[str, Any]:
    """The import-time assertion bench.py runs: guards must be free
    when disabled and semantics-preserving when enabled.

    * disabled ⇒ :func:`no_implicit_transfers` is the shared
      nullcontext (no allocation, no env read in hot paths);
    * enabled ⇒ a tiny jitted computation produces bit-identical
      results inside and outside the guard scope (the scope changes
      WHAT IS ALLOWED, never what is computed).
    """
    if no_implicit_transfers(enabled_override=False) is not _NULL:
        raise MXNetError(
            "guards self_check: disabled transfer scope is not the "
            "zero-overhead nullcontext")
    info: Dict[str, Any] = {"enabled": enabled(), "strict": strict(),
                            "churn_limit":
                                int(knobs.get("MXTPU_GUARDS_CHURN_LIMIT"))}
    if info["enabled"]:
        import jax
        import jax.numpy as jnp
        import numpy as np
        probe = jax.jit(lambda v: v * 2 + 1)
        x = jnp.arange(8, dtype=jnp.float32)
        bare = probe(x)
        with no_implicit_transfers(enabled_override=True):
            guarded = probe(x)
        if not np.array_equal(np.asarray(bare), np.asarray(guarded)):
            raise MXNetError(
                "guards self_check: guarded dispatch changed results")
    return info
