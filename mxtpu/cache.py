"""``mxtpu.cache`` — persistent AOT executable cache (ISSUE 13).

At fleet scale compile time *is* availability: every server process
recompiles its full bucket ladder at warmup, and the control plane's
``warm_from=`` handoff only helps while a *live* donor exists.  This
module is the disk layer that survives process death: compiled XLA
executables (``jax.stages.Compiled``) are serialized through
``jax.experimental.serialize_executable`` and stored one-file-per-key
under a cache root, so a rollout, a spot-preempted worker's
replacement, or a scale-from-floor replica warms its ladder with
**zero data-path compiles** — ``ModelRunner._entry`` and the AOT
``TrainStep`` build load-or-compile through :class:`ExecutableCache`
transparently, ``FleetRouter.add_worker`` / the ``Autoscaler`` warm
donor-less replicas from it.

The robustness core is the failure surface, not the happy path:

* **Crash-safe writes** — entry bytes go to a private temp file in the
  cache root, are fsync'd, then ``os.replace``'d onto the final name:
  readers NEVER observe a torn entry, concurrent writers (threads or
  separate processes) race benignly (last atomic rename wins, both
  files are valid for the same key).
* **Verified loads** — every load re-parses the header, checks the
  payload length and sha256 checksum, and revalidates the FULL key
  component dict (model fingerprint, bucket shape, mesh/topology, jax
  version, backend, device kind, contract hash, salt) against what the
  caller expects.  A corrupt, truncated, or stale entry is moved to
  ``<root>/quarantine/`` and the caller recompiles — a wrong
  executable is never returned (the silent-corruption rule PR 7 set
  for canaries applies to the cache too).  The ``pickle.loads`` below
  is the ONE sanctioned raw-deserialize site in the tree (the
  ``raw-deserialize`` mxlint rule confines it here) and it only runs
  AFTER the checksum has passed.  The checksum defends against
  corruption/truncation, not a malicious cache root — point
  ``MXTPU_CACHE_DIR`` at a directory you trust like you trust your
  checkpoints.
* **Degradation, never errors** — a read-only cache dir, a full disk,
  or a jax/backend whose executables do not serialize all fall back to
  plain compile with a ``cache`` flight-recorder event and a
  ``mxtpu_cache_fallback_total`` count; nothing in the serving or
  training path ever raises because the cache is unhealthy.

Failure paths are exercised deterministically through the scripted
cache faults in :mod:`mxtpu.serving.faults` (``CorruptEntry``,
``TruncateEntry``, ``StaleKey``, ``ReadOnlyDir``) consulted at this
module's write seams, plus the :func:`poison_corrupt` /
:func:`poison_truncate` / :func:`poison_stale` helpers tests and the
``--self-check`` CLI use directly.

``python -m mxtpu.cache --self-check`` round-trips a tiny executable
through a throwaway cache root and probes every poisoning path — the
stage ``tools/ci_static.py`` runs.

Knobs (README "Persistent compile cache"): ``MXTPU_CACHE`` (master
switch), ``MXTPU_CACHE_DIR`` (root; unset = no persistence),
``MXTPU_CACHE_SALT`` (extra key component — bump to invalidate).
"""
from __future__ import annotations

import errno
import hashlib
import itertools
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, Optional, Tuple

from . import knobs
from . import obs
from .base import MXNetError

__all__ = ["CacheKey", "ExecutableCache", "default_cache",
           "contract_fingerprint", "poison_corrupt", "poison_truncate",
           "poison_stale", "self_check"]

# On-disk entry layout: magic, a fixed-width decimal header length,
# the JSON header (key components + payload checksum), the payload
# (pickled ``serialize()`` triple).  FORMAT is also a key component so
# a layout change can never alias an old entry.
_MAGIC = b"MXTPUXC1\n"
_FORMAT = 1
_LEN_WIDTH = 10

_QUARANTINE_DIR = "quarantine"

# temp-file uniquifier: pid alone is not enough — two cache INSTANCES
# in one process writing the same key would share a temp name and one
# writer's atomic rename would steal the other's half-written file
_TMP_SEQ = itertools.count()


class _EntryInvalid(Exception):
    """Internal: entry failed verification; ``reason`` is the
    quarantine label (magic|truncated|header|checksum|stale_key)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.reason = reason


class CacheKey:
    """An immutable, order-independent component dict plus its sha256
    digest (the entry filename).  Components are all strings; flipping
    ANY component — model fingerprint, bucket shape, mesh/topology,
    jax version, backend, contract hash, salt — changes the digest,
    and the full dict is ALSO stored in the entry header and
    revalidated on load (a digest collision or a hand-renamed file can
    never smuggle a stale executable in)."""

    __slots__ = ("components", "digest")

    def __init__(self, components: Dict[str, Any]):
        self.components = {str(k): str(v)
                           for k, v in sorted(components.items())}
        blob = json.dumps(self.components, sort_keys=True,
                          separators=(",", ":"))
        self.digest = hashlib.sha256(blob.encode()).hexdigest()

    def filename(self) -> str:
        return f"{self.digest}.mxc"

    def replace(self, **changes: Any) -> "CacheKey":
        """A new key with some components flipped (tests exercise the
        miss-on-any-component contract through this)."""
        comps = dict(self.components)
        comps.update(changes)
        return CacheKey(comps)

    def __repr__(self) -> str:
        return f"CacheKey({self.digest[:12]}…, {self.components})"


def contract_fingerprint(root: Optional[Path] = None) -> str:
    """sha256 over the committed ``contracts/`` lockfiles (sorted
    name+content) — the natural cache-validity fingerprint: when the
    pinned program contracts change, every cached executable built
    under the old contracts misses.  Computed once per process."""
    global _CONTRACT_FP
    if root is None:
        if _CONTRACT_FP is not None:
            return _CONTRACT_FP
        root = Path(__file__).resolve().parents[1] / "contracts"
    h = hashlib.sha256()
    if root.is_dir():
        for p in sorted(root.rglob("*.json")):
            h.update(p.relative_to(root).as_posix().encode())
            h.update(b"\0")
            try:
                h.update(p.read_bytes())
            except OSError:
                h.update(b"<unreadable>")
            h.update(b"\0")
    fp = h.hexdigest()[:16]
    if root == Path(__file__).resolve().parents[1] / "contracts":
        _CONTRACT_FP = fp
    return fp


_CONTRACT_FP: Optional[str] = None


class ExecutableCache:
    """One on-disk compiled-executable cache root.

    All methods are thread-safe and never raise on cache trouble: a
    failed ``load`` returns None (after quarantining the bad entry), a
    failed ``store`` returns False (after recording the fallback) —
    the caller compiles either way.  ``faults`` is the deterministic
    fault-injection seam (a :class:`~mxtpu.serving.faults.FaultPlan`
    carrying cache faults, consulted at the write seam and after each
    committed entry); production callers leave it None.
    """

    def __init__(self, root, *, salt: str = "", faults=None):
        self.root = Path(root)
        self.salt = str(salt)
        self._faults = faults
        # leaf lock (acquires nothing inside): counters + the write
        # latch; file operations themselves rely on atomic rename,
        # not on this lock, so cross-PROCESS writers are safe too.
        self._lock = threading.Lock()
        self._stores = 0              # guarded-by: _lock (fault script counter)
        self._write_ok = True         # guarded-by: _lock (latched off on EROFS/EACCES)
        self._stats = {"hit": 0, "miss": 0, "store": 0,       # guarded-by: _lock
                       "fallback": 0, "quarantined": 0}
        self._obs = obs.enabled()
        self._m_quarantined = obs.counter(
            "mxtpu_cache_quarantined_total",
            "Cache entries that failed load verification (corrupt/"
            "truncated/stale) and were moved to quarantine/.",
            labels=("reason",))
        self._m_fallback = obs.counter(
            "mxtpu_cache_fallback_total",
            "Cache degradations that fell back to plain compile "
            "(read-only dir, disk full, unserializable executable).",
            labels=("reason",))
        self._m_store = obs.counter(
            "mxtpu_cache_store_total",
            "Cache entries committed to disk (atomic renames).")
        self.recorder = obs.flight("cache")

    # -- keys -----------------------------------------------------------
    def key(self, *, model: str, shape: Any, mesh: Any = "1dev",
            **extra: Any) -> CacheKey:
        """Compose a full cache key: the caller names WHAT was
        compiled (``model`` fingerprint, concrete ``shape``/bucket,
        ``mesh`` topology, anything else via ``extra``); the cache
        adds the environment components every entry must match — jax
        version, backend, contract fingerprint, salt, format."""
        import jax
        comps: Dict[str, Any] = {
            "model": model, "shape": str(shape), "mesh": str(mesh),
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "contract": contract_fingerprint(),
            "salt": self.salt, "format": str(_FORMAT)}
        for k, v in extra.items():
            comps[k] = str(v)
        return CacheKey(comps)

    def path_for(self, key: CacheKey) -> Path:
        return self.root / key.filename()

    def contains(self, key: CacheKey) -> bool:
        """Cheap existence probe (no verification) — what the fleet
        asks before deciding a replacement can warm from disk."""
        return self.path_for(key).is_file()

    # -- load (verify-or-quarantine) ------------------------------------
    def load(self, key: CacheKey, *, with_meta: bool = False):
        """The checksum-verified loader: returns the loaded executable
        or None (missing / invalid / undeserializable — invalid
        entries are quarantined, never returned).  ``with_meta=True``
        returns ``(executable_or_None, meta)`` instead, where ``meta``
        is the writer's :meth:`store` sidecar dict (``{}`` on a miss)
        — how callers learn e.g. which audit modes the writer process
        ran, knobs being per-process."""
        compiled, meta = self._load(key)
        return (compiled, meta) if with_meta else compiled

    def _load(self, key: CacheKey) -> Tuple[Any, Dict[str, Any]]:
        path = self.path_for(key)
        try:
            blob = path.read_bytes()
        except FileNotFoundError:
            self._bump("miss")
            return None, {}
        except OSError as e:
            self._fallback("read_error", key, err=e)
            return None, {}
        try:
            payload, header = self._verify(blob, key)
        except _EntryInvalid as e:
            self._quarantine(path, e.reason, key, detail=str(e))
            return None, {}
        try:
            import pickle
            from jax.experimental.serialize_executable import \
                deserialize_and_load
            # THE sanctioned raw-deserialize site (raw-deserialize
            # lint rule): the payload checksum was verified above.
            unloaded, in_tree, out_tree = pickle.loads(payload)
            compiled = deserialize_and_load(unloaded, in_tree,
                                            out_tree)
        except Exception as e:  # jax/backend mismatch survives checksum
            self._quarantine(path, "deserialize", key, detail=repr(e))
            return None, {}
        self._bump("hit")
        if self._obs:
            self.recorder.record("hit", digest=key.digest[:12],
                                 model=key.components.get("model",
                                                          "")[:16])
        meta = header.get("meta")
        return compiled, meta if isinstance(meta, dict) else {}

    def _verify(self, blob: bytes,
                key: CacheKey) -> Tuple[bytes, Dict[str, Any]]:
        """Structural + checksum + key revalidation; returns
        ``(payload bytes, header dict)`` or raises
        :class:`_EntryInvalid`."""
        if not blob.startswith(_MAGIC):
            raise _EntryInvalid("magic", "bad magic")
        off = len(_MAGIC)
        len_line = blob[off:off + _LEN_WIDTH + 1]
        if len(len_line) < _LEN_WIDTH + 1 or \
                not len_line.endswith(b"\n"):
            raise _EntryInvalid("truncated", "short header-length")
        try:
            hlen = int(len_line[:-1])
        except ValueError:
            raise _EntryInvalid("header", "bad header-length") \
                from None
        off += _LEN_WIDTH + 1
        hbytes = blob[off:off + hlen]
        if len(hbytes) < hlen:
            raise _EntryInvalid("truncated", "short header")
        try:
            header = json.loads(hbytes)
        except ValueError:
            raise _EntryInvalid("header", "undecodable header") \
                from None
        payload = blob[off + hlen:]
        want_len = header.get("payload_len")
        if not isinstance(want_len, int) or len(payload) != want_len:
            raise _EntryInvalid(
                "truncated",
                f"payload {len(payload)}B, header says {want_len}")
        digest = hashlib.sha256(payload).hexdigest()
        if digest != header.get("payload_sha256"):
            raise _EntryInvalid("checksum", "payload sha256 mismatch")
        if header.get("key") != key.components:
            raise _EntryInvalid(
                "stale_key",
                f"entry key {header.get('key')} != expected "
                f"{key.components}")
        return payload, header

    # -- store (crash-safe) ---------------------------------------------
    def store(self, key: CacheKey, compiled, *,
              meta: Optional[Dict[str, Any]] = None) -> bool:
        """Serialize + commit one entry crash-safely: temp file in the
        cache root, fsync, atomic ``os.replace``.  Returns False (and
        records the degradation) instead of raising on any trouble.
        ``meta`` is a small JSON-able sidecar stored in the header and
        handed back by ``load(with_meta=True)`` — NOT part of the key
        (an entry written under different meta still hits); callers
        use it for per-process facts like the writer's audit modes."""
        with self._lock:
            if not self._write_ok:
                return False
            k = self._stores
            self._stores += 1
        try:
            import pickle
            from jax.experimental.serialize_executable import serialize
            unloaded, in_tree, out_tree = serialize(compiled)
            payload = pickle.dumps((unloaded, in_tree, out_tree))
        except Exception as e:
            self._fallback("serialize_unsupported", key, err=e)
            return False
        header = json.dumps(
            {"format": _FORMAT, "key": key.components,
             "digest": key.digest,
             "payload_sha256": hashlib.sha256(payload).hexdigest(),
             "payload_len": len(payload),
             "meta": dict(meta or {}),
             "created": time.time(), "writer_pid": os.getpid()},
            sort_keys=True).encode()
        blob = (_MAGIC + f"{len(header):0{_LEN_WIDTH}d}\n".encode()
                + header + payload)
        path = self.path_for(key)
        tmp = self.root / (f".{key.digest}.{os.getpid()}"
                           f".{next(_TMP_SEQ)}.tmp")
        try:
            if self._faults is not None:
                self._faults.before_cache_write(k)
            self.root.mkdir(parents=True, exist_ok=True)
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                         0o644)
            try:
                os.write(fd, blob)
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, path)
            self._fsync_dir(self.root)
        except OSError as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            if isinstance(e, PermissionError) or \
                    e.errno in (errno.EROFS, errno.EACCES):
                reason = "read_only"
                with self._lock:
                    # latch writes off: a read-only root will not heal
                    # mid-process, and re-failing every compile would
                    # spam the recorder
                    self._write_ok = False
            elif e.errno == errno.ENOSPC:
                reason = "disk_full"
            else:
                reason = "write_error"
            self._fallback(reason, key, err=e)
            return False
        if self._faults is not None:
            self._faults.entry_written(k, path)
        self._bump("store")
        if self._obs:
            self._m_store.inc()
            self.recorder.record("store", digest=key.digest[:12],
                                 bytes=len(blob))
        return True

    def load_or_compile(self, key: CacheKey,
                        compile_fn: Callable[[], Any], *,
                        meta: Optional[Dict[str, Any]] = None
                        ) -> Tuple[Any, str]:
        """``(executable, source)`` where source is ``"disk"`` (a
        verified cache hit) or ``"cold"`` (compiled now; stored for
        the next process if the cache is writable, with ``meta`` as
        the entry's header sidecar)."""
        compiled = self.load(key)
        if compiled is not None:
            return compiled, "disk"
        compiled = compile_fn()
        self.store(key, compiled, meta=meta)
        return compiled, "cold"

    # -- failure bookkeeping --------------------------------------------
    def _bump(self, stat: str, n: int = 1) -> None:
        with self._lock:
            self._stats[stat] += n

    def _quarantine(self, path: Path, reason: str, key: CacheKey,
                    detail: str = "") -> None:
        """Move a failed entry aside (never delete evidence, never
        retry it) and count it.  The quarantined file keeps its digest
        name plus reason + timestamp, so postmortems can inspect what
        the corruption actually was."""
        qdir = self.root / _QUARANTINE_DIR
        dest = qdir / f"{path.name}.{reason}.{os.getpid()}.{int(time.time() * 1e6)}"
        try:
            qdir.mkdir(parents=True, exist_ok=True)
            os.replace(path, dest)
        except OSError:
            try:  # read-only root: at least stop load() retrying it
                os.unlink(path)
            except OSError:
                pass
        self._bump("quarantined")
        if self._obs:
            self._m_quarantined.labels(reason=reason).inc()
            self.recorder.record("quarantine", reason=reason,
                                 digest=key.digest[:12],
                                 detail=detail[:160])

    def _fallback(self, reason: str, key: Optional[CacheKey],
                  err: Optional[BaseException] = None) -> None:
        self._bump("fallback")
        if self._obs:
            self._m_fallback.labels(reason=reason).inc()
            self.recorder.record(
                "fallback", reason=reason,
                digest=key.digest[:12] if key is not None else "",
                error=repr(err)[:160] if err is not None else "")

    @staticmethod
    def _fsync_dir(d: Path) -> None:
        """Make the rename itself durable (crash between rename and
        journal flush must not resurrect the old state as a torn
        view).  Best-effort: not every filesystem allows O_RDONLY
        dir fds."""
        try:
            fd = os.open(d, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)

    # -- introspection ---------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def writable(self) -> bool:
        with self._lock:
            return self._write_ok

    def entries(self) -> int:
        try:
            return sum(1 for _ in self.root.glob("*.mxc"))
        except OSError:
            return 0


# ----------------------------------------------------------------------
# process-wide default (knob-driven)
# ----------------------------------------------------------------------
_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Dict[str, ExecutableCache] = {}  # guarded-by: _DEFAULT_LOCK


def default_cache() -> Optional[ExecutableCache]:
    """The knob-configured process cache: None unless ``MXTPU_CACHE``
    is on AND ``MXTPU_CACHE_DIR`` names a root.  One instance per
    root, shared across every runner/TrainStep in the process (their
    entries can never collide: the key carries the model
    fingerprint)."""
    if not knobs.get("MXTPU_CACHE"):
        return None
    root = str(knobs.get("MXTPU_CACHE_DIR")).strip()
    if not root:
        return None
    salt = str(knobs.get("MXTPU_CACHE_SALT"))
    with _DEFAULT_LOCK:
        cache = _DEFAULT.get(root)
        if cache is None or cache.salt != salt:
            cache = _DEFAULT[root] = ExecutableCache(root, salt=salt)
        return cache


# ----------------------------------------------------------------------
# poisoning helpers — the shared implementation behind the scripted
# cache faults (serving/faults.py) and the self-check probes
# ----------------------------------------------------------------------
def poison_corrupt(path) -> None:
    """Flip one byte inside the payload region (a bit-rot / bad-DMA
    entry: structurally intact, checksum must catch it)."""
    p = Path(path)
    blob = bytearray(p.read_bytes())
    i = len(blob) - max(1, len(blob) // 16)
    blob[i] ^= 0xFF
    p.write_bytes(bytes(blob))


def poison_truncate(path) -> None:
    """Cut the entry in half (a crash mid-write on a filesystem
    without atomic rename semantics, or a partial copy)."""
    p = Path(path)
    blob = p.read_bytes()
    p.write_bytes(blob[:len(blob) // 2])


def poison_stale(path, component: str = "jax",
                 value: str = "0.0.0-stale") -> None:
    """Rewrite one key component in the header, keeping the payload
    checksum VALID — the entry parses and checksums clean but fails
    key revalidation (exactly what an entry from an old jax / old
    contracts looks like after an in-place upgrade)."""
    p = Path(path)
    blob = p.read_bytes()
    off = len(_MAGIC)
    hlen = int(blob[off:off + _LEN_WIDTH])
    off += _LEN_WIDTH + 1
    header = json.loads(blob[off:off + hlen])
    header["key"][component] = value
    hbytes = json.dumps(header, sort_keys=True).encode()
    p.write_bytes(_MAGIC + f"{len(hbytes):0{_LEN_WIDTH}d}\n".encode()
                  + hbytes + blob[off + hlen:])


# ----------------------------------------------------------------------
# self check (the tools/ci_static.py stage)
# ----------------------------------------------------------------------
def self_check(root: Optional[str] = None) -> Dict[str, Any]:
    """Round-trip + poisoning probes on a tiny executable:

    * store → load is a verified hit and the loaded executable
      computes bit-identical results;
    * each poisoning (corrupt byte, truncation, stale key component)
      makes ``load`` return None, quarantines the entry, and a
      re-store recovers;
    * a scripted read-only root degrades ``store`` to False without
      raising (and latches writes off);
    * flipping any key component misses.

    Raises :class:`MXNetError` on any contract violation; returns an
    info dict.  If this jax/backend cannot serialize executables at
    all, that is reported (``serialize_supported: False``) and the
    probes are skipped — that IS the degradation contract, not a
    failure."""
    import shutil
    import tempfile

    import numpy as np
    import jax
    import jax.numpy as jnp

    tmp = root or tempfile.mkdtemp(prefix="mxtpu_cache_check_")
    made_tmp = root is None
    info: Dict[str, Any] = {"root": tmp}
    try:
        cache = ExecutableCache(tmp, salt="self_check")
        x = jnp.arange(8, dtype=jnp.float32)
        compiled = jax.jit(lambda v: v * 2 + 1).lower(x).compile()
        want = np.asarray(compiled(x))  # mxlint: sync-point — probe readback
        key = cache.key(model="self_check", shape="(8,)f32")
        if not cache.store(key, compiled):
            # serialize unsupported here: the fallback path already
            # fired (recorded); nothing further to probe.
            info["serialize_supported"] = False
            return info
        info["serialize_supported"] = True
        loaded = cache.load(key)
        if loaded is None:
            raise MXNetError("cache self_check: round-trip load missed")
        got = np.asarray(loaded(x))  # mxlint: sync-point — probe readback
        if not np.array_equal(want, got):
            raise MXNetError(
                f"cache self_check: loaded executable disagrees "
                f"({got} != {want})")

        # any flipped key component must miss
        for comp, val in (("model", "other"), ("shape", "(9,)f32"),
                          ("mesh", "2dev"), ("jax", "0.0.0"),
                          ("contract", "feedfeedfeedfeed")):
            if cache.load(key.replace(**{comp: val})) is not None:
                raise MXNetError(
                    f"cache self_check: flipped key component "
                    f"{comp!r} still hit")

        # poisoning probes: each must load None + quarantine, and a
        # fresh store must recover
        path = cache.path_for(key)
        probes = (("corrupt", poison_corrupt),
                  ("truncate", poison_truncate),
                  ("stale", poison_stale))
        for name, poison in probes:
            if not cache.contains(key):
                cache.store(key, compiled)
            poison(path)
            if cache.load(key) is not None:
                raise MXNetError(
                    f"cache self_check: poisoned entry ({name}) "
                    f"was served")
            if cache.contains(key):
                raise MXNetError(
                    f"cache self_check: poisoned entry ({name}) "
                    f"not quarantined")
        st = cache.stats()
        if st["quarantined"] != len(probes):
            raise MXNetError(
                f"cache self_check: expected {len(probes)} "
                f"quarantines, saw {st['quarantined']}")
        qdir = Path(tmp) / _QUARANTINE_DIR
        if sum(1 for _ in qdir.iterdir()) != len(probes):
            raise MXNetError(
                "cache self_check: quarantine dir does not hold the "
                "poisoned entries")

        # read-only degradation: scripted PermissionError at the
        # write seam (chmod is unreliable here — CI roots run as
        # uid 0, which ignores mode bits)
        class _Deny:
            def before_cache_write(self, k):
                raise PermissionError("self_check: read-only root")

            def entry_written(self, k, path):
                pass

        ro = ExecutableCache(Path(tmp) / "ro", salt="self_check",
                             faults=_Deny())
        if ro.store(key, compiled):
            raise MXNetError(
                "cache self_check: store on a read-only root "
                "claimed success")
        if ro.writable():
            raise MXNetError(
                "cache self_check: read-only root did not latch "
                "writes off")
        info.update(stats=st, round_trip=True, poisons=len(probes),
                    read_only_fallback=True)
        return info
    finally:
        if made_tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def _main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="python -m mxtpu.cache")
    ap.add_argument("--self-check", action="store_true",
                    help="round-trip + poisoning probes on a tiny "
                         "executable (default action)")
    ap.add_argument("--root", default=None,
                    help="probe inside this directory instead of a "
                         "throwaway tempdir")
    args = ap.parse_args(argv)
    info = self_check(root=args.root)
    print(f"cache.self_check OK: {info}")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_main())
