"""``mxtpu.symbol`` — the declarative graph API (graph-lite).

Reference: ``python/mxnet/symbol/symbol.py``† (Symbol compose / ``tojson``
/ ``infer_shape`` / ``bind``) over the NNVM graph IR
(``3rdparty/tvm/nnvm``†, ``src/nnvm/``†).

TPU-native re-design: a Symbol is a lightweight DAG of op nodes whose
"execution" is *interpretation through the same registry lowering rules
the eager path uses* — so ``bind``/``eval`` run eagerly on NDArray, and
anything that needs performance jits the interpretation (the Executor
does exactly this).  There is no separate graph compiler: XLA is the
graph layer (memory planning, fusion, placement — the jobs of the
reference's ``GraphExecutor``† passes — all happen inside jit).

JSON format: nnvm-style node list (``op``/``name``/``attrs``/``inputs``
+ ``arg_nodes``/``heads``) so ``export()`` artifacts round-trip and
reference-era tooling can introspect them.
"""
from __future__ import annotations

import ast
import builtins
import json
import sys
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError, _as_list
from ..ops.registry import OP_REGISTRY, get_op, list_ops

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "fromjson"]

_NAME_LOCK = threading.Lock()
_NAME_COUNTERS: Dict[str, int] = {}


def _auto_name(op_name: str) -> str:
    hint = op_name.lower().lstrip("_")
    with _NAME_LOCK:
        idx = _NAME_COUNTERS.get(hint, 0)
        _NAME_COUNTERS[hint] = idx + 1
    return f"{hint}{idx}"


class _Node:
    """One graph node: a variable (``op is None``) or an op application."""

    __slots__ = ("op", "name", "inputs", "attrs", "num_outputs")

    def __init__(self, op: Optional[str], name: str,
                 inputs: List[Tuple["_Node", int]],
                 attrs: Dict[str, Any], num_outputs: int = 1):
        self.op = op
        self.name = name
        self.inputs = inputs
        self.attrs = attrs
        self.num_outputs = num_outputs


def _coerce_attr(v: Any) -> Any:
    """JSON attrs are strings (reference format); coerce generically —
    typed coercion happens again in the op's ParamSet on invocation."""
    if not isinstance(v, str):
        return v
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


class Symbol:
    """A set of output heads over the node DAG (exactly nnvm's model:
    a symbol IS its head list)."""

    __slots__ = ("_heads",)

    def __init__(self, heads: List[Tuple[_Node, int]]):
        self._heads = heads

    # -- identity -------------------------------------------------------
    @property
    def name(self) -> str:
        if len(self._heads) != 1:
            return "grouped_symbol"
        return self._heads[0][0].name

    def __repr__(self):
        return f"<Symbol {' '.join(n.name for n, _ in self._heads)}>"

    def __iter__(self):
        return iter(self[i] for i in range(len(self._heads)))

    def __len__(self):
        return len(self._heads)

    def __getitem__(self, index):
        if isinstance(index, str):
            internals = self.get_internals()
            names = internals.list_outputs()
            if index in names:
                return internals[names.index(index)]
            raise MXNetError(f"no internal output named {index!r}; "
                             f"try one of {names[:20]}…")
        # NB: the generated op namespace shadows builtins like ``slice``
        # and ``abs`` at module scope — always go through ``builtins``.
        if isinstance(index, builtins.slice):
            return Symbol(self._heads[index])
        return Symbol([self._heads[index]])

    # -- traversal ------------------------------------------------------
    def _topo(self) -> List[_Node]:
        # Iterative postorder DFS — graphs (unrolled RNNs, deep chains)
        # routinely exceed Python's recursion limit.
        seen: set = set()
        order: List[_Node] = []
        stack: List[Tuple[_Node, bool]] = [
            (node, False) for node, _ in reversed(self._heads)]
        while stack:
            node, expanded = stack.pop()
            if expanded:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for src, _ in reversed(node.inputs):
                if id(src) not in seen:
                    stack.append((src, False))
        return order

    def list_arguments(self) -> List[str]:
        return [n.name for n in self._topo()
                if n.op is None and not _is_aux_name(n.name)]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self._topo()
                if n.op is None and _is_aux_name(n.name)]

    def list_inputs(self) -> List[str]:
        return [n.name for n in self._topo() if n.op is None]

    def list_outputs(self) -> List[str]:
        outs = []
        for node, idx in self._heads:
            if node.num_outputs > 1:
                outs.append(f"{node.name}_output{idx}")
            elif node.op is None:
                outs.append(node.name)
            else:
                outs.append(f"{node.name}_output")
        return outs

    def get_internals(self) -> "Symbol":
        """Every node output as a head (reference ``get_internals``†)."""
        heads = []
        for node in self._topo():
            for i in range(node.num_outputs):
                heads.append((node, i))
        return Symbol(heads)

    def get_children(self) -> Optional["Symbol"]:
        heads = []
        for node, _ in self._heads:
            heads.extend(node.inputs)
        return Symbol(heads) if heads else None

    # -- attributes -----------------------------------------------------
    def attr(self, key: str) -> Optional[str]:
        if len(self._heads) == 1:
            v = self._heads[0][0].attrs.get(key)
            return None if v is None else str(v)
        return None

    def list_attr(self) -> Dict[str, str]:
        if len(self._heads) == 1:
            return {k: str(v) for k, v in self._heads[0][0].attrs.items()}
        return {}

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        out = {}
        for node in self._topo():
            if node.attrs:
                out[node.name] = {k: str(v) for k, v in node.attrs.items()}
        return out

    # -- serialization --------------------------------------------------
    def tojson(self) -> str:
        order = self._topo()
        node_id = {id(n): i for i, n in enumerate(order)}
        nodes = []
        for n in order:
            entry: Dict[str, Any] = {
                "op": "null" if n.op is None else n.op,
                "name": n.name,
                "inputs": [[node_id[id(s)], i, 0] for s, i in n.inputs],
            }
            if n.attrs:
                entry["attrs"] = {k: str(v) for k, v in n.attrs.items()
                                  if v is not None}
            nodes.append(entry)
        payload = {
            "nodes": nodes,
            "arg_nodes": [i for i, n in enumerate(order) if n.op is None],
            "heads": [[node_id[id(n)], i, 0] for n, i in self._heads],
            "attrs": {"mxtpu_json": "1"},
        }
        return json.dumps(payload, indent=2)

    def save(self, fname: str) -> None:
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- composition ----------------------------------------------------
    def _head1(self) -> Tuple[_Node, int]:
        if len(self._heads) != 1:
            raise MXNetError(
                "a multi-output symbol must be indexed before use as an "
                "op input (reference semantics)")
        return self._heads[0]

    # arithmetic (maps to the same registered ops NDArray uses)
    def __add__(self, other):
        return _binop(self, other, "broadcast_add", "_plus_scalar", False)

    __radd__ = __add__

    def __sub__(self, other):
        return _binop(self, other, "broadcast_sub", "_minus_scalar", False)

    def __rsub__(self, other):
        return _binop(self, other, "broadcast_sub", "_rminus_scalar", True)

    def __mul__(self, other):
        return _binop(self, other, "broadcast_mul", "_mul_scalar", False)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _binop(self, other, "broadcast_div", "_div_scalar", False)

    def __rtruediv__(self, other):
        return _binop(self, other, "broadcast_div", "_rdiv_scalar", True)

    def __mod__(self, other):
        return _binop(self, other, "broadcast_mod", "_mod_scalar", False)

    def __rmod__(self, other):
        return _binop(self, other, "broadcast_mod", "_rmod_scalar", True)

    def __pow__(self, other):
        return _binop(self, other, "broadcast_power", "_power_scalar",
                      False)

    def __rpow__(self, other):
        return _binop(self, other, "broadcast_power", "_rpower_scalar",
                      True)

    def __neg__(self):
        return _create("negative", [self], {})

    def __abs__(self):
        return _create("abs", [self], {})

    def __eq__(self, other):  # noqa: A003 — reference returns a symbol
        if isinstance(other, (Symbol, int, float)):
            return _binop(self, other, "broadcast_equal", "_equal_scalar",
                          False)
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (Symbol, int, float)):
            return _binop(self, other, "broadcast_not_equal",
                          "_not_equal_scalar", False)
        return NotImplemented

    def __gt__(self, other):
        return _binop(self, other, "broadcast_greater", "_greater_scalar",
                      False)

    def __ge__(self, other):
        return _binop(self, other, "broadcast_greater_equal",
                      "_greater_equal_scalar", False)

    def __lt__(self, other):
        return _binop(self, other, "broadcast_lesser", "_lesser_scalar",
                      False)

    def __le__(self, other):
        return _binop(self, other, "broadcast_lesser_equal",
                      "_lesser_equal_scalar", False)

    def __hash__(self):
        return id(self)

    def __copy__(self):
        return Symbol(list(self._heads))

    def __deepcopy__(self, memo):
        return fromjson(self.tojson())

    # method-style ops the reference exposes on Symbol
    def reshape(self, shape):
        return _create("reshape", [self], {"shape": tuple(shape)})

    def transpose(self, axes=None):
        return _create("transpose", [self],
                       {} if axes is None else {"axes": tuple(axes)})

    def sum(self, axis=None, keepdims=False):
        return _create("sum", [self],
                       {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return _create("mean", [self],
                       {"axis": axis, "keepdims": keepdims})

    def astype(self, dtype):
        return _create("cast", [self], {"dtype": str(np.dtype(dtype))})

    # -- inference ------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes, unknown = \
            self._infer_shape_impl(args, kwargs)
        if unknown:
            raise MXNetError(
                f"infer_shape: could not infer {sorted(unknown)} — "
                f"provide their shapes (partial inference covers the "
                f"common NN ops; see infer_shape_partial)")
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes, _ = \
            self._infer_shape_impl(args, kwargs)
        return arg_shapes, out_shapes, aux_shapes

    def _infer_shape_impl(self, args, kwargs):
        import jax

        arg_names = self.list_arguments()
        known: Dict[str, Tuple[int, ...]] = {}
        if args:
            if kwargs:
                raise MXNetError("pass shapes positionally or by name")
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        else:
            known = {k: tuple(v) for k, v in kwargs.items()
                     if v is not None}

        shapes: Dict[Tuple[int, int], Optional[Tuple[int, ...]]] = {}
        unknown: set = set()
        var_nodes: Dict[str, _Node] = {}
        for node in self._topo():
            if node.op is None:
                var_nodes.setdefault(node.name, node)
                shp = known.get(node.name)
                if shp is None and node.attrs.get("__shape__") is not None:
                    shp = tuple(_coerce_attr(node.attrs["__shape__"]))
                shapes[(id(node), 0)] = shp
                if shp is None:
                    unknown.add(node.name)
                continue
            in_shapes = [shapes.get((id(s), i)) for s, i in node.inputs]
            if any(s is None for s in in_shapes):
                hook = _INFER_HOOKS.get(node.op)
                if hook is not None:
                    hinted = hook(in_shapes, node.attrs)
                    for (src, i), hs in zip(node.inputs, hinted):
                        if hs is not None and shapes.get((id(src), i)) \
                                is None:
                            shapes[(id(src), i)] = tuple(hs)
                            if src.op is None:
                                unknown.discard(src.name)
                    in_shapes = [shapes.get((id(s), i))
                                 for s, i in node.inputs]
            if any(s is None for s in in_shapes):
                for i in range(node.num_outputs):
                    shapes[(id(node), i)] = None
                continue
            outs = _abstract_eval(node, in_shapes)
            for i, o in enumerate(outs):
                shapes[(id(node), i)] = o

        def _var_head(n):
            node = var_nodes.get(n)
            return (id(node), 0) if node is not None else None

        arg_shapes = [shapes.get(_var_head(n)) for n in arg_names]
        aux_shapes = [shapes.get(_var_head(n))
                      for n in self.list_auxiliary_states()]
        out_shapes = [shapes.get((id(n), i)) for n, i in self._heads]
        # re-scan unknown: hooks may have filled vars
        still_unknown = {n for n, s in zip(arg_names, arg_shapes)
                         if s is None} | \
                        {n for n, s in zip(self.list_auxiliary_states(),
                                           aux_shapes) if s is None}
        return arg_shapes, out_shapes, aux_shapes, still_unknown

    def infer_type(self, *args, **kwargs):
        """Everything defaults to float32 unless a var carries
        ``__dtype__`` (the eager path is the dtype oracle; symbols track
        shapes, XLA tracks dtypes)."""
        var_nodes = {n.name: n for n in self._topo() if n.op is None}
        arg_types = []
        for n in self.list_arguments():
            node = var_nodes.get(n)
            dt = node.attrs.get("__dtype__") if node is not None else None
            arg_types.append(np.dtype(dt) if dt else np.dtype("float32"))
        out_types = [np.dtype("float32")] * len(self._heads)
        aux_types = [np.dtype("float32")] * \
            len(self.list_auxiliary_states())
        return arg_types, out_types, aux_types

    # -- execution ------------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        """Evaluate eagerly with named NDArray bindings."""
        return _eval_symbol(self, kwargs)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from ..executor import Executor
        return Executor(self, ctx, args=args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states)

    def simple_bind(self, ctx=None, grad_req="write", **shape_kwargs):
        from ..executor import Executor
        return Executor.simple_bind(self, ctx, grad_req=grad_req,
                                    **shape_kwargs)

    # reference: symbol composition sym2(data=sym1)
    def __call__(self, *args, **kwargs):
        mapping: Dict[str, Symbol] = {}
        arg_names = self.list_arguments()
        for name, s in zip(arg_names, args):
            mapping[name] = s
        mapping.update(kwargs)
        for k, v in mapping.items():
            if not isinstance(v, Symbol):
                raise MXNetError("composition args must be Symbols")
        return _compose(self, mapping)


def _is_aux_name(name: str) -> bool:
    """Reference convention: BatchNorm moving stats are auxiliary
    states, identified by name (``moving_mean``/``moving_var`` upstream;
    gluon uses ``running_``)."""
    return name.endswith(("moving_mean", "moving_var", "running_mean",
                          "running_var"))


def _abstract_eval(node: _Node, in_shapes) -> List[Tuple[int, ...]]:
    """Shape inference by abstract interpretation of the lowering rule —
    the role of the reference's ``InferShape`` pass
    (``src/executor/infer_graph_attr_pass.cc``†)."""
    import jax
    import jax.numpy as jnp
    from .. import ndarray as nd_mod
    from ..ndarray.ndarray import NDArray

    attrs = {k: _coerce_attr(v) for k, v in node.attrs.items()
             if not k.startswith("__")}
    fn = getattr(nd_mod, node.op, None)
    if fn is None:
        raise MXNetError(f"unknown op {node.op!r} in symbol graph")

    def run(*arrs):
        outs = fn(*[NDArray(a, None, _placed=True) for a in arrs], **attrs)
        if isinstance(outs, (list, tuple)):
            return [o.data for o in outs]
        return [outs.data]

    avals = [jax.ShapeDtypeStruct(s, jnp.float32) for s in in_shapes]
    outs = jax.eval_shape(run, *avals)
    return [tuple(o.shape) for o in outs]


# param-shape hints for ops whose weight shapes the reference infers
# backward from the data shape (what lets Module.bind work from
# data_shapes alone)
def _fc_hook(in_shapes, attrs):
    data = in_shapes[0]
    if data is None:
        return [None] * len(in_shapes)
    nh = int(_coerce_attr(attrs.get("num_hidden", 0)))
    flatten = bool(_coerce_attr(attrs.get("flatten", True)))
    in_units = int(np.prod(data[1:])) if flatten or len(data) == 2 \
        else data[-1]
    out = [data, (nh, in_units)]
    if len(in_shapes) > 2:
        out.append((nh,))
    return out


def _conv_hook(in_shapes, attrs):
    data = in_shapes[0]
    if data is None:
        return [None] * len(in_shapes)
    kernel = tuple(_coerce_attr(attrs.get("kernel", ())))
    nf = int(_coerce_attr(attrs.get("num_filter", 0)))
    ng = int(_coerce_attr(attrs.get("num_group", 1)))
    c = data[1]  # NC... layouts (default); NHWC nets pass explicit shapes
    out = [data, (nf, c // ng) + kernel]
    if len(in_shapes) > 2:
        out.append((nf,))
    return out


def _deconv_hook(in_shapes, attrs):
    data = in_shapes[0]
    if data is None:
        return [None] * len(in_shapes)
    kernel = tuple(_coerce_attr(attrs.get("kernel", ())))
    nf = int(_coerce_attr(attrs.get("num_filter", 0)))
    ng = int(_coerce_attr(attrs.get("num_group", 1)))
    c = data[1]
    out = [data, (c, nf // ng) + kernel]
    if len(in_shapes) > 2:
        out.append((nf,))
    return out


def _channel_hook(in_shapes, attrs, default_axis=1):
    # default_axis must match each op's Param default: BatchNorm/
    # InstanceNorm normalise per channel (axis 1), LayerNorm per the
    # LAST axis (-1) — guessing gamma from axis 1 for a default-axis
    # LayerNorm inferred the wrong shape (r4 fix)
    data = in_shapes[0]
    if data is None:
        return [None] * len(in_shapes)
    axis = int(_coerce_attr(attrs.get("axis", default_axis)))
    c = data[axis]
    return [data] + [(c,)] * (len(in_shapes) - 1)


def _embedding_hook(in_shapes, attrs):
    data = in_shapes[0]
    ind = int(_coerce_attr(attrs.get("input_dim", 0)))
    outd = int(_coerce_attr(attrs.get("output_dim", 0)))
    return [data, (ind, outd)]


_INFER_HOOKS = {
    "FullyConnected": _fc_hook,
    "Convolution": _conv_hook,
    "Deconvolution": _deconv_hook,
    "BatchNorm": _channel_hook,
    "BatchNormRelu": _channel_hook,
    # addend (input 1) is data-shaped, the rest are (C,)
    "BatchNormAddRelu": lambda in_shapes, attrs: (
        lambda full: [full[0], full[0]] + full[1:]
    )(_channel_hook([in_shapes[0]] + list(in_shapes[2:]), attrs)),
    "InstanceNorm": _channel_hook,
    "LayerNorm": lambda in_shapes, attrs: _channel_hook(
        in_shapes, attrs, default_axis=-1),
    "Embedding": _embedding_hook,
}


# ----------------------------------------------------------------------
# construction
# ----------------------------------------------------------------------
def var(name: str, attr=None, shape=None, dtype=None, init=None,
        lr_mult=None, wd_mult=None, **kwargs) -> Symbol:
    """Create a variable (reference ``mx.sym.var``/``Variable``†)."""
    if not isinstance(name, str):
        raise MXNetError("variable name must be a string")
    attrs: Dict[str, Any] = dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(np.dtype(dtype))
    if init is not None:
        attrs["__init__"] = str(init)
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    attrs.update(kwargs)
    return Symbol([(_Node(None, name, [], attrs), 0)])


Variable = var


def Group(symbols: Sequence[Symbol]) -> Symbol:  # noqa: N802
    """Multi-head symbol (reference ``mx.sym.Group``†)."""
    heads: List[Tuple[_Node, int]] = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


def _num_outputs_of(op_name: str, n_inputs: int, attrs) -> int:
    try:
        op = get_op(op_name)
    except Exception:
        return 1
    if op.num_outputs_fn is not None:
        # apply Param defaults first so num_outputs_fn callbacks see
        # resolved attrs, not raw ones — otherwise every callback must
        # individually defend against missing keys (r4 review)
        attrs_c = {k: _coerce_attr(v) for k, v in attrs.items()}
        try:
            attrs_c = op.resolve_params(
                {k: v for k, v in attrs_c.items()
                 if k in op.params.params})
        except MXNetError:
            pass  # bad attr values surface at execution time instead
        return op.num_outputs_fn(attrs_c)
    if op.num_outputs == -1:
        if op_name in ("split", "SliceChannel"):
            return int(_coerce_attr(attrs.get("num_outputs", 1)))
        return 1
    return op.num_outputs


def _create(op_name: str, inputs: Sequence[Any], attrs: Dict[str, Any],
            name: Optional[str] = None) -> Symbol:
    heads: List[Tuple[_Node, int]] = []
    for x in inputs:
        if isinstance(x, Symbol):
            heads.append(x._head1())
        else:
            raise MXNetError(
                f"symbol op {op_name} inputs must be Symbols, got "
                f"{type(x).__name__}")
    clean = {k: v for k, v in attrs.items() if v is not None}
    node = _Node(op_name, name or _auto_name(op_name), heads, clean,
                 _num_outputs_of(op_name, len(heads), clean))
    return Symbol([(node, i) for i in range(node.num_outputs)])


def _binop(lhs: Symbol, rhs, tensor_op: str, scalar_op: str,
           reflected: bool) -> Symbol:
    if isinstance(rhs, Symbol):
        return _create(tensor_op, [rhs, lhs] if reflected else [lhs, rhs],
                       {})
    return _create(scalar_op, [lhs], {"scalar": float(rhs)})


def _compose(sym: Symbol, mapping: Dict[str, Symbol]) -> Symbol:
    """Graft symbols onto named variables (reference composition)."""
    # memo stores the FULL replacement (node, head_idx) so a variable
    # referenced more than once keeps binding to the mapped head's
    # output index (ridx == -1 means "keep the caller's index").
    memo: Dict[int, Tuple[_Node, int]] = {}

    def rebuild(node: _Node) -> Tuple[_Node, int]:
        if id(node) in memo:
            return memo[id(node)]
        if node.op is None and node.name in mapping:
            result = mapping[node.name]._head1()
            memo[id(node)] = result
            return result
        new_inputs = []
        for src, i in node.inputs:
            rep, ridx = rebuild(src)
            new_inputs.append((rep, i if ridx == -1 else ridx))
        if len(new_inputs) == len(node.inputs) and all(
                a is b and i == j for (a, i), (b, j)
                in zip(new_inputs, node.inputs)):
            memo[id(node)] = (node, -1)
            return node, -1
        new = _Node(node.op, node.name, new_inputs, dict(node.attrs),
                    node.num_outputs)
        memo[id(node)] = (new, -1)
        return new, -1

    heads = []
    for node, idx in sym._heads:
        rep, ridx = rebuild(node)
        heads.append((rep, idx if ridx == -1 else ridx))
    return Symbol(heads)


# ----------------------------------------------------------------------
# evaluation (the executor's engine — interpretation over nd ops)
# ----------------------------------------------------------------------
def _eval_symbol(outputs, bindings: Dict[str, Any]):
    """Topologically interpret a symbol through the eager op namespace.
    ``bindings`` maps var name → NDArray.  Returns a list of NDArray
    (single-head symbols still return a 1-list, reference executor
    semantics)."""
    from .. import ndarray as nd_mod
    from ..ndarray.ndarray import NDArray

    sym = outputs if isinstance(outputs, Symbol) else Group(
        _as_list(outputs))
    memo: Dict[Tuple[int, int], Any] = {}
    for node in sym._topo():
        if node.op is None:
            if node.name not in bindings:
                raise MXNetError(f"unbound variable {node.name!r}")
            val = bindings[node.name]
            memo[(id(node), 0)] = val if isinstance(val, NDArray) \
                else nd_mod.array(val)
            continue
        ins = [memo[(id(s), i)] for s, i in node.inputs]
        attrs = {k: _coerce_attr(v) for k, v in node.attrs.items()
                 if not k.startswith("__")}
        fn = getattr(nd_mod, node.op, None)
        if fn is None:
            raise MXNetError(f"unknown op {node.op!r} in symbol graph")
        out = fn(*ins, **attrs)
        if isinstance(out, (list, tuple)):
            for i, o in enumerate(out):
                memo[(id(node), i)] = o
        else:
            memo[(id(node), 0)] = out
    return [memo[(id(n), i)] for n, i in sym._heads]


# ----------------------------------------------------------------------
# deserialization
# ----------------------------------------------------------------------
def fromjson(json_str: str) -> Symbol:
    payload = json.loads(json_str)
    raw_nodes = payload["nodes"]
    nodes: List[_Node] = []
    for rn in raw_nodes:
        op = rn["op"]
        attrs = dict(rn.get("attrs", rn.get("param", {})) or {})
        node = _Node(None if op == "null" else op, rn["name"], [], attrs)
        nodes.append(node)
    for node, rn in zip(nodes, raw_nodes):
        node.inputs = [(nodes[i], idx) for i, idx, *_ in rn["inputs"]]
        if node.op is not None:
            node.num_outputs = _num_outputs_of(
                node.op, len(node.inputs), node.attrs)
    heads = payload.get("heads")
    if heads:
        return Symbol([(nodes[i], idx) for i, idx, *_ in heads])
    return Symbol([(nodes[-1], 0)])


load_json = fromjson


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return fromjson(f.read())


# ----------------------------------------------------------------------
# generated op namespace (mirrors mxtpu.nd, lazily built)
# ----------------------------------------------------------------------
_THIS = sys.modules[__name__]

# Reference behavior: NN ops auto-create their weight variables when not
# passed explicitly (``sym.FullyConnected(data, num_hidden=8, name='fc1')``
# creates ``fc1_weight``/``fc1_bias``) — what makes pure-symbolic model
# definitions (Module examples†) concise.  Slot names follow upstream.
_AUTO_VARS: Dict[str, List[str]] = {
    "FullyConnected": ["data", "weight", "bias"],
    "Convolution": ["data", "weight", "bias"],
    "Deconvolution": ["data", "weight", "bias"],
    "BatchNorm": ["data", "gamma", "beta", "moving_mean", "moving_var"],
    "BatchNormRelu": ["data", "gamma", "beta", "moving_mean",
                      "moving_var"],
    "BatchNormAddRelu": ["data", "addend", "gamma", "beta",
                         "moving_mean", "moving_var"],
    "LayerNorm": ["data", "gamma", "beta"],
    "InstanceNorm": ["data", "gamma", "beta"],
    "Embedding": ["data", "weight"],
    "SoftmaxOutput": ["data", "label"],
}


def _make_sym_fn(op_name: str):
    slots = _AUTO_VARS.get(op_name)

    def fn(*args, name: Optional[str] = None, **kwargs):
        syms = []
        for a in args:
            if isinstance(a, Symbol):
                syms.append(a)
            elif isinstance(a, (list, tuple)) and all(
                    isinstance(x, Symbol) for x in a):
                syms.extend(a)
            else:
                raise MXNetError(
                    f"sym.{op_name} takes Symbol inputs, got "
                    f"{type(a).__name__} (use nd for eager arrays)")
        if slots is not None:
            # Fill remaining slots IN ORDER: a keyword symbol binds to its
            # named slot; any earlier unfilled slot gets an auto-var (so
            # e.g. FullyConnected(data, bias=b) still auto-creates weight).
            node_name = name or _auto_name(op_name)
            n_expected = len(slots)
            if kwargs.get("no_bias") and "bias" in slots:
                n_expected -= 1
            for slot in slots[len(syms):n_expected]:
                if slot in kwargs and isinstance(kwargs[slot], Symbol):
                    syms.append(kwargs.pop(slot))
                elif slot == "label":
                    syms.append(var(f"{node_name}_label"))
                else:
                    syms.append(var(f"{node_name}_{slot}"))
            return _create(op_name, syms, kwargs, name=node_name)
        return _create(op_name, syms, kwargs, name=name)
    fn.__name__ = op_name
    fn.__qualname__ = op_name
    return fn


_seen = set()
for _op in list(OP_REGISTRY._entries.values()):
    for _n in (_op.name,) + _op.aliases:
        if _n not in _seen:
            _seen.add(_n)
            setattr(_THIS, _n, _make_sym_fn(_n))

# sym.Dropout omits the key input (drawn at eval time by nd.Dropout)
setattr(_THIS, "Dropout", _make_sym_fn("Dropout"))
setattr(_THIS, "dropout", getattr(_THIS, "Dropout"))
# same for the fused transformer epilogue
setattr(_THIS, "FusedResidualLayerNorm",
        _make_sym_fn("FusedResidualLayerNorm"))
