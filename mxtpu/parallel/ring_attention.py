"""Ring attention — sequence/context parallelism over a mesh axis.

New capability mandated by the north star (SURVEY.md §2.4 row SP/CP,
§5.7): the reference (2018-era) has nothing for long-context training;
its closest machinery is per-length bucketing.  Here the sequence axis
is sharded over a mesh axis and K/V blocks rotate around the ring via
``lax.ppermute`` while each device accumulates online-softmax partial
results for its local Q block — attention memory per device is
O(T/p · D), enabling sequences p× longer than one chip's HBM allows.

Collectives ride ICI: each of the p steps moves only the local K/V
block to the next neighbour, which XLA schedules as neighbour-to-
neighbour ``collective-permute`` (bandwidth-optimal on a torus).

The per-block math runs in f32 (softmax stability) with MXU matmuls;
fusing the per-block compute into the Pallas flash kernel is the
follow-up — the ring structure is identical.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30

__all__ = ["ring_attention"]


def _block_update(q, kb, vb, m, l, acc, scale, causal, my_idx, kv_idx,
                  t_local):
    """One online-softmax accumulation of q against a K/V block."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kb,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        row = jax.lax.broadcasted_iota(
            jnp.int32, s.shape[-2:], 0) + my_idx * t_local
        col = jax.lax.broadcasted_iota(
            jnp.int32, s.shape[-2:], 1) + kv_idx * t_local
        s = jnp.where(col <= row, s, _NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    # invariant: _NEG_INF is a FINITE sentinel, so exp(sentinel - m)
    # underflows to 0 for fully-masked blocks instead of producing
    # exp(-inf - -inf) = NaN — do not replace it with -jnp.inf
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vb.dtype), vb,
                    preferred_element_type=jnp.float32)
    acc_new = acc * alpha + pv
    return m_new, l_new, acc_new


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Attention with the sequence axis sharded over ``mesh[axis]``.

    q, k, v: (B, H, T, D) with T divisible by the axis size.  Returns
    (B, H, T, D) with the same sharding.  Semantics match
    ``kernels.attention_reference`` (tested to parity).
    """
    D = q.shape[-1]
    scale = float(sm_scale) if sm_scale is not None else 1.0 / (D ** 0.5)
    p_size = mesh.shape[axis]

    def local_fn(q_loc, k_loc, v_loc):
        # q_loc etc: (B, H, T/p, D) — this device's shard
        my_idx = lax.axis_index(axis)
        t_local = q_loc.shape[2]
        qf = q_loc.astype(jnp.float32)
        m = jnp.full(q_loc.shape[:3] + (1,), _NEG_INF, jnp.float32)
        l = jnp.zeros_like(m)
        acc = jnp.zeros(q_loc.shape[:3] + (q_loc.shape[3],),
                        jnp.float32)
        # mark the zero-init carries as device-varying so the fori_loop
        # carry types line up with the per-device accumulation (pcast
        # belongs to the new-jax VMA checker; older releases neither
        # have it nor need it — their check_rep pass is disabled below)
        pcast = getattr(lax, "pcast", None)
        if pcast is not None:
            m, l, acc = (pcast(a, (axis,), to="varying")
                         for a in (m, l, acc))
        perm = [(j, (j + 1) % p_size) for j in range(p_size)]

        def body(i, carry):
            m, l, acc, kb, vb = carry
            kv_idx = (my_idx - i) % p_size
            m, l, acc = _block_update(qf, kb.astype(jnp.float32),
                                      vb.astype(jnp.float32), m, l, acc,
                                      scale, causal, my_idx, kv_idx,
                                      t_local)
            kb = lax.ppermute(kb, axis, perm)
            vb = lax.ppermute(vb, axis, perm)
            return m, l, acc, kb, vb

        m, l, acc, _, _ = lax.fori_loop(
            0, p_size, body, (m, l, acc, k_loc, v_loc))
        safe = jnp.where(l == 0.0, 1.0, l)
        return (acc / safe).astype(q_loc.dtype)

    spec = P(None, None, axis, None)
    from . import _device_put_global, _mesh_is_multiprocess
    if _mesh_is_multiprocess(mesh):
        # cross-process mesh: host inputs must be placed as global
        # arrays (every process passes the same full value; jit cannot
        # implicitly device_put onto non-addressable shardings)
        q, k, v = (_device_put_global(a, mesh, spec)
                   for a in (q, k, v))
    from . import shard_map_compat
    fn = shard_map_compat(local_fn, mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, check=False)
    return fn(q, k, v)
