"""Mixture-of-Experts with expert parallelism (the ``ep`` mesh axis).

The reference era predates MoE; this is a new-capability subsystem
mandated by the north star (full dp/tp/pp/sp/**ep** sharding support).
Design is the Mesh-TensorFlow / Switch-Transformer capacity
formulation — the TPU-native shape-static way to route:

  gate     : (tokens, E) softmax over experts
  dispatch : (tokens, E, C) one-hot — token t is slot c of expert e
  combine  : dispatch * gate prob
  expert_in  = einsum('td,tec->ecd', x, dispatch)   # (E, C, D)
  expert_out = ffn_e(expert_in[e])                   # per expert
  y          = einsum('ecd,tec->td', expert_out, combine)

Everything is dense einsums over static shapes (no ragged gathers —
XLA tiles them onto the MXU), and expert parallelism is pure SPMD:
``expert_in``/``expert_out`` carry a ``P("ep")`` sharding constraint
on the expert axis, so GSPMD lowers the two einsums into all-to-all
dispatch/return collectives over ICI exactly like the reference
NCCL/MPI frameworks hand-code.  Tokens over capacity are dropped
(their combine weight is 0 and the residual path carries them) —
Switch semantics.
"""
from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

__all__ = ["moe_ffn", "switch_router", "MoEFFN"]


def switch_router(x2d, gate_w, capacity: int, *, key=None,
                  jitter: float = 0.0):
    """Top-1 (Switch) routing: returns (dispatch, combine, aux_loss).

    x2d: (T, D) tokens; gate_w: (D, E).
    dispatch: (T, E, C) one-hot float; combine = dispatch * gate_prob.
    aux_loss is the Switch load-balancing loss (mean fraction *
    mean router prob per expert, scaled by E).
    """
    T, D = x2d.shape
    E = gate_w.shape[1]
    logits = (x2d.astype(jnp.float32)
              @ gate_w.astype(jnp.float32))          # (T, E)
    if jitter > 0.0 and key is not None:
        logits = logits + jax.random.uniform(
            key, logits.shape, minval=-jitter, maxval=jitter)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)              # (T,)
    onehot = jax.nn.one_hot(expert, E,
                            dtype=jnp.float32)       # (T, E)
    # position of each token within its expert's queue (prefix count)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # (T, E), -1 ow
    keep = (pos >= 0) & (pos < capacity)
    pos_c = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    slot = jax.nn.one_hot(pos_c, capacity,
                          dtype=jnp.float32)         # (T, E, C)
    dispatch = slot * keep.astype(jnp.float32)[..., None]
    gate_p = jnp.sum(probs * onehot, axis=-1)        # (T,)
    combine = dispatch * gate_p[:, None, None]
    # load-balancing aux (Switch eq. 4): E * sum_e f_e * P_e
    frac = jnp.mean(onehot, axis=0)
    mean_p = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_p)
    return dispatch, combine, aux


def moe_ffn(x, gate_w, w1, b1, w2, b2, *, capacity_factor: float = 1.25,
            mesh: Optional[Mesh] = None, ep_axis: str = "ep",
            activation: Callable = jax.nn.relu, key=None,
            jitter: float = 0.0):
    """Switch-MoE feed-forward.  x: (..., T, D) or (T, D);
    per-expert params w1: (E, D, H), b1: (E, H), w2: (E, H, D),
    b2: (E, D).  Returns (y, aux_loss).

    With ``mesh`` given, the expert axis of the dispatched activations
    is shard-constrained to ``ep_axis`` — GSPMD inserts the
    all-to-alls; each device computes only its local experts."""
    orig_shape = x.shape
    D = orig_shape[-1]
    x2d = x.reshape(-1, D)
    T = x2d.shape[0]
    E = w1.shape[0]
    capacity = max(int(math.ceil(T / E * capacity_factor)), 1)
    dispatch, combine, aux = switch_router(
        x2d, gate_w, capacity, key=key, jitter=jitter)

    cdt = x.dtype
    expert_in = jnp.einsum("td,tec->ecd", x2d.astype(jnp.float32),
                           dispatch).astype(cdt)     # (E, C, D)

    def constrain(v):
        if mesh is not None:
            v = jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, P(ep_axis)))
        return v

    # the PARAMETERS shard over ep too — expert parallelism's whole
    # point is that each device stores and computes only its local
    # experts' weights (r4 review: constraining activations alone
    # leaves every device holding all E experts' parameters)
    w1c = constrain(w1.astype(cdt))
    b1c = constrain(b1.astype(cdt))
    w2c = constrain(w2.astype(cdt))
    b2c = constrain(b2.astype(cdt))
    expert_in = constrain(expert_in)
    h = jnp.einsum("ecd,edh->ech", expert_in, w1c) \
        + b1c[:, None, :]
    h = activation(h)
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2c) \
        + b2c[:, None, :]
    expert_out = constrain(expert_out)
    y = jnp.einsum("ecd,tec->td", expert_out.astype(jnp.float32),
                   combine).astype(cdt)
    return y.reshape(orig_shape), aux


class MoEFFN:
    """Parameter container + apply for a Switch-MoE FFN (functional
    API — compose inside jitted train steps)."""

    def __init__(self, units: int, hidden: int, num_experts: int,
                 capacity_factor: float = 1.25, seed: int = 0):
        k = jax.random.PRNGKey(seed)
        ks = jax.random.split(k, 5)
        E, D, H = num_experts, units, hidden
        s1 = 1.0 / math.sqrt(D)
        s2 = 1.0 / math.sqrt(H)
        self.gate_w = jax.random.normal(ks[0], (D, E)) * s1
        self.w1 = jax.random.normal(ks[1], (E, D, H)) * s1
        self.b1 = jnp.zeros((E, H))
        self.w2 = jax.random.normal(ks[2], (E, H, D)) * s2
        self.b2 = jnp.zeros((E, D))
        self.capacity_factor = capacity_factor

    def params(self):
        return (self.gate_w, self.w1, self.b1, self.w2, self.b2)

    def apply(self, params, x, mesh=None, ep_axis="ep", key=None,
              jitter: float = 0.0):
        gate_w, w1, b1, w2, b2 = params
        return moe_ffn(x, gate_w, w1, b1, w2, b2,
                       capacity_factor=self.capacity_factor,
                       mesh=mesh, ep_axis=ep_axis, key=key,
                       jitter=jitter)
