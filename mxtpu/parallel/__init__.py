# mxlint: hot-path
"""``mxtpu.parallel`` — SPMD execution over a device mesh.

This is the TPU-native replacement for the reference's multi-device
machinery (``DataParallelExecutorGroup``†, KVStore ``device``/``nccl``
reduction, ``src/kvstore/comm.h``†): instead of per-device executors
plus explicit push/pull reductions, the WHOLE training step —
forward, backward, gradient all-reduce, optimizer update, running-stat
(aux) updates — is compiled into ONE XLA executable over a
``jax.sharding.Mesh``.  The batch is sharded over the ``dp`` axis;
parameters are replicated (or sharded per ``param_spec_fn`` for tensor
parallelism); XLA inserts the all-reduce/all-gather collectives and
schedules them over ICI (SURVEY.md §2.4, §5.8).

``KVStore`` (``mxtpu.kvstore``) remains as the API-parity facade; this
module is the mechanism.

ZeRO-1 (default on single-process ``dp`` meshes, kill switch
``MXTPU_ZERO=0``): instead of all-reducing full gradients and keeping
a replicated optimizer-state copy per device, the step reduce-scatters
each (shape, dtype) bucket's gradients, updates the 1/dp state shard
the device owns, and all-gathers the fresh params — the in-graph form
of the reference ``dist_sync`` server-side update
(``kvstore_dist_server.h``†), cutting optimizer HBM ~dp× at equal
total comm bytes (rs + ag == ar).
"""
from __future__ import annotations

import contextlib
import weakref

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import amp as _amp_mod
from ..base import MXNetError
from .. import cache as cache_mod
from .. import guards
from .. import knobs
from .. import obs
from .. import profiler as _prof
from .. import optimizer as opt_mod
from ..ndarray import random as _rnd
from ..ndarray.ndarray import NDArray
from ..ops.registry import get_op

__all__ = ["make_mesh", "shard_batch", "replicate", "TrainStep",
           "build_train_step", "plan_zero_buckets",
           "Mesh", "PartitionSpec", "P",
           "spmd_pipeline", "stack_stage_params", "PipelineTrainStep",
           "build_pipeline_train_step", "snapshot_params",
           "restore_params", "moe"]

PartitionSpec = P

from . import moe  # noqa: E402  (expert parallelism — the ep axis)


def snapshot_params(net):
    """Parameter values of ``net`` in collect_params() order (a list
    of numpy arrays).  Pairs with :func:`restore_params` to clone one
    net's init into another INSTANCE of the same architecture: block
    auto-naming gives every instance fresh prefixes, so values must be
    carried by position, not name — keeping that subtle assumption in
    one place (r4 review)."""
    # mxlint: sync-point — deliberate checkpoint-style host snapshot
    return [p.data().asnumpy() for p in net.collect_params().values()]


def restore_params(net, values):
    """Set ``net``'s parameters from a :func:`snapshot_params` list
    (same architecture, any instance).  The net must already be
    shape-initialised (run one forward first for deferred blocks)."""
    from .. import nd as _nd
    params = list(net.collect_params().values())
    if len(params) != len(values):
        raise ValueError(
            f"parameter count mismatch: net has {len(params)}, "
            f"snapshot has {len(values)} — not the same architecture")
    for p, v in zip(params, values):
        p.set_data(_nd.array(v))


def make_mesh(axes: Optional[Dict[str, int]] = None, devices=None) -> Mesh:
    """Build a named device mesh.  ``axes`` maps axis name → size, e.g.
    ``{'dp': 4, 'mp': 2}``; defaults to pure data parallelism over all
    visible devices."""
    devices = devices if devices is not None else jax.devices()
    if axes is None:
        axes = {"dp": len(devices)}
    names = tuple(axes)
    sizes = tuple(axes[n] for n in names)
    total = int(np.prod(sizes))
    if total > len(devices):
        raise MXNetError(
            f"mesh {axes} needs {total} devices, have {len(devices)}")
    dev_array = np.asarray(  # mxlint: disable=host-sync — device objects, not data
        devices[:total]).reshape(sizes)
    return Mesh(dev_array, names)


# weakref-keyed so entries die with their mesh (an id()-keyed dict
# could hand a stale flag to a new mesh reusing the address)
_MESH_MP_CACHE: "weakref.WeakKeyDictionary[Mesh, bool]" = \
    weakref.WeakKeyDictionary()


def _mesh_is_multiprocess(mesh: Mesh) -> bool:
    # O(devices) scan once per mesh, not per step (real multi-host
    # meshes have thousands of devices)
    try:
        flag = _MESH_MP_CACHE.get(mesh)
    except TypeError:  # unhashable/unweakrefable mesh variant
        me = jax.process_index()
        return any(d.process_index != me for d in mesh.devices.flat)
    if flag is None:
        me = jax.process_index()
        flag = any(d.process_index != me for d in mesh.devices.flat)
        _MESH_MP_CACHE[mesh] = flag
    return flag


def shard_map_compat(fn, mesh: Mesh, in_specs, out_specs,
                     check=None):
    """``shard_map`` across jax releases: new jax exposes
    ``jax.shard_map`` (``check_vma``), older releases only
    ``jax.experimental.shard_map.shard_map`` (``check_rep``).
    ``check=None`` keeps the library default."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check is None else {"check_vma": check}
        return sm(fn, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map
    kw = {} if check is None else {"check_rep": check}
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **kw)


def _device_put_global(raw, mesh: Mesh, spec) -> jax.Array:
    """Place a value onto a mesh sharding, including meshes that span
    processes.  Host values: every process passes the SAME full value
    (each takes only the rows its devices own), so single- and
    multi-process code paths stay identical — `jax.device_put` alone
    would demand cross-host transfers the CPU/gloo transport refuses.
    Already-global jax.Arrays are passed through (or resharded
    in-graph) rather than fetched to host."""
    sh = NamedSharding(mesh, spec)
    if not _mesh_is_multiprocess(mesh):
        return jax.device_put(raw, sh)
    if isinstance(raw, jax.Array):
        if raw.sharding == sh:
            return raw
        if not raw.is_fully_addressable:
            # global array with a different layout: reshard with an
            # in-graph identity (XLA inserts the collectives).  Cold
            # placement path: one compile per (shape, sharding) is the
            # cost of resharding, not churn.
            return jax.jit(  # mxlint: disable=retrace-inline-jit
                lambda a: a, out_shardings=sh)(raw)
    # mxlint: sync-point — global placement fetches host values once
    host = np.asarray(raw)
    idx_map = sh.addressable_devices_indices_map(host.shape)
    shards = [jax.device_put(host[idx], d)
              for d, idx in idx_map.items()]
    return jax.make_array_from_single_device_arrays(host.shape, sh,
                                                    shards)


def shard_batch(mesh: Mesh, arr, axis_name: str = "dp", batch_axis: int = 0):
    """Place an array batch-sharded over a mesh axis."""
    raw = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
    spec = [None] * raw.ndim
    spec[batch_axis] = axis_name
    out = _device_put_global(raw, mesh, P(*spec))
    return NDArray(out, None, _placed=True) if isinstance(arr, NDArray) \
        else out


def replicate(mesh: Mesh, arr):
    """Place an array fully replicated over the mesh."""
    raw = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
    out = _device_put_global(raw, mesh, P())
    return NDArray(out, None, _placed=True) if isinstance(arr, NDArray) \
        else out


# functional optimizer rules for the compiled step now live in
# ``mxtpu.optimizer.functional`` (the ZeRO-1 sharded path needs their
# stacked state-init shapes); the underscored aliases remain this
# package's internal import surface (pipeline.py).
from ..optimizer.functional import (adam_bias_correction as  # noqa: E402
                                    _adam_bias_correction,
                                    opt_rule as _opt_rule)


def plan_zero_buckets(sigs, dp: int, stack_axis_only: bool = False):
    """Plan the ZeRO-1 bucket layout for one optimizer step — pure
    geometry, no arrays (also the provenance of BASELINE.md's
    optimizer-memory table and the bench accounting).

    ``sigs`` is a list of ``(shape, dtype_str)`` per trainable
    parameter, in step order.  Parameters bucket by (shape, dtype) —
    the same buckets MXTPU_BATCHED_OPT stacks — and each bucket picks
    ONE axis of its stacked ``(n,) + shape`` array to shard over
    ``dp``: the axis minimizing relative zero-padding (ties prefer the
    stack axis, whose lr/wd bookkeeping is simplest).  Singleton
    buckets (n=1, e.g. an embedding table) would waste (dp-1)/dp of a
    full row if only the stack axis were allowed — axis choice is what
    makes the ≤ replicated/dp × 1.15 footprint hold.  LAMB buckets are
    pinned to the stack axis (``stack_axis_only=True``): its per-slice
    trust-ratio norms reduce within a bucket row, which stays
    device-local only when whole rows live on one device.

    Zero-padding is numerically inert for every supported rule: a
    padded region starts with w = g = state = 0 and every rule maps
    zeros to zeros (LAMB's padded rows see wnorm = rnorm = 0 → trust
    ratio 1.0, still updating 0 by 0).

    Returns a list of dicts: ``jidx`` (positions within the trainable
    tuple), ``shape``/``dtype`` (per param), ``stacked_shape``,
    ``axis`` (shard axis of the stacked array; 0 = stack axis),
    ``pad`` (zero rows appended on that axis), ``padded_shape``,
    ``rows`` (local extent per device), ``param_bytes`` (logical,
    unpadded) and ``padded_bytes``."""
    if dp < 1:
        raise MXNetError(f"plan_zero_buckets needs dp >= 1, got {dp}")
    by_sig: Dict[Tuple, List[int]] = {}
    for j, (shape, dt) in enumerate(sigs):
        by_sig.setdefault((tuple(shape), str(dt)), []).append(j)
    buckets = []
    for (shape, dt), js in by_sig.items():
        stacked_shape = (len(js),) + shape
        best = None
        cands = [0] if stack_axis_only else range(len(stacked_shape))
        for ax in cands:
            size = stacked_shape[ax]
            pad = (-size) % dp
            key = (pad / size, ax)
            if best is None or key < best[0]:
                best = (key, ax, pad)
        _, axis, pad = best
        padded = list(stacked_shape)
        padded[axis] += pad
        itemsize = jnp.dtype(dt).itemsize
        buckets.append({
            "jidx": js, "shape": shape, "dtype": dt,
            "stacked_shape": stacked_shape, "axis": axis, "pad": pad,
            "padded_shape": tuple(padded),
            "rows": padded[axis] // dp,
            "param_bytes": int(np.prod(stacked_shape, dtype=np.int64))
            * itemsize,
            "padded_bytes": int(np.prod(padded, dtype=np.int64))
            * itemsize,
        })
    return buckets


def _mem_stats(compiled):
    """``memory_analysis()`` of a compiled program as a plain dict
    (None when the backend doesn't report) — delegates to the ONE
    memory analyzer, :func:`mxtpu.analysis.memflow.mem_stats`, which
    owns the ``hbm_peak`` = temp + argument convention."""
    from mxtpu.analysis import memflow
    return memflow.mem_stats(compiled)


class TrainStep:
    """One fused XLA executable per (shape signature): fwd + bwd +
    collectives + optimizer + aux writeback.  Call with (x, y) batches;
    parameters update in place (rebound buffers).

    **ZeRO-1** (``zero``): on a single-process mesh whose ``dp_axis``
    has size > 1 (and no ``param_spec_fn``), the step defaults to
    ZeRO-1 sharded optimizer states: gradients are reduce-scattered
    per (shape, dtype) bucket (see :func:`plan_zero_buckets`), each
    device updates only the 1/dp state shard it owns, and the fresh
    params are all-gathered back to replicated — optimizer HBM drops
    ~dp× at the same total comm bytes as the all-reduce it replaces.
    ``zero=0`` (or ``MXTPU_ZERO=0`` in the environment) restores the
    replicated GSPMD path; ``zero=1`` insists and raises where ZeRO
    can't apply.  The ZeRO step is an explicit ``shard_map`` over
    ``dp_axis``, with three contract changes vs the GSPMD path:

    * the batch dim must divide the dp size (error otherwise);
    * the loss must reduce as a mean over examples (the gluon losses
      do): the global loss is the mean of per-shard means.  BatchNorm
      accumulates per-shard batch statistics (averaged into the
      running stats — the reference's non-sync DDP behaviour) and
      dropout draws an independent stream per shard;
    * optimizer updates always run bucket-stacked (the ZeRO exchange
      is per bucket), regardless of ``MXTPU_BATCHED_OPT``.

    ``save_states`` always writes the canonical per-parameter layout
    (gather-on-save), so checkpoints are interchangeable between ZeRO
    and replicated steps in both directions."""

    def __init__(self, net, loss_fn, optimizer, mesh: Optional[Mesh] = None,
                 dp_axis: str = "dp", batch_axis: int = 0,
                 param_spec_fn: Optional[Callable] = None, donate=True,
                 compute_dtype=None, cast_batch=True, zero=None,
                 cache: Any = "auto", amp=None):
        from ..gluon.block import _traced_forward
        self._traced_forward = _traced_forward
        self.net = net
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.batch_axis = batch_axis
        self.param_spec_fn = param_spec_fn
        self.donate = donate
        # mixed precision: forward/backward in compute_dtype (bf16 puts
        # the matmuls/convs on the MXU's fast path), master weights,
        # loss, and optimizer state stay f32 — the reference's
        # multi_precision=True AMP recipe, compiled into the one program.
        # cast_batch=False keeps the raw batch dtype — REQUIRED when x
        # carries integer ids in a float array (Embedding inputs):
        # bf16 can't represent ids > 256 exactly, so casting would
        # silently fetch wrong rows; the bf16 embedding table already
        # makes everything downstream compute in bf16.
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        self.cast_batch = cast_batch
        # policy-driven AMP (mxtpu.amp): params stored bf16 over f32
        # masters, contraction-only bf16 casts from the committed
        # policy, dynamic loss scaling.  MXTPU_AMP=0 forces this off
        # everywhere; the off path traces the exact pre-AMP program.
        self.amp = _amp_mod.resolve(amp)
        if self.amp and self.compute_dtype is not None:
            raise MXNetError(
                "amp and compute_dtype are two mixed-precision "
                "recipes — pass one (amp supersedes compute_dtype)")
        self._amp_state = None
        if self.amp:
            (self._amp_scaler, self._amp_init_scale,
             self._amp_window) = _amp_mod.scaler_config()
        else:
            self._amp_scaler = False
        self._compiled = {}
        self._params: Optional[List] = None
        self._t = 0
        self._last_mem: Optional[Dict[str, int]] = None
        self.zero = self._decide_zero(zero)
        # Guard rails (mxtpu.guards, MXTPU_GUARDS=1): enabled() is read
        # ONCE here so the disabled hot path costs a single cached-bool
        # test per step (bench.py asserts the zero-overhead contract).
        self._guards = guards.enabled()
        self._churn = guards.ChurnDetector(
            f"TrainStep[{type(net).__name__}]")
        # ISSUE 8: obs registry instruments — step wall time, compile
        # events, and the compiler-estimated FLOPs/step (the MFU
        # numerator, set by cost_analysis).  Same cached-bool contract
        # as _guards: MXTPU_OBS=0 costs one bool test per step.
        self._obs = obs.enabled()
        _entry = f"TrainStep[{type(net).__name__}]"
        self._m_step = obs.histogram(
            "mxtpu_train_step_seconds",
            "Wall time per optimizer step (dispatch + writeback).",
            labels=("entry",)).labels(entry=_entry)
        self._m_compile = obs.counter(
            "mxtpu_train_compile_total",
            "TrainStep executable builds (one per new signature).",
            labels=("entry",)).labels(entry=_entry)
        self._m_flops = obs.gauge(
            "mxtpu_train_flops_per_step",
            "XLA cost_analysis FLOPs of the one-step program "
            "(MFU numerator; 0 until cost_analysis runs).",
            labels=("entry",)).labels(entry=_entry)
        # ISSUE 13: persistent executable cache — the AOT build
        # becomes load-or-compile, with the cold-vs-disk split
        # labeled on the build-time histogram and disk hits counted
        # next to ChurnDetector's miss counter.
        self._cache = cache_mod.default_cache() if cache == "auto" \
            else cache
        _h = obs.histogram(
            "mxtpu_train_compile_seconds",
            "AOT TrainStep build wall time (source=cold: XLA "
            "compile; source=disk: verified load from the persistent "
            "cache).", labels=("entry", "source"))
        self._m_compile_s = {
            src: _h.labels(entry=_entry, source=src)
            for src in ("cold", "disk")}
        self._m_cache_hit = obs.counter(
            "mxtpu_compile_cache_hit_total",
            "In-process compile-cache misses served from the "
            "persistent disk cache instead of XLA.",
            labels=("entry",)).labels(entry=_entry)
        if self.amp:
            self._m_amp_scale = obs.gauge(
                "mxtpu_amp_loss_scale",
                "Current dynamic loss scale (1.0 when scaling is "
                "disabled via MXTPU_AMP_LOSS_SCALE=0).",
                labels=("entry",)).labels(entry=_entry)
            self._m_amp_skipped = obs.gauge(
                "mxtpu_amp_skipped_steps",
                "Optimizer steps skipped because non-finite gradients "
                "tripped the loss-scaler backoff.",
                labels=("entry",)).labels(entry=_entry)

    def _decide_zero(self, zero) -> bool:
        """Resolve the ZeRO-1 mode: ``MXTPU_ZERO=0`` is the global
        kill switch, ``zero=0/1`` the per-step override, and the auto
        default is ON exactly when the mechanism applies — a
        single-process mesh with a >1-sized ``dp_axis`` and no
        tensor-parallel ``param_spec_fn``."""
        env = knobs.get("MXTPU_ZERO").strip().lower()
        if env in ("0", "off", "false"):
            return False
        if zero is not None and not zero:
            return False
        forced = bool(zero)  # mxlint: disable=host-sync — Python arg
        if self.mesh is None or self.dp_axis not in self.mesh.shape \
                or self.mesh.shape[self.dp_axis] <= 1:
            if forced:
                raise MXNetError(
                    "zero=1 needs a mesh whose dp axis "
                    f"({self.dp_axis!r}) has size > 1")
            return False
        if self.param_spec_fn is not None:
            if forced:
                raise MXNetError(
                    "zero=1 does not compose with param_spec_fn "
                    "(tensor parallelism) yet — drop one of the two")
            return False
        if _mesh_is_multiprocess(self.mesh):
            if forced:
                raise MXNetError(
                    "zero=1 needs a single-process mesh (multi-host "
                    "ZeRO is pending transport validation)")
            return False
        return True

    def _amp_extra(self) -> tuple:
        """Trailing loss-scaler argument for the step callables —
        empty when AMP (or scaling) is off, so the off path keeps the
        exact pre-AMP signature and traced program."""
        if self._amp_scaler and self._amp_state is not None:
            return (self._amp_state,)
        return ()

    # -- parameter bookkeeping -----------------------------------------
    def _collect(self, x):
        if self._params is None:
            import mxtpu.autograd as autograd
            if not all(p._data is not None
                       for p in self.net.collect_params().values()):
                with autograd.pause():
                    self.net(x)  # deferred shape inference
            allp = list(self.net.collect_params().values())
            self._params = allp
            self._train_idx = [i for i, p in enumerate(allp)
                               if p.grad_req != "null"]
            # Honour per-parameter lr_mult/wd_mult (Parameter attrs plus
            # any name-keyed overrides set on the optimizer) without
            # touching the optimizer's own param_dict/idx2name — those
            # may be indexed by a different ordering (e.g. a shared
            # gluon.Trainer instance).
            self._opt_init, self._opt_update = _opt_rule(self.optimizer)
            if self.amp:
                # fp32 masters by construction: trainable f32 params
                # are STORED bf16 from here on (halving param comm and
                # the all-gather under ZeRO-1), and the optimizer's
                # multi-precision rule — which seeds a master copy for
                # every sub-f32 weight — keeps the f32 truth in the
                # optimizer state.  Aux-named params (BN running
                # stats) are never trainable and stay f32.
                from ..symbol import _is_aux_name
                for i in self._train_idx:
                    p = allp[i]
                    v = p._data._data
                    if (v.dtype == jnp.float32
                            and not _is_aux_name(p.name)):
                        p._data._data = v.astype(jnp.bfloat16)
            if self.mesh is not None:
                for p in allp:
                    spec = None
                    if self.param_spec_fn is not None:
                        spec = self.param_spec_fn(p)
                    p._data._data = _device_put_global(
                        p._data._data, self.mesh,
                        spec if spec is not None else P())
            if self.zero:
                self._init_zero_state()
            else:
                self._opt_state = tuple(
                    self._opt_init(self._params[i]._data._data)
                    for i in self._train_idx)
                if self.mesh is not None:
                    self._opt_state = jax.tree_util.tree_map(
                        lambda v: _device_put_global(v, self.mesh, P()),
                        self._opt_state)
            if self._amp_scaler and self._amp_state is None:
                st = _amp_mod.scaler_init(self._amp_init_scale)
                if self.mesh is not None:
                    st = tuple(_device_put_global(v, self.mesh, P())
                               for v in st)
                self._amp_state = st

    def _init_zero_state(self):
        """ZeRO-1 state: one stacked, padded array per (shape, dtype)
        bucket, carried dp-sharded on the bucket's planned axis.
        ``out_shardings`` makes XLA materialize each device's slice
        directly — no transient replicated copy exists at any point."""
        mesh, dp_axis = self.mesh, self.dp_axis
        dp = mesh.shape[dp_axis]
        params = self._params
        sigs = [(params[i]._data._data.shape,
                 str(params[i]._data._data.dtype))
                for i in self._train_idx]
        lamb = isinstance(self.optimizer, opt_mod.LAMB)
        self._zero_dp = dp
        self._zero_buckets = plan_zero_buckets(sigs, dp,
                                               stack_axis_only=lamb)
        specs, shardings = [], []
        for b in self._zero_buckets:
            leaf_shapes = jax.eval_shape(
                lambda b=b: self._opt_init(
                    jnp.zeros(b["padded_shape"], b["dtype"]),
                    stacked=True))
            bspecs = []
            for leaf in leaf_shapes:
                # full-rank leaves shard on the planned axis; rank-1
                # per-row leaves (LAMB's t) ride the stack axis, which
                # is the planned axis whenever they exist
                s = [None] * len(leaf.shape)
                s[b["axis"] if b["axis"] < len(leaf.shape) else 0] = \
                    dp_axis
                bspecs.append(P(*s))
            specs.append(tuple(bspecs))
            shardings.append(tuple(NamedSharding(mesh, sp)
                                   for sp in bspecs))
        self._zero_state_specs = tuple(specs)
        self._zero_state_shardings = tuple(shardings)
        buckets = self._zero_buckets
        opt_init = self._opt_init

        def init_all(train_vals):
            # init from the REAL stacked+padded weights, not zeros:
            # the multi-precision rule seeds its f32 master copies
            # here, and a zero master would erase every bf16 param on
            # the first step.  For f32 params every supported rule's
            # state is zeros_like regardless of w, so this is
            # value-identical to the old zeros-based init.
            out = []
            for b in buckets:
                w_s = jnp.stack([train_vals[j] for j in b["jidx"]])
                if b["pad"]:
                    widths = [(0, 0)] * w_s.ndim
                    widths[b["axis"]] = (0, b["pad"])
                    w_s = jnp.pad(w_s, widths)
                out.append(opt_init(w_s, stacked=True))
            return tuple(out)

        train_vals = tuple(self._params[i]._data._data
                           for i in self._train_idx)
        # one setup-time compile per TrainStep, not a hot path
        self._opt_state = jax.jit(  # mxlint: disable=retrace-inline-jit
            init_all,
            out_shardings=self._zero_state_shardings)(train_vals)

    def _build(self, key, x_raw, y_raw):
        params = self._params
        train_idx = self._train_idx
        frozen_idx = [i for i in range(len(params)) if i not in
                      set(train_idx)]
        n_param = len(params)
        loss_fn = self.loss_fn
        net = self.net
        traced_forward = self._traced_forward
        aux_box: Dict[str, Any] = {}

        compute_dtype = self.compute_dtype
        cast_batch = self.cast_batch
        amp_on = self.amp

        def loss_flat(train_vals, frozen_vals, key_data, x, y):
            pvals: List[Any] = [None] * n_param
            for i, v in zip(train_idx, train_vals):
                pvals[i] = v
            for i, v in zip(frozen_idx, frozen_vals):
                pvals[i] = v
            if compute_dtype is not None:
                # BN running stats (aux-named params) stay f32: their
                # EMA updates are too small for a bf16 mantissa
                from ..symbol import _is_aux_name
                pvals = [v.astype(compute_dtype)
                         if v is not None
                         and not _is_aux_name(params[i].name)
                         and jnp.issubdtype(v.dtype, jnp.floating)
                         else v
                         for i, v in enumerate(pvals)]
                if cast_batch and jnp.issubdtype(x.dtype, jnp.floating):
                    x = x.astype(compute_dtype)
            elif amp_on:
                # AMP entry upcast: every float param re-enters the
                # graph in f32, so ONLY the policy's allow-listed
                # contractions ever see bf16 (via the autocast scope
                # below) and every accumulating reduce stays f32 —
                # zero dtype-flow hazards by construction.  XLA folds
                # the bf16→f32→bf16 convert pair at the weight→dot
                # edges, and the AD transpose of this upcast is what
                # hands back bf16 grads at the param boundary.
                pvals = [v.astype(jnp.float32)
                         if v is not None
                         and jnp.issubdtype(v.dtype, jnp.floating)
                         and v.dtype != jnp.float32
                         else v
                         for v in pvals]
            scope = _amp_mod.autocast() if amp_on \
                else contextlib.nullcontext()
            with scope:
                raw_outs, _, aux_params, raw_aux = traced_forward(
                    net, params, pvals, [NDArray(x, None, _placed=True)],
                    True, key_data)
                outs = [NDArray(r, None, _placed=True) for r in raw_outs]
                # Multi-output nets hand ALL outputs to the loss (a
                # custom loss_fn must unpack them) rather than silently
                # training only the first head.
                pred = outs[0] if len(outs) == 1 else outs
                l = loss_fn(pred, NDArray(y, None, _placed=True))
            raw_l = l.data if isinstance(l, NDArray) else l
            aux_box["aux_params"] = aux_params
            # loss and aux (running stats) leave the bf16 region in f32
            if compute_dtype is not None:
                raw_aux = [a.astype(jnp.float32)
                           if jnp.issubdtype(a.dtype, jnp.floating)
                           else a for a in raw_aux]
            return jnp.mean(raw_l.astype(jnp.float32)), tuple(raw_aux)

        # Batched optimizer apply: bucket trainable params by
        # (shape, dtype) and update each bucket as ONE stacked op
        # instead of one HLO chain per parameter — a BERT-Large step
        # drops from ~400 per-param update chains to ~25 bucket
        # updates.  All rules are elementwise in (w, g, state) with
        # lr/wd entering as broadcast (n,1,..,1) scalars, so the
        # stacked apply is numerically identical to the per-param loop
        # (LAMB reduces its trust-ratio norms per slice).
        # MXTPU_BATCHED_OPT=0 restores the per-param loop.
        batched = knobs.get("MXTPU_BATCHED_OPT")
        groups: List[List[int]] = []
        if batched:
            by_sig: Dict[Tuple, List[int]] = {}
            for j, i in enumerate(train_idx):
                v = params[i]._data._data
                by_sig.setdefault((v.shape, str(v.dtype)), []).append(j)
            groups = list(by_sig.values())

        def apply_updates(train_vals, grads, opt_state, lrs, wds):
            n = len(train_vals)
            new_vals: List[Any] = [None] * n
            new_state: List[Any] = [None] * n
            if not batched:
                for j, (w, g, st) in enumerate(zip(train_vals, grads,
                                                   opt_state)):
                    new_vals[j], new_state[j] = self._opt_update(
                        w, g, st, lrs[j], wds[j])
                return tuple(new_vals), tuple(new_state)
            for group in groups:
                if len(group) == 1:
                    j = group[0]
                    new_vals[j], new_state[j] = self._opt_update(
                        train_vals[j], grads[j], opt_state[j],
                        lrs[j], wds[j])
                    continue
                w_s = jnp.stack([train_vals[j] for j in group])
                g_s = jnp.stack([grads[j] for j in group])
                n_leaves = len(opt_state[group[0]])
                st_s = tuple(
                    jnp.stack([opt_state[j][k] for j in group])
                    for k in range(n_leaves))
                # mxlint: disable=host-sync — Python index lists
                idx = jnp.asarray(np.asarray(group, np.int32))
                bshape = (len(group),) + (1,) * (w_s.ndim - 1)
                lr_s = jnp.take(lrs, idx).reshape(bshape)
                wd_s = jnp.take(wds, idx).reshape(bshape)
                w2_s, st2_s = self._opt_update(w_s, g_s, st_s, lr_s,
                                               wd_s, stacked=True)
                for a, j in enumerate(group):
                    new_vals[j] = w2_s[a]
                    new_state[j] = tuple(leaf[a] for leaf in st2_s)
            return tuple(new_vals), tuple(new_state)

        def step(train_vals, frozen_vals, opt_state, key_data, lrs, wds,
                 x, y):
            (loss, raw_aux), grads = jax.value_and_grad(
                loss_flat, has_aux=True)(train_vals, frozen_vals,
                                         key_data, x, y)
            new_vals, new_state = apply_updates(train_vals, grads,
                                                opt_state, lrs, wds)
            return loss, new_vals, new_state, raw_aux

        if amp_on and not self.zero:
            window = self._amp_window if self._amp_scaler else None

            if self._amp_scaler:
                def step(train_vals, frozen_vals, opt_state, key_data,  # noqa: F811
                         lrs, wds, x, y, scaler):
                    scale = scaler[0]

                    def scaled(tv, fv, kd, xx, yy):
                        l, aux = loss_flat(tv, fv, kd, xx, yy)
                        return l * scale.astype(l.dtype), (l, aux)

                    (_, (loss, raw_aux)), grads = jax.value_and_grad(
                        scaled, has_aux=True)(train_vals, frozen_vals,
                                              key_data, x, y)
                    # grads reach the param edge in bf16 (AD transpose
                    # of the entry upcast); unscale in f32 so the
                    # finite test and the optimizer see full range
                    grads = tuple(g.astype(jnp.float32) / scale
                                  for g in grads)
                    finite = _amp_mod.all_finite(grads)
                    new_vals, new_state = apply_updates(
                        train_vals, grads, opt_state, lrs, wds)
                    # skipped step: keep params AND state, back off
                    keep = lambda n, o: jnp.where(finite, n, o)  # noqa: E731
                    new_vals = tuple(map(keep, new_vals, train_vals))
                    new_state = jax.tree_util.tree_map(
                        keep, new_state, opt_state)
                    scaler2 = _amp_mod.scaler_update(scaler, finite,
                                                     window)
                    return loss, new_vals, new_state, raw_aux, scaler2
            else:
                def step(train_vals, frozen_vals, opt_state, key_data,  # noqa: F811
                         lrs, wds, x, y):
                    (loss, raw_aux), grads = jax.value_and_grad(
                        loss_flat, has_aux=True)(train_vals,
                                                 frozen_vals, key_data,
                                                 x, y)
                    grads = tuple(g.astype(jnp.float32) for g in grads)
                    new_vals, new_state = apply_updates(
                        train_vals, grads, opt_state, lrs, wds)
                    return loss, new_vals, new_state, raw_aux

        if self.zero:
            # ZeRO-1 replaces the whole sync+update path: an explicit
            # shard_map whose bucket exchange is reduce-scatter →
            # shard-local update → all-gather
            step = self._build_zero_step(loss_flat, x_raw, y_raw)

        train_vals = tuple(params[i]._data._data for i in train_idx)
        frozen_vals = tuple(params[i]._data._data for i in frozen_idx)
        zeros = jnp.zeros(len(train_idx), jnp.float32)
        donate = (0, 2) if self.donate else ()
        fitted = jax.jit(step, donate_argnums=donate)
        fn = fitted
        mem = None
        if (self.param_spec_fn is None and
                (self.mesh is None
                 or not _mesh_is_multiprocess(self.mesh))):
            # AOT-compile now: the lowering trace doubles as the aux
            # discovery pass (no separate eval_shape), the first step
            # pays no tracing, and memory_analysis / cost_analysis /
            # hlo_text come for free afterwards.  Multi-process meshes
            # keep the jit wrapper — its dispatch handles cross-host
            # arrays.  So does tensor-parallel (param_spec_fn): GSPMD
            # may return updated params with a compiler-chosen
            # sharding that differs from the placement the program was
            # lowered with, and AOT executables reject input shardings
            # that drift between steps.
            # ISSUE 13: load-or-compile through the persistent cache.
            # The lowering trace does double duty: it is the aux
            # discovery pass AND the cache fingerprint — the lowered
            # StableHLO text IS the traced program, so two nets with
            # identical container class and param signatures but
            # different computations (relu vs tanh, a loss built with
            # different flags, distinct lambdas) can never share a
            # key.  A verified disk hit skips only the XLA compile.
            lower_args = (train_vals, frozen_vals, self._opt_state,
                          jax.random.key_data(key), zeros, zeros,
                          x_raw, y_raw) + self._amp_extra()
            t0 = _prof._now_us()
            lowered = fitted.lower(*lower_args)
            source, ckey, loaded, cmeta = "cold", None, None, {}
            if self._cache is not None:
                ckey = self._train_cache_key(lowered, x_raw, y_raw)
                loaded, cmeta = self._cache.load(ckey, with_meta=True)
            if loaded is not None:
                source = "disk"
                fn = loaded
            else:
                fn = lowered.compile()
            mem = _mem_stats(fn)
            self._last_mem = mem
            from mxtpu import analysis
            if source == "cold":
                # audit (which may raise under MXTPU_HLO_AUDIT=2)
                # runs BEFORE the store: a failing program never
                # reaches disk
                analysis.maybe_audit(fn, label="TrainStep", mem=mem)
                if ckey is not None:
                    self._cache.store(ckey, fn,
                                      meta=analysis.audit_stamp())
            elif analysis.needs_reaudit(cmeta):
                # audit knobs are per-process: the entry's writer
                # audited less strictly than this process asks for,
                # so the reloaded program is re-audited here
                analysis.maybe_audit(fn, label="TrainStep", mem=mem)
            if self._obs:
                if source == "disk":
                    self._m_cache_hit.inc()
                self._m_compile_s[source].observe(
                    (_prof._now_us() - t0) / 1e6)
        else:
            # learn the aux structure without device work
            jax.eval_shape(step, train_vals, frozen_vals,
                           self._opt_state, jax.random.key_data(key),
                           zeros, zeros, x_raw, y_raw,
                           *self._amp_extra())
        # aux (BN running stats) positions inside the frozen tuple, in
        # aux_params order, for the scanned multi-step path to thread
        # them through the carry (None if an aux is somehow trainable)
        id2pos = {id(params[i]): j for j, i in enumerate(frozen_idx)}
        aux_pos = [id2pos.get(id(p)) for p in aux_box["aux_params"]]
        return {"fn": fn, "raw_step": step,
                "aux_params": aux_box["aux_params"],
                "frozen_idx": frozen_idx, "aux_pos": aux_pos,
                "mem": mem}

    def _build_zero_step(self, loss_flat, x_raw, y_raw):
        """The ZeRO-1 step body: an explicit ``shard_map`` over
        ``dp_axis``.  GSPMD's ReduceScatterCreator pass is GPU/TPU
        only, so sharding constraints alone cannot guarantee the
        reduce-scatter on every backend — the explicit collectives
        make the comm layout part of the program, testable from the
        HLO on the CPU virtual mesh."""
        from jax.experimental.shard_map import shard_map
        mesh, dp_axis = self.mesh, self.dp_axis
        dp = self._zero_dp
        buckets = self._zero_buckets
        opt_update = self._opt_update
        batch_axis = self.batch_axis
        amp_on = self.amp
        use_scaler = amp_on and self._amp_scaler
        window = self._amp_window if use_scaler else None

        def apply_zero(train_vals, grads, opt_state, lrs, wds):
            new_vals: List[Any] = [None] * len(train_vals)
            new_state = []
            me = lax.axis_index(dp_axis)
            for b, st in zip(buckets, opt_state):
                js, ax, pad, rows = (b["jidx"], b["axis"], b["pad"],
                                     b["rows"])
                w_s = jnp.stack([train_vals[j] for j in js])
                g_s = jnp.stack([grads[j] for j in js])
                orig = w_s.shape[ax]
                if pad:
                    widths = [(0, 0)] * w_s.ndim
                    widths[ax] = (0, pad)
                    w_s = jnp.pad(w_s, widths)
                    g_s = jnp.pad(g_s, widths)
                # THE ZeRO exchange: reduce-scatter replaces the
                # gradient all-reduce; this device owns rows
                # [me*rows, (me+1)*rows) of the padded bucket.
                # psum_scatter sums partial grads; /dp makes the mean
                # matching the mean-of-shard-means loss
                g_loc = lax.psum_scatter(g_s, dp_axis,
                                         scatter_dimension=ax,
                                         tiled=True)
                if amp_on:
                    # THE AMP comm payoff: grads arrive bf16 (half the
                    # per-step reduce-scatter bytes); accumulate the
                    # unscale/update math in f32 from here on
                    g_loc = g_loc.astype(jnp.float32)
                g_loc = g_loc / dp
                start = me * rows
                w_loc = lax.dynamic_slice_in_dim(w_s, start, rows, ax)
                # mxlint: disable=host-sync — Python index lists
                idxa = jnp.asarray(np.asarray(js, np.int32))
                if ax == 0:
                    # per-row lr/wd follow the rows this device owns
                    lr_v = jnp.take(lrs, idxa)
                    wd_v = jnp.take(wds, idxa)
                    if pad:
                        lr_v = jnp.pad(lr_v, (0, pad))
                        wd_v = jnp.pad(wd_v, (0, pad))
                    bshape = (rows,) + (1,) * (w_s.ndim - 1)
                    lr_b = lax.dynamic_slice_in_dim(
                        lr_v, start, rows, 0).reshape(bshape)
                    wd_b = lax.dynamic_slice_in_dim(
                        wd_v, start, rows, 0).reshape(bshape)
                else:
                    # inner-axis shard: every device sees every row
                    bshape = (len(js),) + (1,) * (w_s.ndim - 1)
                    lr_b = jnp.take(lrs, idxa).reshape(bshape)
                    wd_b = jnp.take(wds, idxa).reshape(bshape)
                w2_loc, st2 = opt_update(w_loc, g_loc, st, lr_b, wd_b,
                                         stacked=True)
                w2 = lax.all_gather(w2_loc, dp_axis, axis=ax,
                                    tiled=True)
                if pad:
                    w2 = lax.slice_in_dim(w2, 0, orig, axis=ax)
                for a, j in enumerate(js):
                    new_vals[j] = w2[a]
                new_state.append(st2)
            return tuple(new_vals), tuple(new_state)

        def apply_zero_amp(train_vals, grads, opt_state, lrs, wds,
                           scale):
            """Loss-scaled variant: phase 1 exchanges every bucket
            (bf16 reduce-scatter) and unscales in f32, then ONE global
            finite consensus gates phase 2's updates — every shard
            must agree to skip, or padded-row mismatches would
            desynchronize the replicated params."""
            new_vals: List[Any] = [None] * len(train_vals)
            new_state = []
            me = lax.axis_index(dp_axis)
            prep = []
            bad = jnp.zeros((), jnp.int32)
            for b, st in zip(buckets, opt_state):
                js, ax, pad = b["jidx"], b["axis"], b["pad"]
                w_s = jnp.stack([train_vals[j] for j in js])
                g_s = jnp.stack([grads[j] for j in js])
                orig = w_s.shape[ax]
                if pad:
                    widths = [(0, 0)] * w_s.ndim
                    widths[ax] = (0, pad)
                    w_s = jnp.pad(w_s, widths)
                    g_s = jnp.pad(g_s, widths)
                g_loc = lax.psum_scatter(g_s, dp_axis,
                                         scatter_dimension=ax,
                                         tiled=True)
                g_loc = g_loc.astype(jnp.float32) / dp / scale
                bad = bad + jnp.sum(
                    ~jnp.isfinite(g_loc)).astype(jnp.int32)
                prep.append((b, st, w_s, g_loc, orig))
            finite = lax.psum(bad, dp_axis) == 0
            for b, st, w_s, g_loc, orig in prep:
                js, ax, pad, rows = (b["jidx"], b["axis"], b["pad"],
                                     b["rows"])
                start = me * rows
                w_loc = lax.dynamic_slice_in_dim(w_s, start, rows, ax)
                # mxlint: disable=host-sync — Python index lists
                idxa = jnp.asarray(np.asarray(js, np.int32))
                if ax == 0:
                    lr_v = jnp.take(lrs, idxa)
                    wd_v = jnp.take(wds, idxa)
                    if pad:
                        lr_v = jnp.pad(lr_v, (0, pad))
                        wd_v = jnp.pad(wd_v, (0, pad))
                    bshape = (rows,) + (1,) * (w_s.ndim - 1)
                    lr_b = lax.dynamic_slice_in_dim(
                        lr_v, start, rows, 0).reshape(bshape)
                    wd_b = lax.dynamic_slice_in_dim(
                        wd_v, start, rows, 0).reshape(bshape)
                else:
                    bshape = (len(js),) + (1,) * (w_s.ndim - 1)
                    lr_b = jnp.take(lrs, idxa).reshape(bshape)
                    wd_b = jnp.take(wds, idxa).reshape(bshape)
                w2_loc, st2 = opt_update(w_loc, g_loc, st, lr_b, wd_b,
                                         stacked=True)
                # non-finite anywhere: keep shard params AND state
                keep = lambda n, o: jnp.where(finite, n, o)  # noqa: E731
                w2_loc = keep(w2_loc, w_loc)
                st2 = jax.tree_util.tree_map(keep, st2, st)
                w2 = lax.all_gather(w2_loc, dp_axis, axis=ax,
                                    tiled=True)
                if pad:
                    w2 = lax.slice_in_dim(w2, 0, orig, axis=ax)
                for a, j in enumerate(js):
                    new_vals[j] = w2[a]
                new_state.append(st2)
            return tuple(new_vals), tuple(new_state), finite

        def body(train_vals, frozen_vals, opt_state, key_data, lrs,
                 wds, x, y):
            me = lax.axis_index(dp_axis)
            # decorrelate dropout across shards (the GSPMD path gets
            # this for free from its globally-sharded RNG)
            kd = jax.random.key_data(jax.random.fold_in(
                jax.random.wrap_key_data(key_data), me))
            (loss, raw_aux), grads = jax.value_and_grad(
                loss_flat, has_aux=True)(train_vals, frozen_vals, kd,
                                         x, y)
            # loss_flat reduces over the LOCAL shard; equal shard
            # sizes make the mean of shard means the global mean
            loss = lax.psum(loss, dp_axis) / dp
            raw_aux = tuple(
                lax.pmean(a, dp_axis)
                if jnp.issubdtype(a.dtype, jnp.inexact) else a
                for a in raw_aux)
            new_vals, new_state = apply_zero(train_vals, grads,
                                             opt_state, lrs, wds)
            return loss, new_vals, new_state, raw_aux

        def body_amp(train_vals, frozen_vals, opt_state, key_data,
                     lrs, wds, x, y, scaler):
            me = lax.axis_index(dp_axis)
            kd = jax.random.key_data(jax.random.fold_in(
                jax.random.wrap_key_data(key_data), me))
            scale = scaler[0]

            def scaled(tv, fv, k2, xx, yy):
                l, aux = loss_flat(tv, fv, k2, xx, yy)
                return l * scale.astype(l.dtype), (l, aux)

            (_, (loss, raw_aux)), grads = jax.value_and_grad(
                scaled, has_aux=True)(train_vals, frozen_vals, kd,
                                      x, y)
            loss = lax.psum(loss, dp_axis) / dp
            raw_aux = tuple(
                lax.pmean(a, dp_axis)
                if jnp.issubdtype(a.dtype, jnp.inexact) else a
                for a in raw_aux)
            new_vals, new_state, finite = apply_zero_amp(
                train_vals, grads, opt_state, lrs, wds, scale)
            scaler2 = _amp_mod.scaler_update(scaler, finite, window)
            return loss, new_vals, new_state, raw_aux, scaler2

        xspec = [None] * x_raw.ndim
        xspec[batch_axis] = dp_axis
        yspec = [None] * max(y_raw.ndim, 1)
        if y_raw.ndim > batch_axis:
            yspec[batch_axis] = dp_axis
        in_specs = (P(), P(), self._zero_state_specs, P(), P(), P(),
                    P(*xspec), P(*yspec[:y_raw.ndim]))
        out_specs = (P(), P(), self._zero_state_specs, P())
        fn = body
        if use_scaler:
            fn = body_amp
            in_specs = in_specs + (P(),)
            out_specs = out_specs + (P(),)
        # check_rep=False: the rep checker can't infer that the tiled
        # all_gather output is replicated
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    # -- the hot call ----------------------------------------------------
    def _prep(self, x, y):
        """Collect params, place the batch on the mesh, and return
        ``(x_raw, y_raw, sig)`` — shared by __call__ and the
        introspection entry points."""
        # under a multi-process mesh, keep non-NDArray inputs as HOST
        # buffers: _device_put_global shards them directly, avoiding a
        # wasted H2D→D2H round trip through the default device
        mp = self.mesh is not None and _mesh_is_multiprocess(self.mesh)
        wrap = np.asarray if mp else jnp.asarray
        x_raw = x.data if isinstance(x, NDArray) else wrap(x)
        y_raw = y.data if isinstance(y, NDArray) else wrap(y)
        self._collect(x if isinstance(x, NDArray)
                      else NDArray(x_raw, None, _placed=True))
        if self.zero and x_raw.shape[self.batch_axis] % self._zero_dp:
            raise MXNetError(
                f"ZeRO-1 shards the batch over dp={self._zero_dp}; "
                f"batch dim {x_raw.shape[self.batch_axis]} is not "
                f"divisible (pad the batch, or pass zero=0)")
        if self.mesh is not None:
            spec = [None] * x_raw.ndim
            spec[self.batch_axis] = self.dp_axis
            x_raw = _device_put_global(x_raw, self.mesh, P(*spec))
            yspec = [None] * max(y_raw.ndim, 1)
            yspec[self.batch_axis] = self.dp_axis
            y_raw = _device_put_global(y_raw, self.mesh,
                                       P(*yspec[:y_raw.ndim]))
        sig = (x_raw.shape, str(x_raw.dtype), y_raw.shape,
               str(y_raw.dtype))
        return x_raw, y_raw, sig

    def _train_cache_key(self, lowered, x_raw, y_raw):
        """Persistent-cache key of the AOT one-step program (ISSUE
        13): the model component hashes the LOWERED StableHLO text —
        the traced computation itself, the same program-is-the-
        fingerprint rule ModelRunner applies to its symbol graph — so
        everything that shapes the compiled step (architecture and
        activations, loss flags/lambdas, optimizer rule and baked-in
        hyperparams, precision/donation, ZeRO layout) is fingerprinted
        by construction; class names and param signatures alone could
        alias two different programs.  Weight/optimizer VALUES enter
        the text only as shapes (they are runtime arguments), and
        debug locations stay off (``as_text()`` default) so the text
        is checkout-independent.  The environment components (jax
        version, backend, contract hash, salt) are added by
        ``ExecutableCache.key``."""
        import hashlib
        prog = hashlib.sha256(
            lowered.as_text().encode()).hexdigest()[:24]
        mesh = "none" if self.mesh is None else \
            str(sorted(self.mesh.shape.items()))
        shape = str(((tuple(x_raw.shape), str(x_raw.dtype)),
                     (tuple(y_raw.shape), str(y_raw.dtype))))
        # net/opt class names ride along as debuggable context in the
        # entry header (the program hash already subsumes them)
        return self._cache.key(
            model=prog, shape=shape, mesh=mesh,
            device=getattr(jax.devices()[0], "device_kind", "unknown"),
            net=type(self.net).__name__,
            opt=type(self.optimizer).__name__)

    def _entry_for(self, x_raw, y_raw, sig, key):
        entry = self._compiled.get(sig)
        if entry is None:
            if self._guards:
                self._churn.note_compile(sig)
            if self._obs:
                self._m_compile.inc()
            entry = self._build(key, x_raw, y_raw)
            self._compiled[sig] = entry
        return entry

    def _commit_small(self, *vals):
        """AOT executables validate input shardings — commit the small
        per-step scalars (lr/wd vectors, RNG key data) to the mesh
        replicated layout (single-process meshes only; multi-process
        keeps the jit path whose dispatch handles placement)."""
        if self.mesh is None or _mesh_is_multiprocess(self.mesh):
            return vals
        rs = NamedSharding(self.mesh, P())
        return tuple(jax.device_put(v, rs) for v in vals)

    def __call__(self, x, y):
        x_raw, y_raw, sig = self._prep(x, y)
        key = _rnd._next_key(None)
        entry = self._entry_for(x_raw, y_raw, sig, key)
        self._t += 1
        lrs, wds = self._lrs_wds()
        lrs, wds, kd = self._commit_small(lrs, wds,
                                          jax.random.key_data(key))
        params = self._params
        train_vals = tuple(params[i]._data._data for i in self._train_idx)
        frozen_vals = tuple(params[i]._data._data
                            for i in entry["frozen_idx"])
        if self._guards:
            self._churn.note_call()
        t0 = _prof._now_us() if self._obs else 0.0
        with guards.no_implicit_transfers(self._guards):
            out = entry["fn"](
                train_vals, frozen_vals, self._opt_state,
                kd, lrs, wds, x_raw, y_raw, *self._amp_extra())
        loss, new_vals, new_state, raw_aux = out[:4]
        if self._amp_scaler:
            self._amp_state = out[4]
        for i, v in zip(self._train_idx, new_vals):
            params[i]._data._data = v
        self._opt_state = new_state
        for p, v in zip(entry["aux_params"], raw_aux):
            p._data._data = v
        if self._obs:
            self._m_step.observe((_prof._now_us() - t0) / 1e6)
        return NDArray(loss, None, _placed=True)

    # -- bulked execution -------------------------------------------------
    def run_steps(self, x, y, steps: int, reuse_batch: bool = False):
        """Run ``steps`` optimizer steps in ONE compiled program via
        ``lax.scan`` over microbatches — the TPU-native form of the
        reference's bulked graph execution (``MXNET_EXEC_BULK_EXEC_
        TRAIN``†, ``src/executor/graph_executor.cc`` bulking): host
        dispatch cost is paid once per ``steps`` instead of per step.

        ``x``/``y`` carry ``steps`` microbatches stacked on the batch
        axis (leading dim ``steps * B``), or — with
        ``reuse_batch=True`` — ONE batch stepped ``steps`` times
        (benchmarking / steady-state measurement, where stacking real
        microbatches would waste HBM).  lr/wd schedules are sampled
        once per call (per-``steps`` granularity).  Returns the
        per-step losses as a ``(steps,)`` NDArray."""
        if steps <= 0:
            raise MXNetError("run_steps needs steps >= 1")
        x_raw = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        y_raw = y.data if isinstance(y, NDArray) else jnp.asarray(y)
        if self.batch_axis != 0:
            raise MXNetError("run_steps supports batch_axis=0")
        if reuse_batch:
            B = x_raw.shape[0]
            xs, ys = x_raw, y_raw
        else:
            if x_raw.shape[0] % steps:
                raise MXNetError(
                    f"leading dim {x_raw.shape[0]} not divisible into "
                    f"{steps} microbatches")
            B = x_raw.shape[0] // steps
            xs = x_raw.reshape((steps, B) + x_raw.shape[1:])
            ys = y_raw.reshape((steps, B) + y_raw.shape[1:]) \
                if y_raw.ndim else y_raw
        self._collect(NDArray(x_raw[:B], None, _placed=True))
        if self.zero and B % self._zero_dp:
            raise MXNetError(
                f"ZeRO-1 shards the batch over dp={self._zero_dp}; "
                f"microbatch dim {B} is not divisible (pad the batch, "
                f"or pass zero=0)")
        batch_dim = 0 if reuse_batch else 1
        if self.mesh is not None:
            spec = [None] * xs.ndim
            spec[batch_dim] = self.dp_axis
            xs = _device_put_global(xs, self.mesh, P(*spec))
            yspec = [None] * max(ys.ndim, 1)
            if ys.ndim > batch_dim:
                yspec[batch_dim] = self.dp_axis
            ys = _device_put_global(ys, self.mesh, P(*yspec[:ys.ndim]))
        key = _rnd._next_key(None)
        one_shape = xs.shape[batch_dim:] if not reuse_batch else xs.shape
        y_one = ys.shape[batch_dim:] if not reuse_batch else ys.shape
        sig = (one_shape, str(xs.dtype), y_one, str(ys.dtype))
        entry = self._compiled.get(sig)
        if entry is None:
            if self._guards:
                self._churn.note_compile(sig)
            if self._obs:
                self._m_compile.inc()
            xb0 = xs if reuse_batch else xs[0]
            yb0 = ys if reuse_batch else (ys[0] if ys.ndim else ys)
            entry = self._build(key, xb0, yb0)
            self._compiled[sig] = entry
        msig = ("multi", steps, reuse_batch) + sig
        self._t += steps
        lrs, wds = self._lrs_wds()
        params = self._params
        train_vals = tuple(params[i]._data._data
                           for i in self._train_idx)
        frozen_vals = tuple(params[i]._data._data
                            for i in entry["frozen_idx"])
        keys = jax.vmap(jax.random.key_data)(
            jax.random.split(key, steps))
        lrs, wds, keys = self._commit_small(lrs, wds, keys)
        multi = self._compiled.get(msig)
        if multi is None:
            if self._guards:
                self._churn.note_compile(msig)
            if self._obs:
                self._m_compile.inc()
            raw_step = entry["raw_step"]
            aux_pos = entry["aux_pos"]
            amp_scaler = self._amp_scaler

            def multi_fn(train_vals, frozen_vals, opt_state, key_data,
                         lrs, wds, xs, ys, *amp_s):
                def body(carry, inp):
                    if amp_scaler:
                        tv, frozen, st, sc = carry
                    else:
                        tv, frozen, st = carry
                    if reuse_batch:
                        (kd,) = inp
                        xb, yb = xs, ys
                    else:
                        xb, yb, kd = inp
                    if amp_scaler:
                        loss, tv2, st2, raw_aux, sc2 = raw_step(
                            tv, frozen, st, kd, lrs, wds, xb, yb, sc)
                    else:
                        loss, tv2, st2, raw_aux = raw_step(
                            tv, frozen, st, kd, lrs, wds, xb, yb)
                    frozen2 = list(frozen)
                    for pos, v in zip(aux_pos, raw_aux):
                        if pos is not None:
                            frozen2[pos] = v
                    carry2 = (tv2, tuple(frozen2), st2)
                    if amp_scaler:
                        carry2 = carry2 + (sc2,)
                    return carry2, loss
                scanned = (key_data,) if reuse_batch else \
                    (xs, ys, key_data)
                carry0 = (train_vals, frozen_vals, opt_state)
                if amp_scaler:
                    carry0 = carry0 + (amp_s[0],)
                carry, losses = lax.scan(body, carry0, scanned)
                return (losses,) + carry

            donate = (0, 1, 2) if self.donate else ()
            multi = jax.jit(multi_fn, donate_argnums=donate)
            if (self.param_spec_fn is None and
                    (self.mesh is None
                     or not _mesh_is_multiprocess(self.mesh))):
                # AOT (as in _build): the scanned program's memory
                # stats are what bench.py's hbm_peak reports
                multi = multi.lower(
                    train_vals, frozen_vals, self._opt_state, keys,
                    lrs, wds, xs, ys, *self._amp_extra()).compile()
                self._last_mem = _mem_stats(multi)
                from mxtpu import analysis
                analysis.maybe_audit(multi, label="TrainStep.run_steps",
                                     mem=self._last_mem)
            self._compiled[msig] = multi
        if self._guards:
            self._churn.note_call()
        t0 = _prof._now_us() if self._obs else 0.0
        with guards.no_implicit_transfers(self._guards):
            out = multi(
                train_vals, frozen_vals, self._opt_state, keys, lrs, wds,
                xs, ys, *self._amp_extra())
        losses, tv, frozen, st = out[:4]
        if self._amp_scaler:
            self._amp_state = out[4]
        for i, v in zip(self._train_idx, tv):
            params[i]._data._data = v
        for j, i in enumerate(entry["frozen_idx"]):
            params[i]._data._data = frozen[j]
        self._opt_state = st
        if self._obs:
            # one sample of amortized per-step wall time — dispatch is
            # paid once for the whole scan, which is the point
            self._m_step.observe(
                (_prof._now_us() - t0) / 1e6 / steps)
        return NDArray(losses, None, _placed=True)

    # -- introspection ----------------------------------------------------
    def cost_analysis(self, x, y):
        """XLA ``cost_analysis`` of the ONE-STEP compiled program for
        this batch signature: {'flops', 'bytes accessed', ...} as
        reported by the backend.  This is the provenance of every
        MFU denominator in bench.py/BASELINE.md (fwd+bwd+optimizer,
        XLA's own count — not an analytic 6N estimate).  Note Pallas
        custom calls (flash attention, fused LN) hide their FLOPs from
        XLA, so on TPU the count is a floor; the CPU lowering runs the
        lax reference paths and counts everything.  Compiles the
        program if this signature has not stepped yet."""
        compiled = self._compiled_for(x, y)
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        ca = dict(ca)
        if self._obs and ca.get("flops"):
            # cost_analysis returns host floats — no device sync here
            self._m_flops.set(float(ca["flops"]))  # mxlint: sync-point
        return ca

    def _compiled_for(self, x, y):
        """The compiled one-step executable for this (x, y) signature
        (building it if needed).  On the AOT path this is the very
        executable the step runs; the multi-process jit path lowers a
        twin for inspection."""
        x_raw, y_raw, sig = self._prep(x, y)
        key = _rnd._next_key(None)
        entry = self._entry_for(x_raw, y_raw, sig, key)
        fn = entry["fn"]
        if not hasattr(fn, "lower"):  # AOT: already a Compiled
            return fn
        lrs, wds = self._lrs_wds()
        params = self._params
        train_vals = tuple(params[i]._data._data
                           for i in self._train_idx)
        frozen_vals = tuple(params[i]._data._data
                            for i in entry["frozen_idx"])
        return fn.lower(
            train_vals, frozen_vals, self._opt_state,
            jax.random.key_data(key), lrs, wds, x_raw,
            y_raw, *self._amp_extra()).compile()

    def memory_analysis(self, x, y):
        """Per-device memory footprint of the one-step compiled
        program for this batch signature: argument/output/temp/alias
        bytes from XLA's ``memory_analysis()``, plus ``hbm_peak``
        (temp + argument) and ``opt_state_bytes`` (bytes of optimizer
        state resident per device — under ZeRO-1, only the local
        shard).  Compiles the program if this signature has not
        stepped yet."""
        compiled = self._compiled_for(x, y)
        mem = dict(_mem_stats(compiled) or {})
        mem["opt_state_bytes"] = self.opt_state_bytes()
        return mem

    def memory_summary(self, x, y):
        """The sanctioned memory view (``mxtpu.analysis.memflow``) of
        the one-step program for this batch signature: peak HBM per
        device decomposed into params / optimizer state /
        activations+temps / collectives scratch / donated bytes, the
        ZeRO shard oracle when a dp>1 mesh is active, and any memory
        hazard findings — what tests and operators read instead of
        raw ``memory_analysis()`` grepping (mxlint ``mem-hygiene``)."""
        from mxtpu.analysis import memflow
        record = memflow.train_step_record(self, x, y)
        budgets = memflow.load_budgets(
            memflow.REPO_ROOT / "contracts")
        return memflow.summary_view(record, budgets)

    def hlo_text(self, x, y):
        """Compiled HLO of the one-step program for this batch
        signature.  Tests should prefer :meth:`program_summary` —
        mxlint's ``hlo-raw-assert`` rule bans regexing this text in
        ``tests/``."""
        return self._compiled_for(x, y).as_text()

    def lowered_hlo_text(self, x, y):
        """PRE-optimization HLO (with source metadata) of the
        one-step program — the dtype-flow substrate ``python -m
        tools.mxprec`` analyzes: every cast is still where the model
        code put it, before backend float normalization rewrites
        sub-f32 math."""
        from mxtpu import analysis
        x_raw, y_raw, sig = self._prep(x, y)
        key = _rnd._next_key(None)
        entry = self._entry_for(x_raw, y_raw, sig, key)
        lrs, wds = self._lrs_wds()
        params = self._params
        train_vals = tuple(params[i]._data._data
                           for i in self._train_idx)
        frozen_vals = tuple(params[i]._data._data
                            for i in entry["frozen_idx"])
        return analysis.lowered_text(
            entry["raw_step"], train_vals, frozen_vals,
            self._opt_state, jax.random.key_data(key), lrs, wds,
            x_raw, y_raw, *self._amp_extra())

    def param_sigs(self, x=None, y=None):
        """``(name, shape, dtype)`` per trainable parameter, in step
        order — what mxprec's ``master-weight`` rule audits against
        the optimizer's functional rule.  Pass a batch to trigger
        collection if no step has run yet."""
        if self._params is None:
            if x is None:
                raise MXNetError(
                    "param_sigs before parameter collection — run a "
                    "step or pass a batch")
            self._prep(x, y if y is not None else x)
        return [(self._params[i].name,
                 tuple(self._params[i]._data._data.shape),
                 str(self._params[i]._data._data.dtype))
                for i in self._train_idx]

    def program_summary(self, x, y):
        """Contract-shaped static summary (``mxtpu.analysis``) of the
        one-step compiled program for this batch signature:
        collective inventory, custom-call brackets, dtype policy,
        fusion/memory budgets, host transfers.  What the comm-layout
        regression tests assert on (reduce-scatter/all-gather under
        ZeRO-1, all-reduce on the replicated path) instead of
        grepping ``hlo_text``."""
        from mxtpu import analysis
        compiled = self._compiled_for(x, y)
        return analysis.summarize(compiled.as_text(),
                                  _mem_stats(compiled))

    def last_memory_analysis(self):
        """Memory stats of the most recently compiled program (the
        one-step executable or the ``run_steps`` scan program) as a
        dict with ``hbm_peak`` = temp + argument bytes; None if
        nothing compiled yet or the backend doesn't report."""
        return self._last_mem

    def opt_state_bytes(self) -> int:
        """Optimizer-state bytes resident PER DEVICE.  Replicated
        states count in full; ZeRO-1 sharded states count only the
        local shard — the dp× saving this mode exists for."""
        if self._params is None:
            raise MXNetError(
                "opt_state_bytes before parameter collection — run a "
                "step (or _collect) first")
        from mxtpu.analysis import memflow
        return memflow.opt_state_leaf_bytes(self._opt_state)

    # -- checkpoint/resume (SURVEY §5.4: preemption-safe from day one) --
    def _canonical_state(self):
        """Optimizer state in the canonical per-parameter layout
        (train-idx order, LAMB ``t`` a scalar per param).  The
        replicated path already stores this; ZeRO-1 gathers its
        bucketed shards and strips the padding — so checkpoints are
        interchangeable between zero and replicated steps in both
        directions."""
        if not self.zero:
            return self._opt_state
        per_param: List[Any] = [None] * len(self._train_idx)
        for b, st in zip(self._zero_buckets, self._opt_state):
            js, ax = b["jidx"], b["axis"]
            leaves = []
            for leaf in st:
                # mxlint: sync-point — checkpoint save gathers shards
                a = np.asarray(leaf)
                axk = ax if a.ndim == len(b["padded_shape"]) else 0
                orig = b["stacked_shape"][axk]
                if a.shape[axk] != orig:
                    sl = [slice(None)] * a.ndim
                    sl[axk] = slice(0, orig)
                    a = a[tuple(sl)]
                leaves.append(a)
            for pos, j in enumerate(js):
                per_param[j] = tuple(leaf[pos] for leaf in leaves)
        return tuple(per_param)

    def _state_from_canonical(self, loaded):
        """Restack a canonical per-parameter state into ZeRO-1's
        padded bucket layout, placed shard-per-device."""
        new_state = []
        for b, shardings in zip(self._zero_buckets,
                                self._zero_state_shardings):
            js, ax = b["jidx"], b["axis"]
            n_leaves = len(loaded[js[0]])
            leaves = []
            for k in range(n_leaves):
                # mxlint: sync-point — checkpoint load stages host data
                stk = np.stack([np.asarray(loaded[j][k]) for j in js])
                axk = ax if stk.ndim == len(b["padded_shape"]) else 0
                tgt = b["padded_shape"][axk]
                if stk.shape[axk] != tgt:
                    widths = [(0, 0)] * stk.ndim
                    widths[axk] = (0, tgt - stk.shape[axk])
                    stk = np.pad(stk, widths)
                leaves.append(jax.device_put(jnp.asarray(stk),
                                             shardings[k]))
            new_state.append(tuple(leaves))
        return tuple(new_state)

    def save_states(self, fname: str) -> None:
        """Serialize optimizer state + step counter.  Pair with
        ``net.save_parameters`` for a full resumable checkpoint.
        Always writes the canonical per-parameter layout
        (gather-on-save under ZeRO-1)."""
        import pickle
        if self._params is None:
            raise MXNetError("nothing to save: step never ran")
        state_np = jax.tree_util.tree_map(np.asarray,
                                          self._canonical_state())
        blob = {"t": self._t, "opt_state": state_np}
        if self._amp_scaler and self._amp_state is not None:
            # checkpoint save reads the scaler scalars
            blob["amp"] = {
                "scale": float(np.asarray(self._amp_state[0])),  # mxlint: sync-point
                "good_steps": int(np.asarray(self._amp_state[1])),  # mxlint: sync-point
                "skipped_steps": int(np.asarray(self._amp_state[2]))}  # mxlint: sync-point
        with open(fname, "wb") as f:
            pickle.dump(blob, f)

    def load_states(self, fname: str, x_example=None) -> None:
        """Restore optimizer state; the step counter resumes bias
        correction / schedules where they left off.  Checkpoints are
        canonical per-parameter (see ``save_states``), so a ZeRO-1
        step reshards on load and a replicated step loads a
        ZeRO-written file unchanged."""
        import pickle
        with open(fname, "rb") as f:
            data = pickle.load(f)  # mxlint: disable=raw-deserialize (optimizer-state checkpoint: own save_states framing, arrays not executables)
        if self._params is None:
            if x_example is None:
                raise MXNetError(
                    "load_states before any step: pass x_example so "
                    "parameter collection can run")
            self._collect(x_example if isinstance(x_example, NDArray)
                          else NDArray(jnp.asarray(x_example), None,
                                       _placed=True))
        loaded = data["opt_state"]
        cur = jax.tree_util.tree_structure(tuple(
            jax.eval_shape(
                self._opt_init,
                jax.ShapeDtypeStruct(
                    self._params[i]._data._data.shape,
                    self._params[i]._data._data.dtype))
            for i in self._train_idx))
        got = jax.tree_util.tree_structure(loaded)
        if cur != got:
            raise MXNetError(
                f"optimizer state structure mismatch: {got} vs {cur}")
        self._t = data["t"]
        if self._amp_scaler and "amp" in data:
            # loss-scale state rides the checkpoint: a resumed run
            # neither re-warms the scale from init nor forgets its
            # skipped-step accounting (absent in pre-AMP files → the
            # fresh scaler_init from _collect stands)
            st = (jnp.asarray(data["amp"]["scale"], jnp.float32),
                  jnp.asarray(data["amp"]["good_steps"], jnp.int32),
                  jnp.asarray(data["amp"]["skipped_steps"], jnp.int32))
            if self.mesh is not None:
                st = tuple(_device_put_global(v, self.mesh, P())
                           for v in st)
            self._amp_state = st
        if self.zero:
            self._opt_state = self._state_from_canonical(loaded)
            return
        loaded = jax.tree_util.tree_map(jnp.asarray, loaded)
        if self.mesh is not None:
            loaded = jax.tree_util.tree_map(
                lambda v: _device_put_global(v, self.mesh, P()),
                loaded)
        self._opt_state = loaded

    def amp_stats(self):
        """Host-readable loss-scaler state — ``{'loss_scale',
        'good_steps', 'skipped_steps'}`` — and the obs gauge sync
        point (``mxtpu_amp_loss_scale``, ``mxtpu_amp_skipped_steps``).
        None when AMP is off; static 1.0/0/0 when scaling is disabled
        (``MXTPU_AMP_LOSS_SCALE=0``)."""
        if not self.amp:
            return None
        if not self._amp_scaler or self._amp_state is None:
            stats = {"loss_scale": 1.0, "good_steps": 0,
                     "skipped_steps": 0}
        else:
            # explicit introspection read
            stats = {
                "loss_scale": float(np.asarray(self._amp_state[0])),  # mxlint: sync-point
                "good_steps": int(np.asarray(self._amp_state[1])),  # mxlint: sync-point
                "skipped_steps": int(np.asarray(self._amp_state[2]))}  # mxlint: sync-point
        if self._obs:
            self._m_amp_scale.set(stats["loss_scale"])
            self._m_amp_skipped.set(stats["skipped_steps"])
        return stats

    def _lrs_wds(self):
        """Per-parameter (lr, wd) vectors for this step — two traced
        array args (one transfer each), so scheduler/mult changes never
        trigger a recompile.  The raw ``adam_update`` op does not
        bias-correct, so the correction is folded into the lr here
        (matches the eager ``Adam.update``)."""
        opt = self.optimizer
        opt.num_update = self._t
        base_lr = opt.learning_rate
        bias = _adam_bias_correction(opt, self._t)
        # Mults are read live (not cached at setup) so mid-training
        # changes to Parameter.lr_mult/wd_mult or optimizer.set_lr_mult
        # take effect on the next step — matching the eager Trainer.
        allp = self._params
        lr_mults = np.asarray(  # mxlint: disable=host-sync — Python floats
            [allp[i].lr_mult * opt.lr_mult.get(allp[i].name, 1.0)
             for i in self._train_idx], np.float32)
        wd_mults = np.asarray(  # mxlint: disable=host-sync — Python floats
            [allp[i].wd_mult * opt.wd_mult.get(allp[i].name, 1.0)
             for i in self._train_idx], np.float32)
        lrs = jnp.asarray(base_lr * bias * lr_mults)
        wds = jnp.asarray(opt.wd * wd_mults)
        return lrs, wds


def build_train_step(net, loss_fn, optimizer="sgd", optimizer_params=None,
                     mesh: Optional[Mesh] = None, dp_axis: str = "dp",
                     batch_axis: int = 0, param_spec_fn=None,
                     donate: bool = True, compute_dtype=None,
                     cast_batch: bool = True, zero=None,
                     cache: Any = "auto", amp=None) -> TrainStep:
    """Compile net+loss+optimizer into a single SPMD train step.

    ``mesh=None`` → single-device executable (still one fused program).
    With a mesh, batches shard over ``dp_axis`` and XLA inserts the
    gradient all-reduce; ``param_spec_fn(param) -> PartitionSpec`` adds
    tensor-parallel sharding.  On single-process dp meshes the step
    defaults to ZeRO-1 sharded optimizer states (reduce-scatter +
    all-gather instead of all-reduce; see :class:`TrainStep`) —
    ``zero=0`` or ``MXTPU_ZERO=0`` restores the replicated path,
    ``zero=1`` insists.

    ``amp=1`` turns on policy-driven mixed precision (``mxtpu.amp``):
    bf16 parameter storage over f32 master weights, bf16 casts on the
    allow-listed contractions only (f32 accumulation everywhere),
    dynamic loss scaling, and — under ZeRO-1 — a bf16 reduce-scatter
    at half the f32 comm bytes.  ``MXTPU_AMP=0`` kills it globally,
    ``MXTPU_AMP=1`` enables it globally; ``amp=None`` defers to the
    environment."""
    if not isinstance(optimizer, opt_mod.Optimizer):
        optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
    return TrainStep(net, loss_fn, optimizer, mesh=mesh, dp_axis=dp_axis,
                     batch_axis=batch_axis, param_spec_fn=param_spec_fn,
                     donate=donate, compute_dtype=compute_dtype,
                     cast_batch=cast_batch, zero=zero, cache=cache,
                     amp=amp)


from .pipeline import (spmd_pipeline, stack_stage_params,  # noqa: E402
                       PipelineTrainStep, build_pipeline_train_step)
