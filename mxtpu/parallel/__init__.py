"""``mxtpu.parallel`` — SPMD execution over a device mesh.

This is the TPU-native replacement for the reference's multi-device
machinery (``DataParallelExecutorGroup``†, KVStore ``device``/``nccl``
reduction, ``src/kvstore/comm.h``†): instead of per-device executors
plus explicit push/pull reductions, the WHOLE training step —
forward, backward, gradient all-reduce, optimizer update, running-stat
(aux) updates — is compiled into ONE XLA executable over a
``jax.sharding.Mesh``.  The batch is sharded over the ``dp`` axis;
parameters are replicated (or sharded per ``param_spec_fn`` for tensor
parallelism); XLA inserts the all-reduce/all-gather collectives and
schedules them over ICI (SURVEY.md §2.4, §5.8).

``KVStore`` (``mxtpu.kvstore``) remains as the API-parity facade; this
module is the mechanism.
"""
from __future__ import annotations

import os
import weakref

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from .. import optimizer as opt_mod
from ..ndarray import random as _rnd
from ..ndarray.ndarray import NDArray
from ..ops.registry import get_op

__all__ = ["make_mesh", "shard_batch", "replicate", "TrainStep",
           "build_train_step", "Mesh", "PartitionSpec", "P",
           "spmd_pipeline", "stack_stage_params", "PipelineTrainStep",
           "build_pipeline_train_step", "snapshot_params",
           "restore_params", "moe"]

PartitionSpec = P

from . import moe  # noqa: E402  (expert parallelism — the ep axis)


def snapshot_params(net):
    """Parameter values of ``net`` in collect_params() order (a list
    of numpy arrays).  Pairs with :func:`restore_params` to clone one
    net's init into another INSTANCE of the same architecture: block
    auto-naming gives every instance fresh prefixes, so values must be
    carried by position, not name — keeping that subtle assumption in
    one place (r4 review)."""
    return [p.data().asnumpy() for p in net.collect_params().values()]


def restore_params(net, values):
    """Set ``net``'s parameters from a :func:`snapshot_params` list
    (same architecture, any instance).  The net must already be
    shape-initialised (run one forward first for deferred blocks)."""
    from .. import nd as _nd
    params = list(net.collect_params().values())
    if len(params) != len(values):
        raise ValueError(
            f"parameter count mismatch: net has {len(params)}, "
            f"snapshot has {len(values)} — not the same architecture")
    for p, v in zip(params, values):
        p.set_data(_nd.array(v))


def make_mesh(axes: Optional[Dict[str, int]] = None, devices=None) -> Mesh:
    """Build a named device mesh.  ``axes`` maps axis name → size, e.g.
    ``{'dp': 4, 'mp': 2}``; defaults to pure data parallelism over all
    visible devices."""
    devices = devices if devices is not None else jax.devices()
    if axes is None:
        axes = {"dp": len(devices)}
    names = tuple(axes)
    sizes = tuple(axes[n] for n in names)
    total = int(np.prod(sizes))
    if total > len(devices):
        raise MXNetError(
            f"mesh {axes} needs {total} devices, have {len(devices)}")
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, names)


# weakref-keyed so entries die with their mesh (an id()-keyed dict
# could hand a stale flag to a new mesh reusing the address)
_MESH_MP_CACHE: "weakref.WeakKeyDictionary[Mesh, bool]" = \
    weakref.WeakKeyDictionary()


def _mesh_is_multiprocess(mesh: Mesh) -> bool:
    # O(devices) scan once per mesh, not per step (real multi-host
    # meshes have thousands of devices)
    try:
        flag = _MESH_MP_CACHE.get(mesh)
    except TypeError:  # unhashable/unweakrefable mesh variant
        me = jax.process_index()
        return any(d.process_index != me for d in mesh.devices.flat)
    if flag is None:
        me = jax.process_index()
        flag = any(d.process_index != me for d in mesh.devices.flat)
        _MESH_MP_CACHE[mesh] = flag
    return flag


def _device_put_global(raw, mesh: Mesh, spec) -> jax.Array:
    """Place a value onto a mesh sharding, including meshes that span
    processes.  Host values: every process passes the SAME full value
    (each takes only the rows its devices own), so single- and
    multi-process code paths stay identical — `jax.device_put` alone
    would demand cross-host transfers the CPU/gloo transport refuses.
    Already-global jax.Arrays are passed through (or resharded
    in-graph) rather than fetched to host."""
    sh = NamedSharding(mesh, spec)
    if not _mesh_is_multiprocess(mesh):
        return jax.device_put(raw, sh)
    if isinstance(raw, jax.Array):
        if raw.sharding == sh:
            return raw
        if not raw.is_fully_addressable:
            # global array with a different layout: reshard with an
            # in-graph identity (XLA inserts the collectives)
            return jax.jit(lambda a: a, out_shardings=sh)(raw)
    host = np.asarray(raw)
    idx_map = sh.addressable_devices_indices_map(host.shape)
    shards = [jax.device_put(host[idx], d)
              for d, idx in idx_map.items()]
    return jax.make_array_from_single_device_arrays(host.shape, sh,
                                                    shards)


def shard_batch(mesh: Mesh, arr, axis_name: str = "dp", batch_axis: int = 0):
    """Place an array batch-sharded over a mesh axis."""
    raw = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
    spec = [None] * raw.ndim
    spec[batch_axis] = axis_name
    out = _device_put_global(raw, mesh, P(*spec))
    return NDArray(out, None, _placed=True) if isinstance(arr, NDArray) \
        else out


def replicate(mesh: Mesh, arr):
    """Place an array fully replicated over the mesh."""
    raw = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
    out = _device_put_global(raw, mesh, P())
    return NDArray(out, None, _placed=True) if isinstance(arr, NDArray) \
        else out


def _adam_bias_correction(opt, t: int) -> float:
    """The raw ``adam_update`` op does not bias-correct; fold the
    correction into the lr (single source for TrainStep AND
    PipelineTrainStep)."""
    if isinstance(opt, opt_mod.Adam) and t > 0:
        return float(np.sqrt(1.0 - opt.beta2 ** t) /
                     (1.0 - opt.beta1 ** t))
    return 1.0


# ----------------------------------------------------------------------
# functional optimizer rules for the compiled step
# (reuse the fused registry ops — "optimizers are ops")
# ----------------------------------------------------------------------
def _opt_rule(optimizer: opt_mod.Optimizer):
    """Return (init_state(w)->tuple, update(w,g,state,lr,wd)->(w,state)).

    Every ``update`` accepts ``stacked=False``: the batched optimizer
    path stacks same-shape parameters on a new axis 0 and applies ONE
    update to the bundle.  All rules are elementwise in (w, g, state)
    — numerically identical stacked or not — except LAMB, whose
    per-tensor trust-ratio norms reduce per axis-0 slice when stacked."""
    if isinstance(optimizer, opt_mod.LAMB):
        fn = get_op("lamb_update").fn

        def init(w):
            # per-param step count rides in the state (traced, so lr
            # schedules and resume never recompile)
            return (jnp.zeros_like(w), jnp.zeros_like(w),
                    jnp.zeros((), jnp.int32))

        def update(w, g, state, lr, wd, stacked=False):
            t = state[2] + 1
            w2, m, v = fn(w, g, state[0], state[1], t, lr=lr,
                          beta1=optimizer.beta1, beta2=optimizer.beta2,
                          epsilon=optimizer.epsilon, wd=wd,
                          rescale_grad=optimizer.rescale_grad,
                          clip_gradient=optimizer._clip(),
                          bias_correction=optimizer.bias_correction,
                          stacked=stacked)
            return w2, (m, v, t)
        return init, update
    if isinstance(optimizer, opt_mod.Adam):
        fn = get_op("adam_update").fn

        def init(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def update(w, g, state, lr, wd, stacked=False):
            w2, m, v = fn(w, g, state[0], state[1], lr=lr,
                          beta1=optimizer.beta1, beta2=optimizer.beta2,
                          epsilon=optimizer.epsilon, wd=wd,
                          rescale_grad=optimizer.rescale_grad,
                          clip_gradient=optimizer._clip())
            return w2, (m, v)
        return init, update
    if isinstance(optimizer, opt_mod.RMSProp) and not optimizer.centered:
        fn = get_op("rmsprop_update").fn

        def init(w):
            return (jnp.zeros_like(w),)

        def update(w, g, state, lr, wd, stacked=False):
            w2, n = fn(w, g, state[0], lr=lr, gamma1=optimizer.gamma1,
                       epsilon=optimizer.epsilon, wd=wd,
                       rescale_grad=optimizer.rescale_grad,
                       clip_gradient=optimizer._clip())
            return w2, (n,)
        return init, update
    if isinstance(optimizer, opt_mod.SGD):
        if optimizer.momentum:
            fn = get_op("sgd_mom_update").fn

            def init(w):
                return (jnp.zeros_like(w),)

            def update(w, g, state, lr, wd, stacked=False):
                w2, m = fn(w, g, state[0], lr=lr,
                           momentum=optimizer.momentum, wd=wd,
                           rescale_grad=optimizer.rescale_grad,
                           clip_gradient=optimizer._clip())
                return w2, (m,)
            return init, update
        fn = get_op("sgd_update").fn

        def init(w):
            return ()

        def update(w, g, state, lr, wd, stacked=False):
            return fn(w, g, lr=lr, wd=wd,
                      rescale_grad=optimizer.rescale_grad,
                      clip_gradient=optimizer._clip()), ()
        return init, update
    raise MXNetError(
        f"compiled train step supports SGD/Adam/RMSProp/LAMB; got "
        f"{type(optimizer).__name__} (use gluon.Trainer eager path)")


class TrainStep:
    """One fused XLA executable per (shape signature): fwd + bwd +
    collectives + optimizer + aux writeback.  Call with (x, y) batches;
    parameters update in place (rebound buffers)."""

    def __init__(self, net, loss_fn, optimizer, mesh: Optional[Mesh] = None,
                 dp_axis: str = "dp", batch_axis: int = 0,
                 param_spec_fn: Optional[Callable] = None, donate=True,
                 compute_dtype=None, cast_batch=True):
        from ..gluon.block import _traced_forward
        self._traced_forward = _traced_forward
        self.net = net
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.batch_axis = batch_axis
        self.param_spec_fn = param_spec_fn
        self.donate = donate
        # mixed precision: forward/backward in compute_dtype (bf16 puts
        # the matmuls/convs on the MXU's fast path), master weights,
        # loss, and optimizer state stay f32 — the reference's
        # multi_precision=True AMP recipe, compiled into the one program.
        # cast_batch=False keeps the raw batch dtype — REQUIRED when x
        # carries integer ids in a float array (Embedding inputs):
        # bf16 can't represent ids > 256 exactly, so casting would
        # silently fetch wrong rows; the bf16 embedding table already
        # makes everything downstream compute in bf16.
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        self.cast_batch = cast_batch
        self._compiled = {}
        self._params: Optional[List] = None
        self._t = 0

    # -- parameter bookkeeping -----------------------------------------
    def _collect(self, x):
        if self._params is None:
            import mxtpu.autograd as autograd
            if not all(p._data is not None
                       for p in self.net.collect_params().values()):
                with autograd.pause():
                    self.net(x)  # deferred shape inference
            allp = list(self.net.collect_params().values())
            self._params = allp
            self._train_idx = [i for i, p in enumerate(allp)
                               if p.grad_req != "null"]
            # Honour per-parameter lr_mult/wd_mult (Parameter attrs plus
            # any name-keyed overrides set on the optimizer) without
            # touching the optimizer's own param_dict/idx2name — those
            # may be indexed by a different ordering (e.g. a shared
            # gluon.Trainer instance).
            self._opt_init, self._opt_update = _opt_rule(self.optimizer)
            if self.mesh is not None:
                for p in allp:
                    spec = None
                    if self.param_spec_fn is not None:
                        spec = self.param_spec_fn(p)
                    p._data._data = _device_put_global(
                        p._data._data, self.mesh,
                        spec if spec is not None else P())
            self._opt_state = tuple(
                self._opt_init(self._params[i]._data._data)
                for i in self._train_idx)
            if self.mesh is not None:
                self._opt_state = jax.tree_util.tree_map(
                    lambda v: _device_put_global(v, self.mesh, P()),
                    self._opt_state)

    def _build(self, key, x_raw, y_raw):
        params = self._params
        train_idx = self._train_idx
        frozen_idx = [i for i in range(len(params)) if i not in
                      set(train_idx)]
        n_param = len(params)
        loss_fn = self.loss_fn
        net = self.net
        traced_forward = self._traced_forward
        aux_box: Dict[str, Any] = {}

        compute_dtype = self.compute_dtype
        cast_batch = self.cast_batch

        def loss_flat(train_vals, frozen_vals, key_data, x, y):
            pvals: List[Any] = [None] * n_param
            for i, v in zip(train_idx, train_vals):
                pvals[i] = v
            for i, v in zip(frozen_idx, frozen_vals):
                pvals[i] = v
            if compute_dtype is not None:
                # BN running stats (aux-named params) stay f32: their
                # EMA updates are too small for a bf16 mantissa
                from ..symbol import _is_aux_name
                pvals = [v.astype(compute_dtype)
                         if v is not None
                         and not _is_aux_name(params[i].name)
                         and jnp.issubdtype(v.dtype, jnp.floating)
                         else v
                         for i, v in enumerate(pvals)]
                if cast_batch and jnp.issubdtype(x.dtype, jnp.floating):
                    x = x.astype(compute_dtype)
            raw_outs, _, aux_params, raw_aux = traced_forward(
                net, params, pvals, [NDArray(x, None, _placed=True)],
                True, key_data)
            outs = [NDArray(r, None, _placed=True) for r in raw_outs]
            # Multi-output nets hand ALL outputs to the loss (a custom
            # loss_fn must unpack them) rather than silently training
            # only the first head.
            pred = outs[0] if len(outs) == 1 else outs
            l = loss_fn(pred, NDArray(y, None, _placed=True))
            raw_l = l.data if isinstance(l, NDArray) else l
            aux_box["aux_params"] = aux_params
            # loss and aux (running stats) leave the bf16 region in f32
            if compute_dtype is not None:
                raw_aux = [a.astype(jnp.float32)
                           if jnp.issubdtype(a.dtype, jnp.floating)
                           else a for a in raw_aux]
            return jnp.mean(raw_l.astype(jnp.float32)), tuple(raw_aux)

        # Batched optimizer apply: bucket trainable params by
        # (shape, dtype) and update each bucket as ONE stacked op
        # instead of one HLO chain per parameter — a BERT-Large step
        # drops from ~400 per-param update chains to ~25 bucket
        # updates.  All rules are elementwise in (w, g, state) with
        # lr/wd entering as broadcast (n,1,..,1) scalars, so the
        # stacked apply is numerically identical to the per-param loop
        # (LAMB reduces its trust-ratio norms per slice).
        # MXTPU_BATCHED_OPT=0 restores the per-param loop.
        batched = os.environ.get("MXTPU_BATCHED_OPT", "1").lower() \
            not in ("0", "off", "false")
        groups: List[List[int]] = []
        if batched:
            by_sig: Dict[Tuple, List[int]] = {}
            for j, i in enumerate(train_idx):
                v = params[i]._data._data
                by_sig.setdefault((v.shape, str(v.dtype)), []).append(j)
            groups = list(by_sig.values())

        def apply_updates(train_vals, grads, opt_state, lrs, wds):
            n = len(train_vals)
            new_vals: List[Any] = [None] * n
            new_state: List[Any] = [None] * n
            if not batched:
                for j, (w, g, st) in enumerate(zip(train_vals, grads,
                                                   opt_state)):
                    new_vals[j], new_state[j] = self._opt_update(
                        w, g, st, lrs[j], wds[j])
                return tuple(new_vals), tuple(new_state)
            for group in groups:
                if len(group) == 1:
                    j = group[0]
                    new_vals[j], new_state[j] = self._opt_update(
                        train_vals[j], grads[j], opt_state[j],
                        lrs[j], wds[j])
                    continue
                w_s = jnp.stack([train_vals[j] for j in group])
                g_s = jnp.stack([grads[j] for j in group])
                n_leaves = len(opt_state[group[0]])
                st_s = tuple(
                    jnp.stack([opt_state[j][k] for j in group])
                    for k in range(n_leaves))
                idx = jnp.asarray(np.asarray(group, np.int32))
                bshape = (len(group),) + (1,) * (w_s.ndim - 1)
                lr_s = jnp.take(lrs, idx).reshape(bshape)
                wd_s = jnp.take(wds, idx).reshape(bshape)
                w2_s, st2_s = self._opt_update(w_s, g_s, st_s, lr_s,
                                               wd_s, stacked=True)
                for a, j in enumerate(group):
                    new_vals[j] = w2_s[a]
                    new_state[j] = tuple(leaf[a] for leaf in st2_s)
            return tuple(new_vals), tuple(new_state)

        def step(train_vals, frozen_vals, opt_state, key_data, lrs, wds,
                 x, y):
            (loss, raw_aux), grads = jax.value_and_grad(
                loss_flat, has_aux=True)(train_vals, frozen_vals,
                                         key_data, x, y)
            new_vals, new_state = apply_updates(train_vals, grads,
                                                opt_state, lrs, wds)
            return loss, new_vals, new_state, raw_aux

        # learn the aux structure without device work
        train_vals = tuple(params[i]._data._data for i in train_idx)
        frozen_vals = tuple(params[i]._data._data for i in frozen_idx)
        zeros = jnp.zeros(len(train_idx), jnp.float32)
        jax.eval_shape(step, train_vals, frozen_vals, self._opt_state,
                       jax.random.key_data(key), zeros, zeros,
                       x_raw, y_raw)
        donate = (0, 2) if self.donate else ()
        fitted = jax.jit(step, donate_argnums=donate)
        # aux (BN running stats) positions inside the frozen tuple, in
        # aux_params order, for the scanned multi-step path to thread
        # them through the carry (None if an aux is somehow trainable)
        id2pos = {id(params[i]): j for j, i in enumerate(frozen_idx)}
        aux_pos = [id2pos.get(id(p)) for p in aux_box["aux_params"]]
        return {"fn": fitted, "raw_step": step,
                "aux_params": aux_box["aux_params"],
                "frozen_idx": frozen_idx, "aux_pos": aux_pos}

    # -- the hot call ----------------------------------------------------
    def __call__(self, x, y):
        # under a multi-process mesh, keep non-NDArray inputs as HOST
        # buffers: _device_put_global shards them directly, avoiding a
        # wasted H2D→D2H round trip through the default device
        mp = self.mesh is not None and _mesh_is_multiprocess(self.mesh)
        wrap = np.asarray if mp else jnp.asarray
        x_raw = x.data if isinstance(x, NDArray) else wrap(x)
        y_raw = y.data if isinstance(y, NDArray) else wrap(y)
        self._collect(x if isinstance(x, NDArray)
                      else NDArray(x_raw, None, _placed=True))
        if self.mesh is not None:
            spec = [None] * x_raw.ndim
            spec[self.batch_axis] = self.dp_axis
            x_raw = _device_put_global(x_raw, self.mesh, P(*spec))
            yspec = [None] * max(y_raw.ndim, 1)
            yspec[self.batch_axis] = self.dp_axis
            y_raw = _device_put_global(y_raw, self.mesh,
                                       P(*yspec[:y_raw.ndim]))
        sig = (x_raw.shape, str(x_raw.dtype), y_raw.shape,
               str(y_raw.dtype))
        key = _rnd._next_key(None)
        entry = self._compiled.get(sig)
        if entry is None:
            entry = self._build(key, x_raw, y_raw)
            self._compiled[sig] = entry
        self._t += 1
        lrs, wds = self._lrs_wds()
        params = self._params
        train_vals = tuple(params[i]._data._data for i in self._train_idx)
        frozen_vals = tuple(params[i]._data._data
                            for i in entry["frozen_idx"])
        loss, new_vals, new_state, raw_aux = entry["fn"](
            train_vals, frozen_vals, self._opt_state,
            jax.random.key_data(key), lrs, wds, x_raw, y_raw)
        for i, v in zip(self._train_idx, new_vals):
            params[i]._data._data = v
        self._opt_state = new_state
        for p, v in zip(entry["aux_params"], raw_aux):
            p._data._data = v
        return NDArray(loss, None, _placed=True)

    # -- bulked execution -------------------------------------------------
    def run_steps(self, x, y, steps: int, reuse_batch: bool = False):
        """Run ``steps`` optimizer steps in ONE compiled program via
        ``lax.scan`` over microbatches — the TPU-native form of the
        reference's bulked graph execution (``MXNET_EXEC_BULK_EXEC_
        TRAIN``†, ``src/executor/graph_executor.cc`` bulking): host
        dispatch cost is paid once per ``steps`` instead of per step.

        ``x``/``y`` carry ``steps`` microbatches stacked on the batch
        axis (leading dim ``steps * B``), or — with
        ``reuse_batch=True`` — ONE batch stepped ``steps`` times
        (benchmarking / steady-state measurement, where stacking real
        microbatches would waste HBM).  lr/wd schedules are sampled
        once per call (per-``steps`` granularity).  Returns the
        per-step losses as a ``(steps,)`` NDArray."""
        if steps <= 0:
            raise MXNetError("run_steps needs steps >= 1")
        x_raw = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        y_raw = y.data if isinstance(y, NDArray) else jnp.asarray(y)
        if self.batch_axis != 0:
            raise MXNetError("run_steps supports batch_axis=0")
        if reuse_batch:
            B = x_raw.shape[0]
            xs, ys = x_raw, y_raw
        else:
            if x_raw.shape[0] % steps:
                raise MXNetError(
                    f"leading dim {x_raw.shape[0]} not divisible into "
                    f"{steps} microbatches")
            B = x_raw.shape[0] // steps
            xs = x_raw.reshape((steps, B) + x_raw.shape[1:])
            ys = y_raw.reshape((steps, B) + y_raw.shape[1:]) \
                if y_raw.ndim else y_raw
        self._collect(NDArray(x_raw[:B], None, _placed=True))
        batch_dim = 0 if reuse_batch else 1
        if self.mesh is not None:
            spec = [None] * xs.ndim
            spec[batch_dim] = self.dp_axis
            xs = _device_put_global(xs, self.mesh, P(*spec))
            yspec = [None] * max(ys.ndim, 1)
            if ys.ndim > batch_dim:
                yspec[batch_dim] = self.dp_axis
            ys = _device_put_global(ys, self.mesh, P(*yspec[:ys.ndim]))
        key = _rnd._next_key(None)
        one_shape = xs.shape[batch_dim:] if not reuse_batch else xs.shape
        y_one = ys.shape[batch_dim:] if not reuse_batch else ys.shape
        sig = (one_shape, str(xs.dtype), y_one, str(ys.dtype))
        entry = self._compiled.get(sig)
        if entry is None:
            xb0 = xs if reuse_batch else xs[0]
            yb0 = ys if reuse_batch else (ys[0] if ys.ndim else ys)
            entry = self._build(key, xb0, yb0)
            self._compiled[sig] = entry
        msig = ("multi", steps, reuse_batch) + sig
        multi = self._compiled.get(msig)
        if multi is None:
            raw_step = entry["raw_step"]
            aux_pos = entry["aux_pos"]

            def multi_fn(train_vals, frozen_vals, opt_state, key_data,
                         lrs, wds, xs, ys):
                def body(carry, inp):
                    tv, frozen, st = carry
                    if reuse_batch:
                        (kd,) = inp
                        xb, yb = xs, ys
                    else:
                        xb, yb, kd = inp
                    loss, tv2, st2, raw_aux = raw_step(
                        tv, frozen, st, kd, lrs, wds, xb, yb)
                    frozen2 = list(frozen)
                    for pos, v in zip(aux_pos, raw_aux):
                        if pos is not None:
                            frozen2[pos] = v
                    return (tv2, tuple(frozen2), st2), loss
                scanned = (key_data,) if reuse_batch else \
                    (xs, ys, key_data)
                (tv, frozen, st), losses = lax.scan(
                    body, (train_vals, frozen_vals, opt_state), scanned)
                return losses, tv, frozen, st

            donate = (0, 1, 2) if self.donate else ()
            multi = jax.jit(multi_fn, donate_argnums=donate)
            self._compiled[msig] = multi
        self._t += steps
        lrs, wds = self._lrs_wds()
        params = self._params
        train_vals = tuple(params[i]._data._data
                           for i in self._train_idx)
        frozen_vals = tuple(params[i]._data._data
                            for i in entry["frozen_idx"])
        keys = jax.vmap(jax.random.key_data)(
            jax.random.split(key, steps))
        losses, tv, frozen, st = multi(
            train_vals, frozen_vals, self._opt_state, keys, lrs, wds,
            xs, ys)
        for i, v in zip(self._train_idx, tv):
            params[i]._data._data = v
        for j, i in enumerate(entry["frozen_idx"]):
            params[i]._data._data = frozen[j]
        self._opt_state = st
        return NDArray(losses, None, _placed=True)

    # -- introspection ----------------------------------------------------
    def cost_analysis(self, x, y):
        """XLA ``cost_analysis`` of the ONE-STEP compiled program for
        this batch signature: {'flops', 'bytes accessed', ...} as
        reported by the backend.  This is the provenance of every
        MFU denominator in bench.py/BASELINE.md (fwd+bwd+optimizer,
        XLA's own count — not an analytic 6N estimate).  Note Pallas
        custom calls (flash attention, fused LN) hide their FLOPs from
        XLA, so on TPU the count is a floor; the CPU lowering runs the
        lax reference paths and counts everything.  Compiles the
        program if this signature has not stepped yet."""
        x_raw = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        y_raw = y.data if isinstance(y, NDArray) else jnp.asarray(y)
        self._collect(x if isinstance(x, NDArray)
                      else NDArray(x_raw, None, _placed=True))
        sig = (x_raw.shape, str(x_raw.dtype), y_raw.shape,
               str(y_raw.dtype))
        key = _rnd._next_key(None)
        entry = self._compiled.get(sig)
        if entry is None:
            entry = self._build(key, x_raw, y_raw)
            self._compiled[sig] = entry
        lrs, wds = self._lrs_wds()
        params = self._params
        train_vals = tuple(params[i]._data._data
                           for i in self._train_idx)
        frozen_vals = tuple(params[i]._data._data
                            for i in entry["frozen_idx"])
        compiled = entry["fn"].lower(
            train_vals, frozen_vals, self._opt_state,
            jax.random.key_data(key), lrs, wds, x_raw,
            y_raw).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        return dict(ca)

    # -- checkpoint/resume (SURVEY §5.4: preemption-safe from day one) --
    def save_states(self, fname: str) -> None:
        """Serialize optimizer state + step counter.  Pair with
        ``net.save_parameters`` for a full resumable checkpoint."""
        import pickle
        if self._params is None:
            raise MXNetError("nothing to save: step never ran")
        state_np = jax.tree_util.tree_map(np.asarray, self._opt_state)
        with open(fname, "wb") as f:
            pickle.dump({"t": self._t, "opt_state": state_np}, f)

    def load_states(self, fname: str, x_example=None) -> None:
        """Restore optimizer state; the step counter resumes bias
        correction / schedules where they left off."""
        import pickle
        with open(fname, "rb") as f:
            data = pickle.load(f)
        if self._params is None:
            if x_example is None:
                raise MXNetError(
                    "load_states before any step: pass x_example so "
                    "parameter collection can run")
            self._collect(x_example if isinstance(x_example, NDArray)
                          else NDArray(jnp.asarray(x_example), None,
                                       _placed=True))
        self._t = data["t"]
        loaded = jax.tree_util.tree_map(jnp.asarray, data["opt_state"])
        cur = jax.tree_util.tree_structure(self._opt_state)
        got = jax.tree_util.tree_structure(loaded)
        if cur != got:
            raise MXNetError(
                f"optimizer state structure mismatch: {got} vs {cur}")
        if self.mesh is not None:
            loaded = jax.tree_util.tree_map(
                lambda v: _device_put_global(v, self.mesh, P()),
                loaded)
        self._opt_state = loaded

    def _lrs_wds(self):
        """Per-parameter (lr, wd) vectors for this step — two traced
        array args (one transfer each), so scheduler/mult changes never
        trigger a recompile.  The raw ``adam_update`` op does not
        bias-correct, so the correction is folded into the lr here
        (matches the eager ``Adam.update``)."""
        opt = self.optimizer
        opt.num_update = self._t
        base_lr = opt.learning_rate
        bias = _adam_bias_correction(opt, self._t)
        # Mults are read live (not cached at setup) so mid-training
        # changes to Parameter.lr_mult/wd_mult or optimizer.set_lr_mult
        # take effect on the next step — matching the eager Trainer.
        allp = self._params
        lr_mults = np.asarray(
            [allp[i].lr_mult * opt.lr_mult.get(allp[i].name, 1.0)
             for i in self._train_idx], np.float32)
        wd_mults = np.asarray(
            [allp[i].wd_mult * opt.wd_mult.get(allp[i].name, 1.0)
             for i in self._train_idx], np.float32)
        lrs = jnp.asarray(base_lr * bias * lr_mults)
        wds = jnp.asarray(opt.wd * wd_mults)
        return lrs, wds


def build_train_step(net, loss_fn, optimizer="sgd", optimizer_params=None,
                     mesh: Optional[Mesh] = None, dp_axis: str = "dp",
                     batch_axis: int = 0, param_spec_fn=None,
                     donate: bool = True, compute_dtype=None,
                     cast_batch: bool = True) -> TrainStep:
    """Compile net+loss+optimizer into a single SPMD train step.

    ``mesh=None`` → single-device executable (still one fused program).
    With a mesh, batches shard over ``dp_axis`` and XLA inserts the
    gradient all-reduce; ``param_spec_fn(param) -> PartitionSpec`` adds
    tensor-parallel sharding."""
    if not isinstance(optimizer, opt_mod.Optimizer):
        optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
    return TrainStep(net, loss_fn, optimizer, mesh=mesh, dp_axis=dp_axis,
                     batch_axis=batch_axis, param_spec_fn=param_spec_fn,
                     donate=donate, compute_dtype=compute_dtype,
                     cast_batch=cast_batch)


from .pipeline import (spmd_pipeline, stack_stage_params,  # noqa: E402
                       PipelineTrainStep, build_pipeline_train_step)
