"""Pipeline parallelism — GPipe microbatch schedule over a mesh axis.

The reference era expressed model parallelism as manual per-device
layer placement (``mx.AttrScope(ctx_group=...)`` + ``group2ctx`` in
bind); there is no pipelined schedule in the 2018 codebase at all.
This module supplies the modern capability TPU-natively: the layer
stack is sharded over a ``pp`` mesh axis (each device holds a
contiguous stage of layers), the batch is split into microbatches, and
activations flow stage-to-stage via ``lax.ppermute`` — XLA lowers the
rotation to neighbour-to-neighbour collective-permutes over ICI.

The schedule is written as ONE ``lax.scan`` over
``n_microbatches + n_stages - 1`` ticks inside ``shard_map``, so both
the forward and (via reverse-mode AD through the scan) the backward
pipeline compile into a single SPMD program.  Bubble fraction is the
GPipe ``(S-1)/(M+S-1)``; raise ``n_microbatches`` to amortise.

Composes with data parallelism: run over a ``{'pp': S, 'dp': D}`` mesh
and pass ``batch_spec=P('dp')`` — gradient all-reduce over ``dp`` is
inserted by XLA as usual.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = ["spmd_pipeline", "stack_stage_params", "PipelineTrainStep",
           "build_pipeline_train_step"]


def spmd_pipeline(stage_fn: Callable, stage_params: Any, x: jax.Array,
                  *, mesh: Mesh, axis: str = "pp",
                  n_microbatches: int = 4,
                  batch_spec: Optional[P] = None,
                  key: Optional[jax.Array] = None) -> jax.Array:
    """Apply a homogeneous layer pipeline to ``x`` with GPipe scheduling.

    ``stage_params``: pytree whose leaves have leading dim ``L`` (total
    layers), sharded over ``mesh[axis]`` so each of the ``S`` stages
    holds ``L/S`` layers.  ``stage_fn(local_params, x[, key])`` applies
    one stage's layers to a microbatch activation and must preserve its
    shape (the homogeneous-stack contract — exactly the transformer
    case).  ``x``: (B, ...) with ``B % n_microbatches == 0``.

    ``batch_spec``: PartitionSpec for the per-microbatch activation
    dims (e.g. ``P('dp')`` to keep the batch dim sharded over a data-
    parallel axis).  ``key``: optional uint32 key-data; when given,
    ``stage_fn`` receives a per-(microbatch, stage) folded key for
    dropout.
    """
    S = mesh.shape[axis]
    B = x.shape[0]
    if B % n_microbatches:
        raise MXNetError(f"batch {B} not divisible by "
                         f"n_microbatches {n_microbatches}")
    mb = B // n_microbatches
    x_mb = x.reshape((n_microbatches, mb) + x.shape[1:])
    n_ticks = n_microbatches + S - 1
    with_key = key is not None

    def local_fn(params_loc, x_all, key_data):
        stage = lax.axis_index(axis)
        perm = [(j, (j + 1) % S) for j in range(S)]
        state0 = jnp.zeros(x_all.shape[1:], x_all.dtype)

        def tick(state, t):
            # stage 0 ingests a fresh microbatch; later stages consume
            # what the ring delivered last tick
            inp = lax.dynamic_index_in_dim(
                x_all, jnp.clip(t, 0, n_microbatches - 1), 0,
                keepdims=False)
            state = jnp.where(stage == 0, inp, state)
            if with_key:
                mb_idx = jnp.clip(t - stage, 0, n_microbatches - 1)
                k = jax.random.fold_in(jax.random.fold_in(
                    jax.random.wrap_key_data(key_data), mb_idx), stage)
                out = stage_fn(params_loc, state, jax.random.key_data(k))
            else:
                out = stage_fn(params_loc, state)
            return lax.ppermute(out, axis, perm), out

        _, outs = lax.scan(tick, state0, jnp.arange(n_ticks))
        # on the last stage, tick (S-1)+m emitted microbatch m's result
        outs = outs[S - 1:]
        # broadcast the last stage's rows to every device (masked psum:
        # cheap at these sizes, and replicated-out keeps out_specs simple)
        outs = jnp.where(stage == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(outs, axis)

    bspec = tuple(batch_spec) if batch_spec is not None else ()
    x_spec = P(*((None,) + bspec))
    p_specs = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    out_spec = x_spec
    from . import shard_map_compat
    fn = shard_map_compat(
        local_fn, mesh,
        in_specs=(p_specs, x_spec, P()),
        out_specs=out_spec, check=False)
    key_data = key if key is not None else jnp.zeros((), jnp.uint32)
    from . import _device_put_global, _mesh_is_multiprocess
    if _mesh_is_multiprocess(mesh):
        # cross-process mesh: place host values as global arrays per
        # spec (every process passes the same full value)
        stage_params = jax.tree_util.tree_map(
            lambda leaf, s: _device_put_global(leaf, mesh, s),
            stage_params, p_specs)
        x_mb = _device_put_global(x_mb, mesh, x_spec)
        key_data = _device_put_global(key_data, mesh, P())
        y_mb = fn(stage_params, x_mb, key_data)
        # reshape stays in-graph: eager ops on non-addressable global
        # arrays are rejected by jax.  Output replicated (the merged
        # batch axis has no single-axis sharding after the collapse).
        # Cold multiprocess path: one compile per pipeline shape.
        return jax.jit(  # mxlint: disable=retrace-inline-jit
            lambda a: a.reshape((B,) + a.shape[2:]),
            out_shardings=jax.NamedSharding(mesh, P()))(y_mb)
    y_mb = fn(stage_params, x_mb, key_data)
    return y_mb.reshape((B,) + y_mb.shape[2:])


def stack_stage_params(per_layer_vals: Sequence[Sequence[jax.Array]]):
    """Stack per-layer parameter value lists into leading-dim-L leaves:
    ``[[w0,b0],[w1,b1],...] -> [stack(w),stack(b)]``.  All layers must
    be structurally identical (the homogeneous-stack contract)."""
    n = {len(v) for v in per_layer_vals}
    if len(n) != 1:
        raise MXNetError(f"layers are not homogeneous: param counts {n}")
    return [jnp.stack([vals[j] for vals in per_layer_vals])
            for j in range(n.pop())]


class PipelineTrainStep:
    """Compiled training step: replicated embed → layer pipeline over
    the ``pp`` axis → replicated head → loss; fwd+bwd+optimizer in one
    XLA program.

    ``cells`` must be structurally identical HybridBlocks (e.g.
    ``TransformerEncoderCell``s) whose forward maps (mb, ...) → same
    shape; ``len(cells)`` divisible by ``mesh.shape[pp_axis]``.  The
    stacked cell parameters live sharded over ``pp`` between steps;
    call :meth:`sync_params` to write them back into the Parameter
    objects (for checkpointing).
    """

    def __init__(self, embed, cells, head, loss_fn, optimizer,
                 mesh: Mesh, pp_axis: str = "pp",
                 n_microbatches: int = 4, dp_axis: Optional[str] = None,
                 donate: bool = True):
        from .. import optimizer as opt_mod
        from . import _opt_rule
        if not isinstance(optimizer, opt_mod.Optimizer):
            optimizer = opt_mod.create(optimizer)
        self.embed, self.cells, self.head = embed, cells, head
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.pp_axis = pp_axis
        self.dp_axis = dp_axis
        self.n_microbatches = n_microbatches
        self.donate = donate
        S = mesh.shape[pp_axis]
        if len(cells) % S:
            raise MXNetError(f"{len(cells)} layers not divisible by "
                             f"pipeline size {S}")
        self._opt_init, self._opt_update = _opt_rule(optimizer)
        self._built = False
        self._compiled: Dict[Any, Any] = {}
        self._t = 0

    # -- setup ----------------------------------------------------------
    def _setup(self, x_nd):
        import mxtpu.autograd as autograd
        from ..ndarray.ndarray import NDArray

        # deferred init through one eager pass of the whole model
        need = any(p._data is None for blk in
                   [self.embed, *self.cells, self.head]
                   for p in blk.collect_params().values())
        if need:
            with autograd.pause():
                h = self.embed(x_nd)
                h = h[0] if isinstance(h, (list, tuple)) else h
                for c in self.cells:
                    h = c(h)
                self.head(h)

        def pvals(blk):
            ps = list(blk.collect_params().values())
            return ps, [p._data._data for p in ps]

        self._embed_params, ev = pvals(self.embed)
        self._head_params, hv = pvals(self.head)
        cell_vals = []
        self._cell_params = []
        for c in self.cells:
            ps, vs = pvals(c)
            self._cell_params.append(ps)
            cell_vals.append(vs)
        for ps in self._cell_params:
            if [tuple(v.shape) for v in cell_vals[0]] != \
                    [p._data._data.shape for p in ps]:
                raise MXNetError("pipeline cells are not homogeneous")
        from ..symbol import _is_aux_name
        for blk in [self.embed, *self.cells, self.head]:
            # BN-style aux updates would need per-tick writeback through
            # the scan — unsupported; transformer stacks carry none.
            # (_apply_block also hard-fails if a trace EMITS aux, so
            # unconventionally-named running stats can't slip through.)
            for p in blk.collect_params().values():
                if _is_aux_name(p.name):
                    raise MXNetError(
                        "pipeline stages with aux (running stats) "
                        "are unsupported")

        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        self._ev = [jax.device_put(v, repl) for v in ev]
        self._hv = [jax.device_put(v, repl) for v in hv]
        stacked = stack_stage_params(cell_vals)
        self._sv = [jax.device_put(v, NamedSharding(mesh, P(self.pp_axis)))
                    for v in stacked]
        # honour grad_req='null' (frozen params).  For the stacked cell
        # params this must be uniform across layers per slot — the
        # stacked leaf updates as one unit.
        eh = self._embed_params + self._head_params
        self._eh_train = [i for i, p in enumerate(eh)
                          if p.grad_req != "null"]
        self._slot_train = []
        for j in range(len(self._sv)):
            reqs = {ps[j].grad_req for ps in self._cell_params}
            if len(reqs) > 1:
                raise MXNetError(
                    f"grad_req must be uniform across pipeline layers "
                    f"for param slot {j}: {reqs}")
            if reqs.pop() != "null":
                self._slot_train.append(j)
            mults = {(ps[j].lr_mult, ps[j].wd_mult)
                     for ps in self._cell_params}
            if len(mults) > 1:
                raise MXNetError(
                    f"lr_mult/wd_mult must be uniform across pipeline "
                    f"layers for param slot {j}: {mults}")
        self._opt_state = jax.device_put(
            tuple(self._opt_init(eh[i]._data._data)
                  for i in self._eh_train), repl)
        self._opt_state_s = tuple(
            jax.device_put(self._opt_init(self._sv[j]),
                           NamedSharding(mesh, P(self.pp_axis)))
            for j in self._slot_train)
        self._built = True

    # -- trace helpers --------------------------------------------------
    def _apply_block(self, blk, params, vals, x_raw, training, key_data):
        from ..gluon.block import _traced_forward
        from ..ndarray.ndarray import NDArray
        outs, _, aux_params, _ = _traced_forward(
            blk, params, vals, [NDArray(x_raw, None, _placed=True)],
            training, key_data)
        if aux_params:
            raise MXNetError(
                f"pipeline stages with aux (running-stat) updates are "
                f"unsupported: {[p.name for p in aux_params]}")
        return outs[0] if len(outs) == 1 else outs

    def _build(self, x_raw, y_raw, training):
        cell0 = self.cells[0]
        cell0_params = self._cell_params[0]
        loss_fn = self.loss_fn
        n_embed = len(self._ev)
        mesh, pp_axis, dp_axis = self.mesh, self.pp_axis, self.dp_axis
        n_micro = self.n_microbatches
        apply_block = self._apply_block

        def stage_fn(params_loc, h, key_data):
            # params_loc leaves: (L/S, ...) — scan this stage's layers
            def layer(carry, xs):
                lp, k = xs
                return apply_block(cell0, cell0_params, list(lp), carry,
                                   training, k), None
            nloc = params_loc[0].shape[0]
            # key_data is already unique per (microbatch, stage); fold
            # the local layer index for per-layer dropout masks
            keys = jax.vmap(
                lambda i: jax.random.key_data(jax.random.fold_in(
                    jax.random.wrap_key_data(key_data), i)))(
                jnp.arange(nloc))
            h, _ = lax.scan(layer, h, (tuple(params_loc), keys))
            return h

        def loss_flat(ev, hv, sv, key_data, x, y):
            from ..ndarray.ndarray import NDArray
            kf = jax.random.wrap_key_data(key_data)
            ke, kp, kh = (jax.random.key_data(jax.random.fold_in(kf, i))
                          for i in range(3))
            h = apply_block(self.embed, self._embed_params, list(ev),
                            x, training, ke)
            h = spmd_pipeline(
                stage_fn, list(sv), h, mesh=mesh, axis=pp_axis,
                n_microbatches=n_micro,
                batch_spec=P(dp_axis) if dp_axis else None, key=kp)
            out = apply_block(self.head, self._head_params, list(hv), h,
                              training, kh)
            pred = NDArray(out, None, _placed=True)
            l = loss_fn(pred, NDArray(y, None, _placed=True))
            raw = l.data if hasattr(l, "data") else l
            return jnp.mean(raw.astype(jnp.float32))

        if not training:
            return {"eval": jax.jit(loss_flat)}

        eh_train = self._eh_train
        slot_train = self._slot_train

        def step(ev, hv, sv, opt_state, opt_state_s, key_data,
                 lrs, wds, lrs_s, wds_s, x, y):
            loss, (ge, gh, gs) = jax.value_and_grad(
                loss_flat, argnums=(0, 1, 2))(ev, hv, sv, key_data, x, y)
            vals = list(ev) + list(hv)
            grads = list(ge) + list(gh)
            new_st = []
            for k, i in enumerate(eh_train):
                w2, st2 = self._opt_update(vals[i], grads[i],
                                           opt_state[k], lrs[k], wds[k])
                vals[i] = w2
                new_st.append(st2)
            new_s = list(sv)
            new_st_s = []
            for k, j in enumerate(slot_train):
                w2, st2 = self._opt_update(sv[j], gs[j], opt_state_s[k],
                                           lrs_s[k], wds_s[k])
                new_s[j] = w2
                new_st_s.append(st2)
            return (loss, tuple(vals[:n_embed]), tuple(vals[n_embed:]),
                    tuple(new_s), tuple(new_st), tuple(new_st_s))

        donate = (0, 1, 2, 3, 4) if self.donate else ()
        return {"fn": jax.jit(step, donate_argnums=donate)}

    # -- the hot call ---------------------------------------------------
    def __call__(self, x, y, training: bool = True):
        from ..ndarray import random as _rnd
        from ..ndarray.ndarray import NDArray
        x_raw = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        y_raw = y.data if isinstance(y, NDArray) else jnp.asarray(y)
        if not self._built:
            self._setup(x if isinstance(x, NDArray)
                        else NDArray(x_raw, None, _placed=True))
        repl = NamedSharding(self.mesh, P())
        if self.dp_axis is not None:
            spec = [None] * x_raw.ndim
            spec[0] = self.dp_axis
            x_raw = jax.device_put(
                x_raw, NamedSharding(self.mesh, P(*spec)))
            yspec = [None] * max(y_raw.ndim, 1)
            yspec[0] = self.dp_axis
            y_raw = jax.device_put(
                y_raw,
                NamedSharding(self.mesh, P(*yspec[:y_raw.ndim])))
        else:
            x_raw = jax.device_put(x_raw, repl)
            y_raw = jax.device_put(y_raw, repl)
        sig = (x_raw.shape, str(x_raw.dtype), y_raw.shape,
               str(y_raw.dtype), training)
        entry = self._compiled.get(sig)
        if entry is None:
            entry = self._build(x_raw, y_raw, training)
            self._compiled[sig] = entry
        key = _rnd._next_key(None)
        key_data = jax.device_put(jax.random.key_data(key), repl)
        if not training:
            # eval: loss only — no optimizer update, no step-counter
            # advance, parameters untouched
            loss = entry["eval"](tuple(self._ev), tuple(self._hv),
                                 tuple(self._sv), key_data, x_raw, y_raw)
            return NDArray(loss, None, _placed=True)
        self._t += 1
        opt = self.optimizer
        opt.num_update = self._t
        from . import _adam_bias_correction
        base = opt.learning_rate * _adam_bias_correction(opt, self._t)
        # live per-param mults, matching TrainStep._lrs_wds semantics
        eh = self._embed_params + self._head_params
        lrs = jnp.asarray([base * eh[i].lr_mult for i in self._eh_train],
                          jnp.float32)
        wds = jnp.asarray([opt.wd * eh[i].wd_mult
                           for i in self._eh_train], jnp.float32)
        # mults are read live each step, but the stacked leaf updates as
        # one unit — re-validate uniformity so a mid-training change on
        # one cell can't be silently ignored
        for j in self._slot_train:
            mults = {(ps[j].lr_mult, ps[j].wd_mult)
                     for ps in self._cell_params}
            if len(mults) > 1:
                raise MXNetError(
                    f"lr_mult/wd_mult diverged across pipeline layers "
                    f"for param slot {j}: {mults} (stacked layers "
                    f"update as one unit)")
        c0 = self._cell_params[0]
        lrs_s = jnp.asarray([base * c0[j].lr_mult
                             for j in self._slot_train], jnp.float32)
        wds_s = jnp.asarray([opt.wd * c0[j].wd_mult
                             for j in self._slot_train], jnp.float32)
        lrs, wds, lrs_s, wds_s = (jax.device_put(a, repl)
                                  for a in (lrs, wds, lrs_s, wds_s))
        loss, ev, hv, sv, st, st_s = entry["fn"](
            tuple(self._ev), tuple(self._hv), tuple(self._sv),
            self._opt_state, self._opt_state_s,
            key_data, lrs, wds, lrs_s, wds_s, x_raw, y_raw)
        self._ev, self._hv, self._sv = list(ev), list(hv), list(sv)
        self._opt_state, self._opt_state_s = st, st_s
        return NDArray(loss, None, _placed=True)

    # -- parameter writeback -------------------------------------------
    def sync_params(self) -> None:
        """Write the (replicated / pp-sharded) training values back into
        the source Parameter objects, unstacking the layer dimension —
        so ``save_parameters`` checkpoints see the trained weights."""
        if not self._built:
            return
        # stage through host so the written-back buffers are ordinary
        # single-device arrays (eager ops reject mixed mesh/plain
        # placements)
        for p, v in zip(self._embed_params, self._ev):
            p._data._data = jnp.asarray(np.asarray(v))
        for p, v in zip(self._head_params, self._hv):
            p._data._data = jnp.asarray(np.asarray(v))
        for j, stacked in enumerate(self._sv):
            host = np.asarray(stacked)
            for i, ps in enumerate(self._cell_params):
                ps[j]._data._data = jnp.asarray(host[i])


    # -- checkpoint/resume (parity with TrainStep) ----------------------
    def save_states(self, fname: str) -> None:
        """Serialize optimizer state + step counter; pair with
        :meth:`sync_params` + ``save_parameters`` for a full resumable
        checkpoint."""
        import pickle
        if not self._built:
            raise MXNetError("nothing to save: step never ran")
        with open(fname, "wb") as f:
            pickle.dump({
                "t": self._t,
                "opt_state": jax.tree_util.tree_map(
                    np.asarray, self._opt_state),
                "opt_state_s": jax.tree_util.tree_map(
                    np.asarray, self._opt_state_s),
            }, f)

    def load_states(self, fname: str) -> None:
        import pickle
        if not self._built:
            raise MXNetError("load_states requires a built step: run "
                             "one step (or call _setup) first")
        with open(fname, "rb") as f:
            data = pickle.load(f)  # mxlint: disable=raw-deserialize (optimizer-state checkpoint: own save_states framing, arrays not executables)
        self._t = data["t"]
        repl = NamedSharding(self.mesh, P())
        self._opt_state = jax.device_put(
            jax.tree_util.tree_map(jnp.asarray, data["opt_state"]), repl)
        self._opt_state_s = tuple(
            jax.device_put(jax.tree_util.tree_map(jnp.asarray, st),
                           NamedSharding(self.mesh, P(self.pp_axis)))
            for st in data["opt_state_s"])


def build_pipeline_train_step(embed, cells, head, loss_fn,
                              optimizer="sgd", optimizer_params=None,
                              mesh: Optional[Mesh] = None,
                              pp_axis: str = "pp",
                              n_microbatches: int = 4,
                              dp_axis: Optional[str] = None,
                              donate: bool = True) -> PipelineTrainStep:
    """Compile embed→cells-pipeline→head into one SPMD GPipe step."""
    from .. import optimizer as opt_mod
    if not isinstance(optimizer, opt_mod.Optimizer):
        optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
    if mesh is None:
        raise MXNetError("pipeline parallelism requires a mesh with a "
                         f"'{pp_axis}' axis")
    return PipelineTrainStep(embed, cells, head, loss_fn, optimizer,
                             mesh, pp_axis=pp_axis,
                             n_microbatches=n_microbatches,
                             dp_axis=dp_axis, donate=donate)
