"""Engine controls (reference ``python/mxnet/engine.py``† +
``MXNET_ENGINE_TYPE`` semantics, SURVEY §5.2).

The dependency engine itself is subsumed by XLA/PjRt async dispatch
(SURVEY §2.1-N5); what survives is the *debugging surface*:

- ``set_bulk_size`` — the reference's bulked-execution knob
  (``MXNET_EXEC_BULK_EXEC_TRAIN``†).  The TPU-native bulk path is
  ``TrainStep.run_steps`` (``mxtpu/parallel``): N optimizer steps
  scanned inside ONE compiled program, amortizing host dispatch the
  way the reference's engine bulked op segments.  The value set here
  is the default ``steps`` consumers of ``bulk_size()`` use (eager
  per-op dispatch itself is already async-batched by jax).
- NaiveEngine mode — ``MXNET_ENGINE_TYPE=NaiveEngine`` (or
  ``set_sync_mode(True)``) makes every eager op synchronous: each
  dispatch blocks until the result is materialized, turning async
  heisenbugs into reproducible stack traces, exactly the reference's
  serial-debug switch.
"""
from __future__ import annotations

from contextlib import contextmanager

from . import knobs

__all__ = ["set_bulk_size", "bulk_size", "bulk", "set_sync_mode",
           "sync_enabled"]

_BULK_SIZE = 15
# MXTPU_ENGINE_TYPE falls back to the reference MXNET_ENGINE_TYPE
# spelling inside knobs.get, preserving the original env contract.
_SYNC = knobs.get("MXTPU_ENGINE_TYPE") == "NaiveEngine" or \
    knobs.get("MXTPU_ENGINE_SYNC")


def set_bulk_size(size: int) -> int:
    """Set (and return the previous) bulk execution size
    (reference ``set_bulk_size``†).  Consumed as the default ``steps``
    for ``TrainStep.run_steps`` by bulk-aware training loops."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


def bulk_size() -> int:
    """Current bulk size (steps per compiled multi-step program)."""
    return _BULK_SIZE


@contextmanager
def bulk(size: int):
    """Bulk-execution scope (reference ``engine.bulk``†)."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


def set_sync_mode(sync: bool) -> bool:
    """Serial (NaiveEngine-style) execution: every op blocks until
    complete.  Returns the previous setting."""
    global _SYNC
    prev, _SYNC = _SYNC, bool(sync)
    return prev


def sync_enabled() -> bool:
    return _SYNC
