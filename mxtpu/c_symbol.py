"""Python side of the symbolic/executor C ABI tier (VERDICT r4 item 6;
reference ``src/c_api/c_api_symbolic.cc``† / ``c_api_executor.cc``†).

``core/c_api_symbolic.cc`` embeds CPython and calls these helpers; the
boundary follows the same conventions as ``c_ndarray.py`` — strings
and string key/value attr pairs cross as C strings, tensors as
NDArray handles from the imperative tier, shapes as flat int arrays.

One deliberate divergence from the reference ABI, documented in
``c_api_symbolic.h``: the reference lets frontends mutate executor
argument arrays in place (aliased device buffers); XLA arrays are
immutable, so argument updates go through explicit
``MXExecutorSetArg`` rebinds instead (the same rebinding discipline
``MXNDArraySyncCopyFromCPU`` already uses).
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .symbol import Symbol, Variable, load_json


class AtomicSymbol:
    """An op + attrs awaiting composition (MXSymbolCreateAtomicSymbol
    semantics: the reference creates the node first, then
    MXSymbolCompose supplies its inputs)."""

    def __init__(self, op_name: str, attrs):
        self.op_name = op_name
        self.attrs = dict(attrs)


def create_from_json(json_str: str) -> Symbol:
    return load_json(json_str)


def create_from_file(fname: str) -> Symbol:
    with open(fname, "r", encoding="utf-8") as f:
        return load_json(f.read())


def save_to_json(sym: Symbol) -> str:
    return sym.tojson()


def save_to_file(sym: Symbol, fname: str) -> None:
    sym.save(fname)


def create_variable(name: str) -> Symbol:
    return Variable(name)


def create_atomic(op_name: str, keys: Sequence[str],
                  vals: Sequence[str]) -> AtomicSymbol:
    from . import symbol as sym_mod
    if not callable(getattr(sym_mod, op_name, None)):
        raise MXNetError(f"unknown operator {op_name}")
    return AtomicSymbol(op_name, zip(keys, vals))


def compose(sym, name: str, keys: Sequence[str],
            args: Sequence[Symbol]):
    """MXSymbolCompose: supply inputs to an atomic symbol (positionally
    when ``keys`` is empty, by name otherwise).  Returns the composed
    Symbol — the C side rebinds the handle to it."""
    from .symbol import _coerce_attr
    from . import symbol as sym_mod
    if isinstance(sym, AtomicSymbol):
        op = getattr(sym_mod, sym.op_name, None)
        if not callable(op):
            raise MXNetError(f"unknown operator {sym.op_name}")
        kwargs = {k: _coerce_attr(v) for k, v in sym.attrs.items()}
        if name:
            kwargs["name"] = name
        if keys:
            kwargs.update(dict(zip(keys, args)))
            return op(**kwargs)
        return op(*args, **kwargs)
    # composing a full symbol: sym(**{input_name: replacement})
    if keys:
        return sym(**dict(zip(keys, args)))
    return sym(*args)


def list_arguments(sym: Symbol) -> List[str]:
    return list(sym.list_arguments())


def list_outputs(sym: Symbol) -> List[str]:
    return list(sym.list_outputs())


def list_auxiliary_states(sym: Symbol) -> List[str]:
    return list(sym.list_auxiliary_states())


def infer_shape(sym: Symbol, names: Sequence[str],
                shapes: Sequence[Sequence[int]]):
    """Returns (arg_shapes, out_shapes, aux_shapes) as tuple lists."""
    kwargs = {n: tuple(int(d) for d in s)
              for n, s in zip(names, shapes)}
    arg_s, out_s, aux_s = sym.infer_shape(**kwargs)
    conv = lambda ss: [tuple(int(d) for d in s) for s in ss]
    return conv(arg_s), conv(out_s), conv(aux_s)


# ---------------------------------------------------------------------
# executor tier
# ---------------------------------------------------------------------

def simple_bind(sym: Symbol, grad_req: str, names: Sequence[str],
                shapes: Sequence[Sequence[int]]):
    """MXExecutorSimpleBind: infer shapes from the provided inputs and
    allocate zero-initialised argument/aux arrays."""
    from .executor import Executor
    kwargs = {n: tuple(int(d) for d in s)
              for n, s in zip(names, shapes)}
    return Executor.simple_bind(sym, grad_req=grad_req, **kwargs)


def executor_set_arg(ex, name: str, arr: NDArray) -> None:
    if name in ex.arg_dict:
        d = ex.arg_dict
    elif name in ex.aux_dict:
        d = ex.aux_dict
    else:
        raise MXNetError(f"executor has no argument '{name}'")
    # reject shape mismatches at assignment time, as the reference ABI
    # does — otherwise the failure surfaces as an opaque XLA error at
    # the next forward, attributed to the wrong call
    cur = d[name]
    if tuple(cur.shape) != tuple(arr.shape):
        raise MXNetError(
            f"MXExecutorSetArg: '{name}' expects shape "
            f"{tuple(cur.shape)}, got {tuple(arr.shape)}")
    d[name] = arr


def executor_get_arg(ex, name: str) -> NDArray:
    if name in ex.arg_dict:
        return ex.arg_dict[name]
    if name in ex.aux_dict:
        return ex.aux_dict[name]
    raise MXNetError(f"executor has no argument '{name}'")


def executor_get_grad(ex, name: str) -> NDArray:
    g = ex.grad_dict.get(name)
    if g is None:
        raise MXNetError(f"no gradient bound for '{name}' "
                         f"(grad_req null?)")
    return g


def executor_forward(ex, is_train: int) -> None:
    ex.forward(is_train=bool(is_train))


def executor_backward(ex, head_grads: Sequence[NDArray]) -> None:
    ex.backward(list(head_grads) if head_grads else None)


def executor_outputs(ex) -> List[NDArray]:
    return list(ex.outputs)
