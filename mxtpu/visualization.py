"""Network visualization (reference ``python/mxnet/visualization.py``†):
``print_summary`` parameter/shape table and a graphviz ``plot_network``
(dot source; rendering needs the optional graphviz binary)."""
from __future__ import annotations

from typing import Dict, Optional

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape: Optional[Dict] = None,
                  line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """Layer-by-layer summary table with parameter counts
    (reference ``print_summary``†)."""
    if shape is None:
        raise MXNetError("print_summary requires input shapes")
    # partial: label vars etc. may be unbound in a summary context
    arg_shapes, out_shapes, aux_shapes = \
        symbol.infer_shape_partial(**shape)
    arg_names = symbol.list_arguments()
    shape_of = dict(zip(arg_names, arg_shapes))
    aux_of = dict(zip(symbol.list_auxiliary_states(), aux_shapes))

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    positions = [int(line_length * p) for p in positions]
    print("_" * line_length)
    print_row(["Layer (type)", "Output Shape", "Param #",
               "Previous Layer"], positions)
    print("=" * line_length)

    total_params = 0
    nodes = symbol._topo()
    counted = set()  # a shared (tied) weight counts once
    for node in nodes:
        if node.op is None:
            continue
        inputs = [src.name for src, _ in node.inputs]
        params = 0
        for src, _ in node.inputs:
            if src.op is not None or id(src) in counted:
                continue
            shp = None
            if src.name in shape_of and src.name not in shape:
                shp = shape_of[src.name]
            elif src.name in aux_of:
                shp = aux_of[src.name]
            if shp:
                counted.add(id(src))
                n = 1
                for d in shp:
                    n *= d
                params += n
        total_params += params
        out_shape = ""
        first = inputs[0] if inputs else ""
        print_row([f"{node.name} ({node.op})", out_shape, params, first],
                  positions)
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the symbol (reference
    ``plot_network``†).  Returns the Digraph; rendering to disk needs
    the graphviz system binary."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError(
            "plot_network requires the python graphviz package") from e
    node_attrs = node_attrs or {}
    dot = Digraph(name=title)
    attrs = {"shape": "box", "fixedsize": "false"}
    attrs.update(node_attrs)
    hidden = set()
    if hide_weights:
        for node in symbol._topo():
            if node.op is not None:
                for src, _ in node.inputs:
                    if src.op is None and (
                            src.name.endswith(("weight", "bias", "gamma",
                                               "beta", "mean", "var"))):
                        hidden.add(id(src))
    for node in symbol._topo():
        if id(node) in hidden:
            continue
        label = node.name if node.op is None else \
            f"{node.op}\n{node.name}"
        dot.node(str(id(node)), label=label, **attrs)
    for node in symbol._topo():
        if node.op is None:
            continue
        for src, _ in node.inputs:
            if id(src) in hidden:
                continue
            dot.edge(str(id(src)), str(id(node)))
    return dot
