"""``mx.random`` — global seeding (reference ``python/mxnet/random.py``†).

Delegates to the counter-based key streams in ``mxtpu.ndarray.random``."""
from .ndarray.random import (seed, uniform, normal, randn, gamma,
                             exponential, poisson, negative_binomial,
                             generalized_negative_binomial, multinomial,
                             shuffle, randint, bernoulli)

__all__ = ["seed", "uniform", "normal", "randn", "gamma", "exponential",
           "poisson", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle", "randint", "bernoulli"]
