# mxlint: hot-path
"""mxtpu.serving.generate — KV-cache incremental decode with
continuous batching, token streaming, and replay-on-steal (ISSUE 19
tentpole).

Three pieces:

- :class:`GenerateRunner` AOT-compiles a *prefill* executable per
  (batch-rung x prompt-bucket) and ONE incremental *decode-step*
  executable over a preallocated bucket-paged KV cache.  The cache is
  a slot table: each in-flight request owns a cache *lane* (axis 2 of
  the stacked ``(num_layers, 2, slots, heads, L, head_dim)`` array);
  ``kv_cache_write`` (``lax.dynamic_update_slice`` under vmap) writes
  each lane at its OWN step index and ``cached_attention`` masks
  scores to each lane's valid prefix, so stale cache beyond a lane's
  frontier is unreachable and lane reuse needs no zeroing.  Both
  executables load-or-compile through the persistent disk cache
  (ISSUE 13) under generation-specific keys, so a rollout's first
  token on a warmed worker is never a compile.

- :class:`GenerateRequest` is the streaming future: tokens fire
  through ``on_token`` as they are sampled, ``result()`` returns the
  full stream, and ``partial_state()`` describes generation progress
  so a worker death mid-decode hands the fleet layer everything a
  replay needs (prompt + already-streamed tokens + the ORIGINAL
  submit clock — ``WorkerLost.partial``).

- :class:`GenerateBatcher` is the continuous (in-flight) batching
  policy, pure and clock-injected like :class:`DynamicBatcher`: each
  ``step(now)`` admits queued requests into freed lanes (join at a
  step boundary — grouped by prompt bucket, prefilled, first token
  sampled), runs ONE decode step over all lanes, samples/streams one
  token per active lane, and evicts finished (EOS / max_tokens /
  capacity) and deadline-expired requests.  Deterministic in sync
  mode — fake-clock tests drive it step by step.

Sampling is host-side and replay-deterministic: greedy argmax, or
top-k seeded by ``(seed, absolute_position)`` — the same token ids
come out across runs AND across a mid-stream worker steal, because a
replayed request resumes at the same absolute positions.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from .. import guards
from .. import knobs
from .. import obs
from .. import profiler
from .batcher import (InferenceRequest, RequestTimeout, ServerBusy,
                      WorkerLost, _lost_for)
from .runner import batch_ladder

__all__ = ["GenerateRequest", "GenerateRunner", "GenerateBatcher",
           "sample_token"]


def sample_token(logits, *, position: int, seed: int = 0,
                 top_k: int = 1) -> int:
    """Replay-deterministic host-side sampling of ONE token.

    ``top_k <= 1`` is greedy argmax.  Otherwise the top-k logits are
    softmaxed and drawn with a generator seeded by ``(seed,
    absolute_position)`` — a pure function of (logits, seed,
    position), so a replayed generation that re-reaches the same
    position samples the SAME token regardless of which worker (or
    which run) computes it."""
    # mxlint: sync-point — logits are already host rows here
    row = np.asarray(logits, np.float64).reshape(-1)  # mxlint: disable=dtype-hygiene (f64 host sampling on purpose: platform-identical softmax/ties)
    if top_k is None or top_k <= 1:
        return int(np.argmax(row))
    k = min(int(top_k), row.shape[0])
    idx = np.argpartition(row, -k)[-k:]
    # stable descending order: ties break by token id, not partition
    # order, so the distribution is identical on every platform
    idx = idx[np.lexsort((idx, -row[idx]))]
    sub = row[idx] - row[idx].max()
    p = np.exp(sub)
    p /= p.sum()
    rng = np.random.default_rng([int(seed) & 0x7FFFFFFF,
                                 int(position) & 0x7FFFFFFF])
    return int(idx[rng.choice(k, p=p)])


class GenerateRequest(InferenceRequest):
    """Streaming generation future.

    ``prompt`` is the token-id list to condition on; ``prefix`` is the
    already-streamed continuation a REPLAY resumes from (empty for a
    fresh request) — the worker prefills ``prompt + prefix`` and the
    first freshly sampled token has stream index ``len(prefix)``.
    ``on_token(token, index)`` fires per emitted token (the streaming
    channel); ``result()`` returns the full stream
    ``prefix + new tokens``.  ``finish_reason`` is "eos" or "length"
    once complete."""

    __slots__ = ("prompt", "max_tokens", "eos_id", "top_k", "seed",
                 "prefix", "on_token", "tokens", "finish_reason")

    def __init__(self, prompt: Sequence[int], *,
                 max_tokens: int, eos_id: Optional[int] = None,
                 top_k: int = 1, seed: int = 0,
                 prefix: Sequence[int] = (),
                 on_token: Optional[Callable[[int, int], None]] = None,
                 group: Any = None, t_submit: float = 0.0,
                 deadline: Optional[float] = None,
                 trace_id: Optional[str] = None):
        prompt = [int(t) for t in prompt]
        super().__init__(prompt, group=group, seq_len=len(prompt),
                         t_submit=t_submit, deadline=deadline,
                         trace_id=trace_id)
        self.prompt = prompt
        self.max_tokens = int(max_tokens)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.prefix = [int(t) for t in prefix]
        self.on_token = on_token
        # tokens emitted by THIS attempt, appended by the (single)
        # stepping thread under the batcher's _cond; readers see them
        # through partial_state() / result() after completion.
        # mxrace: disable=unguarded-attr (single-writer stepping thread)
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None  # mxrace: disable=unguarded-attr (single-writer stepping thread)

    @property
    def emitted(self) -> int:
        """Total stream length so far (replayed prefix included)."""
        return len(self.prefix) + len(self.tokens)

    def partial_state(self) -> Dict[str, Any]:
        """What a replay needs (rides ``WorkerLost.partial`` when the
        worker holding this request dies): the prompt, EVERY token
        streamed so far (prefix + this attempt), and the ORIGINAL
        submit clock + deadline — a replay resumes the stream and
        inherits the first attempt's deadline accounting, it never
        double-bills."""
        return {"prompt": list(self.prompt),
                "tokens": list(self.prefix) + list(self.tokens),
                "t_submit": self.t_submit,
                "deadline": self.deadline}


class GenerateRunner:
    """AOT-compiled prefill + decode-step executables over a slot-table
    KV cache (one device).

    Parameters
    ----------
    symbol : mxtpu.symbol.Symbol
        A 3-input incremental export (``HybridBlock.export`` of a
        model called in incremental mode): inputs ``(tokens, step,
        cache)``, outputs ``(logits, new_cache)``.  The cache layout
        contract is ``(num_layers, 2, B, heads, L, head_dim)`` —
        exactly what ``TransformerModel.kv_cache_spec`` /
        ``BERTModel.kv_cache_spec`` describe.
    params : dict name -> numpy/NDArray
        Trained weights (uploaded once, shared by every executable).
    kv_spec : tuple
        ``net.kv_cache_spec(max_lanes, max_len)`` — axis 2 is the lane
        count, axis 4 the cache capacity L.  The runner allocates ONE
        extra scratch slot internally (prefill batch padding scatters
        there; its contents are garbage by construction and never
        read), so the device cache has ``max_lanes + 1`` slots.
    prompt_buckets : ascending ints
        Prompt-length rungs; prefill compiles per (batch-rung x
        prompt-bucket).  Prompts (plus replay prefixes) longer than
        the largest bucket prefill in bucket-width chunks.
    quant_scales : dict, optional — calibrated activation thresholds
        (from a :class:`ModelRunner` ``calibrate()`` over the same
        architecture) arming the int8 trace path; required when
        ``quant`` resolves on.  Quantized executables key SEPARATELY
        in the persistent cache (``quant=int8`` key component).
    """

    def __init__(self, symbol, params: Dict[str, Any],
                 kv_spec: Sequence[int], *,
                 prompt_buckets: Sequence[int],
                 input_names: Sequence[str] = ("data0", "data1",
                                               "data2"),
                 device=None, donate: Optional[bool] = None,
                 cache: Any = "auto", amp=None, quant=None,
                 quant_scales: Optional[Dict[str, float]] = None):
        import jax

        from .. import amp as _amp_mod
        from .. import quant as _quant_mod
        self._amp = _amp_mod.resolve(amp)
        self._quant = _quant_mod.resolve(quant)
        self._quant_scales = dict(quant_scales) if quant_scales else None
        self._symbol = symbol
        if len(input_names) != 3:
            raise MXNetError(
                "generate: input_names must be the (tokens, step, "
                "cache) triple of the incremental export")
        self._input_names = tuple(input_names)
        kv_spec = tuple(int(d) for d in kv_spec)
        self.kv_spec = kv_spec  # the declared cache geometry mxmem audits
        if len(kv_spec) != 6 or kv_spec[1] != 2:
            raise MXNetError(
                "generate: kv_spec must be (num_layers, 2, lanes, "
                "heads, L, head_dim) — use net.kv_cache_spec()")
        self.max_lanes = kv_spec[2]
        if self.max_lanes < 1:
            raise MXNetError("generate: kv_spec lane count must be >= 1")
        # one scratch slot past the lanes: prefill batch-padding rows
        # scatter there (duplicate scratch writes are garbage by
        # design — the scratch lane is never sampled from)
        self._slots = self.max_lanes + 1
        self.scratch_slot = self.max_lanes
        self._kv_shape = kv_spec[:2] + (self._slots,) + kv_spec[3:]
        self.max_len = kv_spec[4]
        self.prompt_buckets = tuple(sorted(int(s)
                                           for s in prompt_buckets))
        if not self.prompt_buckets:
            raise MXNetError("generate: prompt_buckets must be "
                             "non-empty")
        if self.prompt_buckets[-1] > self.max_len:
            raise MXNetError(
                f"generate: largest prompt bucket "
                f"{self.prompt_buckets[-1]} exceeds KV capacity "
                f"{self.max_len}")
        self.batch_buckets = batch_ladder(self.max_lanes)
        self._device = device if device is not None else jax.devices()[0]
        if donate is None:
            donate = knobs.get("MXTPU_SERVING_DONATE")
        # _donate records the INTENT (what mxmem's donation-missed
        # rule audits); the CPU backend, where XLA drops donation,
        # is gated at the jit site in _entry so compiled programs
        # stay byte-identical there.
        self._donate = bool(donate)  # mxlint: disable=host-sync

        # -- one weight upload shared by prefill AND decode ------------
        known = set(symbol.list_inputs())
        for n in self._input_names:
            if n not in known:
                raise MXNetError(
                    f"generate: graph has no input {n!r} — pass the "
                    f"incremental export's input_names")
        self._param_names = tuple(
            n for n in params
            if n in known and n not in self._input_names)
        missing = known - set(self._param_names) \
            - set(self._input_names)
        if missing:
            raise MXNetError(
                f"generate: graph inputs {sorted(missing)} have "
                f"neither a param nor an input name")
        if self._amp:
            import jax.numpy as jnp
            from ..symbol import _is_aux_name

            def _stage(n):
                v = self._as_np(params[n])
                if v.dtype == np.float32 and not _is_aux_name(n):
                    v = v.astype(jnp.bfloat16)
                return jax.device_put(v, self._device)

            self._param_vals = tuple(_stage(n)
                                     for n in self._param_names)
        else:
            self._param_vals = tuple(
                jax.device_put(self._as_np(params[n]), self._device)
                for n in self._param_names)
        self._sharding = jax.sharding.SingleDeviceSharding(self._device)
        self._param_structs = tuple(
            jax.ShapeDtypeStruct(v.shape, v.dtype,
                                 sharding=self._sharding)
            for v in self._param_vals)

        self._lock = threading.Lock()
        self._entries: Dict[Tuple, Any] = {}  # guarded-by: _lock
        self.compile_seconds: Dict[Tuple, float] = {}  # guarded-by: _lock
        # source per built entry ("cold" paid XLA, "disk" loaded off
        # the persistent cache) — what the zero-cold-compile-on-a-
        # warmed-worker acceptance test asserts on.
        self._compile_sources: Dict[Tuple, str] = {}  # guarded-by: _lock
        self._guards = guards.enabled()
        self._entry_label = f"GenerateRunner[{type(symbol).__name__}]"
        self._churn = guards.ChurnDetector(
            self._entry_label, limit=len(self.buckets()) + 4)
        self._obs = obs.enabled()
        self._m_compile = obs.counter(
            "mxtpu_serving_compile_total",
            "Bucket executables actually compiled by XLA (cold "
            "builds only — disk-cache hits count in "
            "mxtpu_compile_cache_hit_total instead).",
            labels=("entry",)).labels(entry=self._entry_label)
        _h = obs.histogram(
            "mxtpu_serving_compile_seconds",
            "Per-bucket entry build wall time (source=cold: XLA "
            "compile; source=disk: verified load from the persistent "
            "cache).", labels=("entry", "source"))
        self._m_compile_s = {
            src: _h.labels(entry=self._entry_label, source=src)
            for src in ("cold", "disk")}
        self._m_cache_hit = obs.counter(
            "mxtpu_compile_cache_hit_total",
            "In-process compile-cache misses served from the "
            "persistent disk cache instead of XLA.",
            labels=("entry",)).labels(entry=self._entry_label)

        from .. import cache as cache_mod
        self._cache = cache_mod.default_cache() if cache == "auto" \
            else cache
        self._fingerprint = ""
        if self._cache is not None:
            self._fingerprint = self._model_fingerprint()

    @staticmethod
    def _as_np(v):
        # mxlint: sync-point — host-side param ingest, pre-upload
        return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)

    @classmethod
    def from_export(cls, symbol_file: str, params_file: str,
                    kv_spec: Sequence[int], **kwargs
                    ) -> "GenerateRunner":
        """Load the incremental export's ``-symbol.json`` +
        ``-NNNN.params`` artifacts through the c_predict binding
        path."""
        from .. import symbol as sym_mod
        from ..c_predict import _params_from_bytes
        with open(symbol_file) as f:
            symbol = sym_mod.load_json(f.read())
        with open(params_file, "rb") as f:
            params = _params_from_bytes(f.read())
        return cls(symbol, params, kv_spec, **kwargs)

    # -- buckets ---------------------------------------------------------
    def prompt_bucket_for(self, need: int) -> int:
        """Smallest prompt bucket covering ``need`` tokens — capped at
        the largest bucket (longer prefills chunk at that width)."""
        if need < 1:
            raise MXNetError("generate: empty prompt")
        for s in self.prompt_buckets:
            if s >= need:
                return s
        return self.prompt_buckets[-1]

    def batch_rung_for(self, n: int) -> int:
        if n < 1 or n > self.max_lanes:
            raise MXNetError(
                f"generate: prefill batch {n} outside 1..{self.max_lanes}")
        return next(r for r in self.batch_buckets if r >= n)

    def buckets(self) -> List[Tuple]:
        """Full executable ladder: every (prefill, (batch, prompt))
        rung plus THE decode step — what ``warmup()`` compiles."""
        out: List[Tuple] = [("prefill", (b, s))
                            for s in self.prompt_buckets
                            for b in self.batch_buckets]
        out.append(("decode", (self._slots,)))
        return out

    # -- persistent cache keys (ISSUE 13) --------------------------------
    def _model_fingerprint(self) -> str:
        """sha256 over everything that shapes the compiled programs
        except the bucket: graph json (op names canonicalized), input
        names, KV layout, donation, amp/quant arming.  Weight VALUES
        are runtime arguments — one entry warms every checkpoint of
        the architecture."""
        import hashlib
        import json as _json
        graph = _json.loads(self._symbol.tojson())
        for i, node in enumerate(graph.get("nodes", ())):
            if node.get("op") not in (None, "null"):
                node["name"] = f"_op{i}"
        fp = {
            "symbol": graph,
            "gen_inputs": list(self._input_names),
            "kv_shape": list(self._kv_shape),
            "params": [[n, list(v.shape), str(v.dtype)]
                       for n, v in zip(self._param_names,
                                       self._param_vals)],
            "donate": self._donate,
        }
        if self._amp:
            fp["amp"] = True
        if self._quant:
            fp["quant"] = sorted(
                (self._quant_scales or {}).items()) or True
        blob = _json.dumps(fp, sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:24]

    def _cache_key(self, bucket: Tuple):
        """Persistent-cache key of one generation executable:
        fingerprint x ``gen:<kind>:<shape>`` x device — the ``gen:``
        prefix keys decode-step programs apart from any batch-path
        entry of the same graph, and ``quant=int8`` keys int8 decode
        apart from the float path (never loadable cross-mode)."""
        kind, shp = bucket
        extra = {}
        if self._quant:
            extra["quant"] = "int8"
        return self._cache.key(
            model=self._fingerprint,
            shape=f"gen:{kind}:{tuple(shp)}", mesh="1dev",
            device=getattr(self._device, "device_kind", "unknown"),
            **extra)

    def cached_buckets(self) -> List[Tuple]:
        """Subset of the ladder present in the persistent cache right
        now (existence probe; loads verify later)."""
        if self._cache is None:
            return []
        return [b for b in self.buckets()
                if self._cache.contains(self._cache_key(b))]

    def warm_from_disk(self) -> Dict[Tuple, float]:
        """Warm every ladder entry the persistent cache holds —
        zero cold compiles on a warmed worker (asserted by test via
        :meth:`compile_sources`)."""
        hits = self.cached_buckets()
        if not hits:
            return {}
        return self.warmup(hits)

    def compile_sources(self) -> Dict[Tuple, str]:
        """Per built entry: "cold" (paid XLA) or "disk" (loaded off
        the persistent cache)."""
        with self._lock:
            return dict(self._compile_sources)

    def cold_compiles(self) -> int:
        with self._lock:
            return sum(1 for s in self._compile_sources.values()
                       if s == "cold")

    # -- pure (traceable) programs ---------------------------------------
    def _scopes(self):
        import contextlib
        from .. import amp as _amp_mod
        from .. import quant as _quant_mod
        if self._quant and self._quant_scales is None:
            raise MXNetError(
                "generate: quantized runner has no calibrated scales "
                "— pass quant_scales (from a ModelRunner.calibrate "
                "over the same architecture)")
        scope = contextlib.ExitStack()
        if self._quant:
            scope.enter_context(
                _quant_mod.quantize(self._quant_scales))
        if self._amp:
            scope.enter_context(_amp_mod.autocast())
        return scope

    def _eval_incremental(self, tokens, step, kv_small, param_vals):
        """Trace the incremental graph once: (tokens, step, small
        cache) -> (logits, new small cache), inference mode."""
        import jax.numpy as jnp
        from .. import autograd
        from ..ndarray.ndarray import NDArray
        from ..symbol import _eval_symbol
        if self._amp:
            param_vals = tuple(
                v.astype(jnp.float32)
                if (jnp.issubdtype(v.dtype, jnp.floating)
                    and v.dtype != jnp.float32)
                else v for v in param_vals)
        bindings = {self._input_names[0]: NDArray(tokens, None,
                                                  _placed=True),
                    self._input_names[1]: NDArray(step, None,
                                                  _placed=True),
                    self._input_names[2]: NDArray(kv_small, None,
                                                  _placed=True)}
        for n, v in zip(self._param_names, param_vals):
            bindings[n] = NDArray(v, None, _placed=True)
        prev_rec = autograd.set_recording(False)
        prev_train = autograd.set_training(False)
        try:
            with self._scopes():
                outs = _eval_symbol(self._symbol, bindings)
        finally:
            autograd.set_training(prev_train)
            autograd.set_recording(prev_rec)
        if len(outs) != 2:
            raise MXNetError(
                f"generate: incremental graph must output (logits, "
                f"cache), got {len(outs)} outputs")
        return outs[0].data, outs[1].data

    def _prefill_pure(self):
        """(tokens (b,s), step (b,), lane_idx (b,), kv_big, params) ->
        (logits (b,s,V), kv_big').  Gather-extend-scatter: each row's
        lane is pulled from the slot table, extended by its s tokens
        at its own step offset, and written back — so chunked prefill
        of a long prompt+prefix is just repeated calls at advancing
        step offsets.  Padding rows target the scratch slot."""
        import jax.numpy as jnp

        def fn(tokens, step, lane_idx, kv_big, param_vals):
            idx = lane_idx.astype(jnp.int32)
            kv_small = kv_big[:, :, idx]
            logits, new_small = self._eval_incremental(
                tokens, step, kv_small, param_vals)
            kv_big = kv_big.at[:, :, idx].set(
                new_small.astype(kv_big.dtype))
            return logits, kv_big

        return fn

    def _decode_pure(self):
        """(tokens (slots,1), step (slots,), kv_big, params) ->
        (logits (slots,1,V), kv_big') — THE decode step: every slot
        advances one position; inactive slots compute ignored rows
        (masked attention keeps them finite)."""
        def fn(tokens, step, kv_big, param_vals):
            return self._eval_incremental(tokens, step, kv_big,
                                          param_vals)

        return fn

    def _structs(self, bucket: Tuple):
        import jax
        f32 = np.float32
        kind, shp = bucket

        def sds(shape):
            return jax.ShapeDtypeStruct(tuple(shape), f32,
                                        sharding=self._sharding)

        kv = sds(self._kv_shape)
        if kind == "prefill":
            b, s = shp
            return (sds((b, s)), sds((b,)), sds((b,)), kv)
        if kind == "decode":
            (slots,) = shp
            return (sds((slots, 1)), sds((slots,)), kv)
        raise MXNetError(f"generate: unknown executable kind {kind!r}")

    def _entry(self, bucket: Tuple):
        """Load-or-compile one generation executable (exactly once,
        under ``_lock``) through the persistent cache — same contract
        as ``ModelRunner._entry``."""
        bucket = (bucket[0], tuple(bucket[1]))
        with self._lock:
            entry = self._entries.get(bucket)
            if entry is not None:
                return entry
            import jax
            if self._guards:
                self._churn.note_compile(bucket)
            kind = bucket[0]
            in_structs = self._structs(bucket)
            # the KV slot table is the LAST data operand — donated on
            # accelerator backends so every step recycles it in place
            kv_argnum = len(in_structs) - 1
            t0 = time.perf_counter()
            from mxtpu import analysis
            compiled, source, ckey, cmeta = None, "cold", None, {}
            if self._cache is not None:
                ckey = self._cache_key(bucket)
                compiled, cmeta = self._cache.load(ckey, with_meta=True)  # mxlint: sync-point — disk, pre-serving
                if compiled is not None:
                    source = "disk"
            if compiled is None:
                fn = self._prefill_pure() if kind == "prefill" \
                    else self._decode_pure()
                # donation applied only where XLA honors it; on cpu
                # it is a silent no-op, so skipping it keeps that
                # backend's programs byte-identical
                apply_donate = (self._donate and
                                jax.default_backend() != "cpu")
                with profiler.Task(f"generate:compile:{kind}"
                                   f"{bucket[1]}"):
                    jitted = jax.jit(
                        fn, donate_argnums=(kv_argnum,)
                        if apply_donate else ())
                    compiled = jitted.lower(
                        *in_structs, self._param_structs).compile()
                analysis.maybe_audit(compiled,
                                     label=f"GenerateRunner{bucket}")
                if ckey is not None:
                    self._cache.store(ckey, compiled,
                                      meta=analysis.audit_stamp())
            elif analysis.needs_reaudit(cmeta):
                analysis.maybe_audit(compiled,
                                     label=f"GenerateRunner{bucket}")
            self.compile_seconds[bucket] = time.perf_counter() - t0
            entry = {"compiled": compiled, "in_structs": in_structs}
            self._entries[bucket] = entry
            self._compile_sources[bucket] = source
            if self._obs:
                if source == "cold":
                    self._m_compile.inc()
                else:
                    self._m_cache_hit.inc()
                self._m_compile_s[source].observe(
                    self.compile_seconds[bucket])
                obs.flight("compile").record(
                    "compile_miss", entry=self._entry_label,
                    bucket=str(bucket), source=source,
                    seconds=round(self.compile_seconds[bucket], 4))
            return entry

    def warmup(self, buckets: Optional[Sequence[Tuple]] = None
               ) -> Dict[Tuple, float]:
        """Pre-build the ladder (or a subset) so no token pays a
        compile; returns per-entry build seconds."""
        with guards.no_implicit_transfers(self._guards):
            for bucket in (buckets if buckets is not None
                           else self.buckets()):
                self._entry(bucket)
        with self._lock:
            return dict(self.compile_seconds)

    # -- execution --------------------------------------------------------
    def new_cache(self):
        """Fresh zeroed KV slot table on this runner's device."""
        import jax
        return jax.device_put(
            np.zeros(self._kv_shape, np.float32), self._device)

    def prefill(self, tokens: np.ndarray, step: np.ndarray,
                lane_idx: np.ndarray, kv) -> Tuple[np.ndarray, Any]:
        """One prefill dispatch on already-bucketed host arrays:
        ``tokens (b, s)`` / ``step (b,)`` / ``lane_idx (b,)`` must
        match a ladder rung exactly (the batcher pads).  Returns
        (host logits (b, s, V), new device KV table) — the passed
        table is consumed (donated on accelerator backends)."""
        import jax
        b, s = tokens.shape
        entry = self._entry(("prefill", (b, s)))
        tok = jax.device_put(np.asarray(tokens, np.float32),  # mxlint: sync-point — staging host rows for device_put
                             self._device)
        stp = jax.device_put(np.asarray(step, np.float32),  # mxlint: sync-point — staging host rows for device_put
                             self._device)
        idx = jax.device_put(np.asarray(lane_idx, np.float32),  # mxlint: sync-point — staging host rows for device_put
                             self._device)
        if self._guards:
            self._churn.note_call()
        with guards.no_implicit_transfers(self._guards):
            logits, kv = entry["compiled"](tok, stp, idx, kv,
                                           self._param_vals)
        # mxlint: sync-point — deliberate D2H: the batcher samples on host
        return np.asarray(logits), kv

    def decode(self, tokens: np.ndarray, step: np.ndarray, kv
               ) -> Tuple[np.ndarray, Any]:
        """THE decode step: ``tokens (slots, 1)`` / ``step (slots,)``
        advance every slot one position.  Returns (host logits
        (slots, 1, V), new device KV table)."""
        import jax
        entry = self._entry(("decode", (self._slots,)))
        tok = jax.device_put(np.asarray(tokens, np.float32),  # mxlint: sync-point — staging host rows for device_put
                             self._device)
        stp = jax.device_put(np.asarray(step, np.float32),  # mxlint: sync-point — staging host rows for device_put
                             self._device)
        if self._guards:
            self._churn.note_call()
        with guards.no_implicit_transfers(self._guards):
            logits, kv = entry["compiled"](tok, stp, kv,
                                           self._param_vals)
        # mxlint: sync-point — deliberate D2H: the batcher samples on host
        return np.asarray(logits), kv

    # -- introspection / contracts ----------------------------------------
    def default_bucket(self, kind: str = "decode") -> Tuple:
        if kind == "decode":
            return ("decode", (self._slots,))
        return ("prefill", (self.batch_buckets[-1],
                            self.prompt_buckets[-1]))

    def program_artifact(self, bucket: Optional[Tuple] = None):
        """``(hlo_text, mem_stats)`` of one executable (decode step by
        default) — what tools/hlocheck pins the ``generate_decode``
        contract on."""
        from mxtpu import analysis
        if bucket is None:
            bucket = self.default_bucket()
        compiled = self._entry(bucket)["compiled"]
        return compiled.as_text(), analysis.mem_stats(compiled)

    def program_summary(self, bucket: Optional[Tuple] = None):
        from mxtpu import analysis
        text, mem = self.program_artifact(bucket)
        return analysis.summarize(text, mem)

    def memory_summary(self, buckets: Optional[Sequence[Tuple]] = None):
        """The sanctioned memory view (``mxtpu.analysis.memflow``) of
        this runner's ladder (decode step + largest prefill rung by
        default): per-program HBM decomposition with the KV slot
        table attributed, the kv-geometry oracle, and any memory
        hazard findings."""
        from mxtpu.analysis import memflow
        if buckets is None:
            buckets = [self.default_bucket("prefill"),
                       self.default_bucket("decode")]
        record = memflow.generate_record(self, buckets=buckets)
        budgets = memflow.load_budgets(
            memflow.REPO_ROOT / "contracts")
        return memflow.summary_view(record, budgets)

    def lowered_program_text(self, bucket: Optional[Tuple] = None
                             ) -> str:
        """PRE-optimization HLO of one generation program (lowers
        only, never compiles) — mxprec's ledger substrate."""
        from mxtpu import analysis
        if bucket is None:
            bucket = self.default_bucket()
        bucket = (bucket[0], tuple(bucket[1]))
        fn = self._prefill_pure() if bucket[0] == "prefill" \
            else self._decode_pure()
        return analysis.lowered_text(fn, *self._structs(bucket),
                                     self._param_structs)

    def num_compiled(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- fleet handoff -----------------------------------------------------
    def ladder_metadata(self) -> Dict[str, Any]:
        """What a draining worker hands its replacement: which
        generation executables were actually built and what each
        cost."""
        with self._lock:
            compiled = sorted(self._entries)
            secs = dict(self.compile_seconds)
        return {"max_lanes": self.max_lanes,
                "prompt_buckets": list(self.prompt_buckets),
                "compiled_buckets": [[k, list(s)] for k, s in compiled],
                "compile_seconds": {str(k): v for k, v in secs.items()},
                "weight_bytes": self.weight_bytes()}

    def warm_from(self, metadata: Dict[str, Any]) -> Dict[Tuple, float]:
        """Warm this (replacement) runner from a donor's
        :meth:`ladder_metadata`, restricted to this runner's own
        ladder."""
        own = set(self.buckets())
        donor = [(k, tuple(s))
                 for k, s in metadata.get("compiled_buckets", [])]
        return self.warmup([b for b in donor if b in own])

    def weight_buffers(self) -> Tuple:
        return self._param_vals

    def weight_bytes(self) -> int:
        return int(sum(v.nbytes for v in self._param_vals))


class _Lane:
    """One in-flight generation: the lane's cache frontier (tokens
    written so far) and the last sampled token (next decode input)."""

    __slots__ = ("req", "frontier", "last_token", "t_last")

    def __init__(self, req: GenerateRequest, frontier: int,
                 last_token: int, t_last: float):
        self.req = req
        self.frontier = frontier
        self.last_token = last_token
        self.t_last = t_last


class GenerateBatcher:
    """Continuous (in-flight) batching over a :class:`GenerateRunner`.

    Pure, clock-injected policy: ``submit()`` enqueues, ``step(now)``
    advances the whole slot table one decode step — admitting queued
    requests into freed lanes at the step boundary first (prompt-
    bucket-grouped prefill, first token sampled from the last valid
    prompt position), then ONE decode dispatch over all slots, then
    per-lane sampling, streaming, and eviction (EOS / max_tokens /
    KV capacity / deadline).  No wall time, no threads — fake-clock
    tests drive it deterministically; the server wraps it in a
    stepping thread.

    Lock order: ``_step_lock`` (one stepper at a time) -> ``_cond``
    (queue + lane table); executions run OUTSIDE ``_cond`` so submit
    never blocks on the device."""

    def __init__(self, runner: GenerateRunner, *,
                 max_queue: Optional[int] = None,
                 max_lanes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 stats=None,
                 default_max_tokens: Optional[int] = None,
                 stream: Optional[bool] = None,
                 on_timeout: Optional[Callable[[int], None]] = None):
        self.runner = runner
        # operational width cap (MXTPU_GEN_MAX_LANES): the runner's
        # KV table is sized at export time; this narrows how many of
        # its lanes continuous batching may occupy at once without
        # re-exporting (the decode executable still spans all slots)
        self.max_lanes = max(1, min(
            runner.max_lanes,
            int(max_lanes if max_lanes is not None
                else knobs.get("MXTPU_GEN_MAX_LANES"))))
        self.max_queue = int(max_queue) if max_queue is not None \
            else 8 * runner.max_lanes
        self._clock = clock
        self._stats = stats
        self.default_max_tokens = int(
            default_max_tokens if default_max_tokens is not None
            else knobs.get("MXTPU_GEN_MAX_TOKENS"))
        self.stream = bool(knobs.get("MXTPU_GEN_STREAM")  # mxlint: disable=host-sync (knob bool, no device data)
                           if stream is None else stream)
        self._on_timeout = on_timeout
        self._step_lock = threading.Lock()
        self._cond = threading.Condition()
        self._queue: List[GenerateRequest] = []  # guarded-by: _cond
        # guarded-by: _cond
        self._lanes: List[Optional[_Lane]] = [None] * self.max_lanes
        self._closed = False  # guarded-by: _cond
        self._joins = 0       # guarded-by: _cond — lifetime lane claims
        self._steps = 0       # guarded-by: _cond — decode steps run
        # the slot table lives here; only the stepping thread touches
        # it (single stepper enforced by _step_lock)
        self._kv = None  # guarded-by: _step_lock

    # -- submit side ------------------------------------------------------
    def submit(self, prompt: Sequence[int], *,
               max_tokens: Optional[int] = None,
               eos_id: Optional[int] = None, top_k: int = 1,
               seed: int = 0, prefix: Sequence[int] = (),
               timeout_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               on_token: Optional[Callable[[int, int], None]] = None
               ) -> GenerateRequest:
        """Enqueue one generation; it joins the running decode batch
        at the next step boundary with a free lane.  ``prefix`` seeds
        a replay (already-streamed tokens — prefilled, not re-emitted).
        Raises :class:`ServerBusy` when the bounded queue is full."""
        now = self._clock()
        prompt = [int(t) for t in prompt]
        prefix = [int(t) for t in prefix]
        if not prompt:
            raise MXNetError("generate: empty prompt")
        need = len(prompt) + len(prefix)
        if need >= self.runner.max_len:
            raise MXNetError(
                f"generate: prompt+prefix ({need}) fills the KV "
                f"capacity ({self.runner.max_len}) — nothing left to "
                f"generate")
        mt = int(max_tokens if max_tokens is not None
                 else self.default_max_tokens)
        if mt <= len(prefix):
            raise MXNetError(
                f"generate: max_tokens {mt} already exhausted by the "
                f"replayed prefix ({len(prefix)} tokens)")
        req = GenerateRequest(
            prompt, max_tokens=mt, eos_id=eos_id, top_k=top_k,
            seed=seed, prefix=prefix, on_token=on_token,
            group=self.runner.prompt_bucket_for(need), t_submit=now,
            deadline=None if timeout_s is None else now + timeout_s,
            trace_id=trace_id)
        with self._cond:
            if self._closed:
                raise WorkerLost(
                    "generate: batcher is closed (worker shut down "
                    "or lost) — resubmit elsewhere")
            if len(self._queue) >= self.max_queue:
                raise ServerBusy(
                    f"generate: queue full ({self.max_queue} "
                    f"waiting); retry with backoff")
            self._queue.append(req)
            self._cond.notify()
        return req

    # -- accounting (what the router's admission control reads) ----------
    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def free_lanes(self) -> int:
        with self._cond:
            return sum(1 for l in self._lanes if l is None)

    def active(self) -> Dict[int, GenerateRequest]:
        """Lane table snapshot: {lane index: request} — the lane-
        accounting surface the join-at-step-boundary tests assert
        on."""
        with self._cond:
            return {i: l.req for i, l in enumerate(self._lanes)
                    if l is not None}

    @property
    def joins(self) -> int:
        """Lifetime lane claims (a request joining the running batch
        bumps this exactly once)."""
        with self._cond:
            return self._joins

    @property
    def steps(self) -> int:
        with self._cond:
            return self._steps

    def oldest_waiting_age(self, now: Optional[float] = None
                           ) -> Optional[float]:
        with self._cond:
            if not self._queue:
                return None
            return (self._clock() if now is None else now) \
                - self._queue[0].t_submit

    # -- the step ---------------------------------------------------------
    def step(self, now: Optional[float] = None) -> Dict[str, int]:
        """Advance the whole batch one decode step; returns counters
        ``{"admitted", "active", "emitted", "finished"}``.  The join
        point for queued requests AND the eviction point for finished/
        expired ones — continuous batching is exactly this loop."""
        with self._step_lock:
            now = self._clock() if now is None else now
            # (req, token, stream index, is_first, seconds since the
            # request's previous emission) — fired outside all locks
            emissions: List[Tuple[GenerateRequest, int, int, bool,
                                  float]] = []
            finished: List[GenerateRequest] = []
            # (req, final value): resolved AFTER _fire so the future's
            # done-callbacks (the fleet watcher) observe a fully
            # delivered stream — completing first would let a watcher
            # snapshot the ledger one token short of the final emission
            completions: List[Tuple[GenerateRequest, List[int]]] = []
            with self._cond:
                if self._closed:
                    return {"admitted": 0, "active": 0, "emitted": 0,
                            "finished": 0}
                self._expire_queued_locked(now)
                self._evict_deadlines_locked(now, finished)
                admitted = self._admit_locked(now)
            if admitted:
                self._prefill_locked(admitted, now, emissions, finished,
                              completions)
            with self._cond:
                active = [(i, l) for i, l in enumerate(self._lanes)
                          if l is not None]
            n_active = len(active)
            if active:
                self._decode_locked(active, now, emissions, finished,
                             completions)
            self._fire(emissions, now)
            for r, value in completions:
                r._complete(value, now)
            return {"admitted": len(admitted), "active": n_active,
                    "emitted": len(emissions),
                    "finished": len(finished)}

    def _finish_reason(self, r: GenerateRequest, lane: _Lane
                       ) -> Optional[str]:
        """Evaluated right after each emission: EOS terminates the
        stream; ``max_tokens`` and KV capacity (no room left to write
        the token just emitted, so it cannot be extended) finish as
        "length"."""
        if r.eos_id is not None and lane.last_token == r.eos_id:
            return "eos"
        if r.emitted >= r.max_tokens:
            return "length"
        if lane.frontier >= self.runner.max_len:
            return "length"
        return None

    def _expire_queued_locked(self, now: float) -> None:
        expired = [r for r in self._queue
                   if r.deadline is not None and now > r.deadline]
        if not expired:
            return
        self._queue = [r for r in self._queue if r not in expired]
        if self._on_timeout is not None:
            self._on_timeout(len(expired))
        for r in expired:
            r._fail(RequestTimeout(
                "generate: deadline expired while queued"), now)

    def _evict_deadlines_locked(self, now: float,
                                finished: List[GenerateRequest]
                                ) -> None:
        """Mid-decode deadline eviction: an expired lane frees at the
        step boundary — its caller gets RequestTimeout, never a late
        stream."""
        n_evicted = 0
        for i, lane in enumerate(self._lanes):
            if lane is None:
                continue
            r = lane.req
            if r.deadline is not None and now > r.deadline:
                self._lanes[i] = None
                n_evicted += 1
                r._fail(RequestTimeout(
                    f"generate: deadline expired mid-decode after "
                    f"{r.emitted} tokens"), now)
                finished.append(r)
        if n_evicted and self._on_timeout is not None:
            self._on_timeout(n_evicted)

    def _admit_locked(self, now: float
                      ) -> List[Tuple[int, GenerateRequest]]:
        """Claim freed lanes for the oldest queued requests — one
        prompt-bucket group per step (FIFO head priority, same rule as
        DynamicBatcher)."""
        free = [i for i, l in enumerate(self._lanes) if l is None]
        if not free or not self._queue:
            return []
        head = self._queue[0]
        take = [r for r in self._queue
                if r.group == head.group][:len(free)]
        taken = set(map(id, take))
        self._queue = [r for r in self._queue if id(r) not in taken]
        pairs = []
        for r in take:
            lane = free.pop(0)
            r.t_dequeue = now
            self._joins += 1
            pairs.append((lane, r))
        return pairs

    def _prefill_locked(self, pairs: List[Tuple[int, GenerateRequest]],
                 now: float, emissions, finished,
                 completions) -> None:
        """Prefill the joiners' prompts (+ replay prefixes) into their
        claimed lanes and sample each one's first token.  Prompts
        longer than the bucket chunk at bucket width; batch padding
        rows target the scratch slot.  Device dispatches run outside
        ``_cond``; the lane-table commit reacquires it."""
        runner = self.runner
        if self._kv is None:
            self._kv = runner.new_cache()
        s = pairs[0][1].group
        b = runner.batch_rung_for(len(pairs))
        full = [r.prompt + r.prefix for _, r in pairs]
        need = [len(f) for f in full]
        chunks = max(1, math.ceil(max(need) / s))
        first_logits: List[Optional[np.ndarray]] = [None] * len(pairs)
        t0 = now * 1e6
        for c in range(chunks):
            base = c * s
            tokens = np.zeros((b, s), np.float32)
            step = np.zeros((b,), np.float32)
            lidx = np.full((b,), runner.scratch_slot, np.float32)
            for row, (lane, r) in enumerate(pairs):
                if base >= need[row]:
                    continue  # this row finished in an earlier chunk
                valid = min(s, need[row] - base)
                tokens[row, :valid] = full[row][base:base + valid]
                step[row] = base
                lidx[row] = lane
            logits, self._kv = runner.prefill(tokens, step, lidx,
                                              self._kv)
            for row in range(len(pairs)):
                last = need[row] - 1
                if base <= last < base + s:
                    first_logits[row] = logits[row, last - base]
        with self._cond:
            if self._closed:
                # the batcher died between admit and commit: these
                # joiners were already off the queue, so close()
                # could not see them — fail them here, with partial
                # state (nothing emitted yet) for replay
                err = WorkerLost("generate: batcher closed during "
                                 "prefill")
                for _, r in pairs:
                    if not r.done():
                        r._fail(_lost_for(r, err), now)
                        finished.append(r)
                return
            for row, (lane, r) in enumerate(pairs):
                pos = need[row]  # absolute position of the 1st new token
                tok = sample_token(first_logits[row], position=pos,
                                   seed=r.seed, top_k=r.top_k)
                ln = _Lane(r, frontier=need[row], last_token=tok,
                           t_last=now)
                r.tokens.append(tok)
                emissions.append((r, tok, len(r.prefix), True,
                                  now - r.t_submit))
                reason = self._finish_reason(r, ln)
                if reason is not None:
                    r.finish_reason = reason
                    completions.append(
                        (r, list(r.prefix) + list(r.tokens)))
                    finished.append(r)
                else:
                    self._lanes[lane] = ln
                if r.trace_id is not None and profiler.is_active():
                    obs.span(obs.SPAN_PREFILL, t0, now * 1e6 - t0,
                             trace_id=r.trace_id, cat="gen",
                             lane=lane, prompt=len(r.prompt),
                             prefix=len(r.prefix))
            self._cond.notify_all()

    def _decode_locked(self, active: List[Tuple[int, _Lane]], now: float,
                emissions, finished, completions) -> None:
        """ONE decode dispatch over the whole slot table (each lane's
        last token written at its own frontier), then per-lane
        sampling, finish evaluation, and lane release."""
        runner = self.runner
        slots = runner.max_lanes + 1
        tokens = np.zeros((slots, 1), np.float32)
        steps = np.zeros((slots,), np.float32)
        for i, lane in active:
            tokens[i, 0] = lane.last_token
            steps[i] = lane.frontier
        logits, self._kv = runner.decode(tokens, steps, self._kv)
        done: List[Tuple[int, _Lane, str]] = []
        for i, lane in active:
            r = lane.req
            lane.frontier += 1   # last_token is now in the cache
            dt = now - lane.t_last
            pos = lane.frontier  # absolute position of the new token
            tok = sample_token(logits[i, 0], position=pos,
                               seed=r.seed, top_k=r.top_k)
            lane.last_token = tok
            lane.t_last = now
            r.tokens.append(tok)
            emissions.append((r, tok, r.emitted - 1, False, dt))
            reason = self._finish_reason(r, lane)
            if reason is not None:
                done.append((i, lane, reason))
        with self._cond:
            self._steps += 1
            for i, lane, reason in done:
                if self._lanes[i] is lane:
                    self._lanes[i] = None
                r = lane.req
                r.finish_reason = reason
                completions.append(
                    (r, list(r.prefix) + list(r.tokens)))
                finished.append(r)
            self._cond.notify_all()

    def _fire(self, emissions, now: float) -> None:
        """Stream callbacks + per-token stats/spans, OUTSIDE every
        lock (on_token is arbitrary user code)."""
        stats = self._stats
        active = profiler.is_active()
        for r, tok, index, is_first, dt in emissions:
            if stats is not None:
                if is_first and not r.prefix:
                    # true time-to-first-token: submit -> first emit
                    stats.record_ttft(max(0.0, dt) * 1e6)
                else:
                    stats.record_token(max(0.0, dt) * 1e6)
            if active and r.trace_id is not None:
                obs.span(obs.SPAN_TOKEN, now * 1e6, 0.0,
                         trace_id=r.trace_id, cat="gen", token=tok,
                         index=index)
            if self.stream and r.on_token is not None:
                try:
                    r.on_token(tok, index)
                except Exception:  # noqa: BLE001 — a stream consumer
                    pass           # must never poison the decode loop

    # -- wind-down ---------------------------------------------------------
    def drain(self) -> bool:
        with self._cond:
            return not self._queue and all(
                l is None for l in self._lanes)

    def close(self, error: Optional[BaseException] = None) -> None:
        """Fail everything queued AND every in-flight lane with a
        :class:`WorkerLost` carrying each request's partial-generation
        state (``partial_state()``), so the fleet layer can replay the
        stream on a surviving worker.  No waiter is left hanging."""
        with self._cond:
            self._closed = True
            now = self._clock()
            err = error if error is not None else WorkerLost(
                "generate: batcher closed — worker lost before the "
                "stream completed")
            for r in self._queue:
                r._fail(_lost_for(r, err), now)
            self._queue.clear()
            for i, lane in enumerate(self._lanes):
                if lane is not None and not lane.req.done():
                    lane.req._fail(_lost_for(lane.req, err), now)
                self._lanes[i] = None
            self._cond.notify_all()
