"""Fault-tolerant serving fleet: a front-end router over N workers
(ISSUE 7 tentpole).

``InferenceServer`` round-robins device replicas inside one process
with no notion of a worker dying; this layer is the fleet story on
top: N :class:`FleetWorker`\\ s (each one runner + one bounded
:class:`DynamicBatcher` + optionally one execution thread) behind a
:class:`FleetRouter` that

* runs **active health checks** — periodic canary inferences (result
  compared against an expected output, so silent corruption is a
  detected failure) plus liveness deadlines on dispatched batches and
  queued requests — driving the per-worker
  :class:`~.health.WorkerHealth` state machine
  (HEALTHY → SUSPECT → DRAINING → DEAD → RECOVERING);
* **retries with capped exponential backoff + deterministic jitter**
  (seeded RNG), preferring a worker the request has not tried, with
  optional **hedged requests** (a second attempt dispatched when the
  first is slow; first completion wins, the loser is discarded);
* **requeues — never drops** — the outstanding requests of a dead
  worker: its batcher is closed with :class:`WorkerLost`, the
  attempt watchers fire, and every request whose deadline still
  permits re-enters the dispatch loop (late ones fail fast as
  :class:`RequestTimeout`);
* supports **preemption-safe draining**: ``drain(name)`` stops new
  admissions, the worker flushes its queue and completes in-flight
  work, and :meth:`FleetWorker.handoff` exposes the compiled-ladder
  metadata a replacement warms from (``ModelRunner.warm_from``).

Determinism: the router is clock-injected and tick-driven.  With
``threaded=False`` nothing runs in the background — tests call
``tick(now)`` with a hand-stepped clock and every recovery path in
``tests/test_fleet.py`` is exercised reproducibly against the
scripted :mod:`~.faults` plans.  With ``threaded=True`` (production)
each worker runs an execution thread and the router runs a ticker
thread; the policy code is identical.

ISSUE 11 grows the control plane onto this layer: requests carry a
:class:`~.controlplane.PriorityClass` name, the router's parked
backlog dispatches by weighted round-robin with per-class in-system
quotas, submit-time admission control sheds by *predicted* deadline
feasibility (``ServingStats.queue_eta_us``, class-aware: only
same-or-higher-priority backlog counts ahead — a brownout sheds low
classes first), and ``add_controller`` lets an
:class:`~.controlplane.Autoscaler` ride the tick.

Lock order (must hold): ``FleetRouter._lock`` → ``DynamicBatcher
._cond`` → leaf locks (``_evlock``, ``_class_lock``, request
``_wlock``, ``ServingStats._lock``).  Completion watchers can fire
under a batcher lock, so they only ever touch ``_evlock`` /
``_class_lock`` / request / stats state — never the router lock.
Control-plane hooks (``add_controller``) run at the end of ``tick``
with NO router lock held, because they call back into
``add_worker``/``drain``.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from .. import knobs
from .. import obs
from .. import profiler
from .batcher import (DynamicBatcher, InferenceRequest, RequestTimeout,
                      ServerBusy, WorkerLost)
from .controlplane import PriorityClass, parse_classes
from .faults import FaultPlan, HangSignal, WorkerCrashed
from .generate import GenerateBatcher
from .health import WorkerHealth, WorkerState
from .stats import ServingStats

__all__ = ["FleetRequest", "FleetGenerateRequest", "FleetWorker",
           "FleetRouter"]

logger = logging.getLogger("mxtpu.serving.fleet")


class FleetRequest:
    """Caller-side future spanning every attempt (retries, hedges) the
    router makes for one logical request.  One-shot completion under a
    leaf lock: with hedging, two workers can finish simultaneously."""

    __slots__ = ("payload", "group", "seq_len", "t_submit", "deadline",
                 "retries", "requeues", "hedges", "tried", "last_error",
                 "t_done", "won_by_hedge", "trace_id", "priority",
                 "_event", "_value", "_error", "_wlock", "_on_done")

    def __init__(self, payload: Any, group: Any, seq_len: Optional[int],
                 t_submit: float, deadline: Optional[float],
                 trace_id: Optional[str] = None,
                 priority: str = "default"):
        self.payload = payload
        self.group = group
        self.seq_len = seq_len
        self.t_submit = t_submit
        self.deadline = deadline
        self.trace_id = trace_id  # obs: minted at FleetRouter.submit
        self.priority = priority  # PriorityClass name (ISSUE 11)
        # completion hook for the router's class accounting: set once
        # at submit before any dispatch, invoked exactly once after
        # the one-shot completion — no concurrent mutation by design
        # mxrace: disable=unguarded-attr (set once at submit, before dispatch)
        self._on_done: Optional[
            Callable[["FleetRequest"], None]] = None
        self.retries = 0          # router-level re-dispatches
        self.requeues = 0         # of those, forced by a worker death
        self.hedges = 0           # hedge attempts dispatched
        self.tried: List[str] = []    # worker names, dispatch order
        self.last_error: Optional[BaseException] = None
        # outcome fields are event-sequenced like InferenceRequest's:
        # written under _wlock before _event.set(), read after wait().
        # mxrace: disable=unguarded-attr (event-sequenced via _event)
        self.t_done: Optional[float] = None
        self.won_by_hedge = False
        self._event = threading.Event()
        # mxrace: disable=unguarded-attr (event-sequenced via _event)
        self._value: Any = None
        # mxrace: disable=unguarded-attr (event-sequenced via _event)
        self._error: Optional[BaseException] = None
        self._wlock = threading.Lock()

    def _complete(self, value: Any, now: float,
                  hedge: bool = False) -> bool:
        with self._wlock:
            if self._event.is_set():
                return False
            if self.deadline is not None and now > self.deadline:
                self._error = RequestTimeout(
                    f"serving: fleet request missed its deadline by "
                    f"{(now - self.deadline) * 1e3:.2f} ms")
            else:
                self._value = value
                self.won_by_hedge = hedge
            self.t_done = now
            self._event.set()
            return True

    def _fail(self, error: BaseException, now: float) -> bool:
        with self._wlock:
            if self._event.is_set():
                return False
            self._error = error
            self.t_done = now
            self._event.set()
            return True

    def _notify_done(self) -> None:
        """Run the router's class-accounting hook.  Called by whoever
        won the one-shot ``_complete``/``_fail``, AFTER its stats
        accounting and outside ``_wlock`` (keeps ``_wlock`` a leaf:
        the hook takes the router's class leaf lock)."""
        cb = self._on_done
        if cb is not None:
            try:
                cb(self)
            except Exception:   # noqa: BLE001 — accounting must never
                pass            # poison a completing worker

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise RequestTimeout(
                "serving: fleet result() wait timed out (request "
                "still in flight)")
        if self._error is not None:
            raise self._error
        return self._value

    @property
    def latency_us(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1e6


class FleetGenerateRequest(FleetRequest):
    """Caller-side streamed-generation future spanning every attempt
    (ISSUE 19): tokens arrive through an incremental result channel
    (``_note_token``, wired as the worker attempt's ``on_token``) and
    are DEDUPED BY STREAM INDEX under a leaf lock — a replay after a
    worker death re-emits nothing the caller already saw, and a
    replayed worker disagreeing with the original stream is counted
    as a wrong token (the kill-mid-generation test asserts both stay
    zero).  The dedup ledger doubles as the replay prefix: the next
    attempt prefills ``prompt + tokens_snapshot()`` and resumes."""

    __slots__ = ("prompt", "max_tokens", "eos_id", "top_k", "seed",
                 "on_token", "finish_reason", "_tok_lock", "_stream",
                 "duplicate_tokens", "wrong_tokens")

    def __init__(self, prompt: List[int], *, max_tokens: int,
                 eos_id: Optional[int], top_k: int, seed: int,
                 t_submit: float, deadline: Optional[float],
                 trace_id: Optional[str] = None,
                 priority: str = "default",
                 on_token: Optional[Callable[[int, int], None]] = None):
        super().__init__(None, None, len(prompt), t_submit, deadline,
                         trace_id=trace_id, priority=priority)
        self.prompt = [int(t) for t in prompt]
        self.max_tokens = int(max_tokens)
        self.eos_id = eos_id
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.on_token = on_token
        # mxrace: disable=unguarded-attr (written once by the winning watcher before _event.set())
        self.finish_reason: Optional[str] = None
        # leaf lock (may fire under a GenerateBatcher step): the
        # deduped stream ledger + anomaly counters
        self._tok_lock = threading.Lock()
        self._stream: List[int] = []   # guarded-by: _tok_lock
        self.duplicate_tokens = 0      # guarded-by: _tok_lock
        self.wrong_tokens = 0          # guarded-by: _tok_lock

    def tokens_snapshot(self) -> List[int]:
        """The deduped stream so far — what the NEXT attempt prefills
        as its replay prefix."""
        with self._tok_lock:
            return list(self._stream)

    def _note_token(self, tok: int, index: int) -> None:
        """Incremental result channel (the worker attempt's
        ``on_token``).  Exactly-once forwarding: only the first
        arrival of each stream index reaches the caller; duplicates
        (a replay racing the original) and disagreements are
        counted, never forwarded."""
        fire = False
        with self._tok_lock:
            if index == len(self._stream):
                self._stream.append(int(tok))
                fire = True
            elif index < len(self._stream):
                self.duplicate_tokens += 1
                if self._stream[index] != int(tok):
                    self.wrong_tokens += 1
            else:
                # a gap means the stream skipped indices — count it
                # as wrong rather than silently reordering
                self.wrong_tokens += 1
        if fire and self.on_token is not None:
            try:
                self.on_token(int(tok), int(index))
            except Exception:   # noqa: BLE001 — a stream consumer
                pass            # must never poison the decode loop

    def _merge_partial(self, partial: Dict[str, Any]) -> None:
        """Fold a dead worker's ``WorkerLost.partial`` into the
        ledger: tokens the stream channel already delivered must
        AGREE (else they count as wrong); tokens it never delivered
        (e.g. MXTPU_GEN_STREAM=0) extend it and reach the caller
        exactly once."""
        toks = partial.get("tokens") or []
        added: List[tuple] = []
        with self._tok_lock:
            for i, t in enumerate(toks):
                if i < len(self._stream):
                    if self._stream[i] != int(t):
                        self.wrong_tokens += 1
                else:
                    self._stream.append(int(t))
                    added.append((int(t), i))
        if self.on_token is not None:
            for t, i in added:
                try:
                    self.on_token(t, i)
                except Exception:  # noqa: BLE001
                    pass

    def anomalies(self) -> Dict[str, int]:
        with self._tok_lock:
            return {"duplicate_tokens": self.duplicate_tokens,
                    "wrong_tokens": self.wrong_tokens}


class FleetWorker:
    """One fleet worker: a runner + its own bounded batcher + health
    record (+ an execution thread in threaded mode).  The dispatch
    seam consults an optional :class:`~.faults.FaultPlan`, which is
    how every failure mode is injected deterministically."""

    def __init__(self, runner, name: str = "w0", *,
                 clock=time.monotonic,
                 max_queue_delay_us: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 faults: Optional[FaultPlan] = None,
                 start_recovering: bool = False,
                 liveness_s: Optional[float] = None,
                 dead_after: Optional[int] = None,
                 exec_recovers: bool = False,
                 gen_runner=None):
        if runner is None and gen_runner is None:
            raise ValueError("FleetWorker needs a runner, a "
                             "gen_runner, or both")
        self.runner = runner
        self.name = name
        self._clock = clock
        self.faults = faults
        if max_queue_delay_us is None:
            max_queue_delay_us = knobs.get("MXTPU_SERVING_MAX_DELAY_US")
        if max_queue is None:
            mq = knobs.get("MXTPU_SERVING_MAX_QUEUE")
            max_queue = mq if mq else None
        self.stats = ServingStats(name=f"fleet/{name}", clock=clock)
        # obs flight recorder: bounded ring of structured events for
        # this worker — health transitions, canary verdicts, fault
        # firings, evictions — dumped by the router on death.  The
        # shared no-op when MXTPU_OBS=0.
        self.recorder = obs.flight(f"fleet/{name}", clock=clock)
        self.batcher = DynamicBatcher(
            max_batch_size=runner.max_batch_size if runner is not None
            else 1,
            max_queue_delay_us=max_queue_delay_us,
            max_queue=max_queue, clock=clock,
            on_timeout=self._on_evicted,
            on_depth=self.stats.record_queue_depth)
        # decode plane (ISSUE 19): its own continuous batcher so
        # generation lanes and one-shot inference batches never
        # contend for admission — both planes share the worker's
        # health record and stats
        self.generator = None if gen_runner is None else \
            GenerateBatcher(gen_runner, clock=clock, stats=self.stats,
                            on_timeout=self._on_evicted)
        self.health = WorkerHealth(
            name,
            liveness_s=liveness_s if liveness_s is not None
            else knobs.get("MXTPU_FLEET_LIVENESS_S"),
            dead_after=dead_after if dead_after is not None
            else knobs.get("MXTPU_FLEET_DEAD_AFTER"),
            start_recovering=start_recovering,
            exec_recovers=exec_recovers,
            on_transition=self._on_health_transition)
        self._lock = threading.Lock()
        self._inflight_t: Optional[float] = None  # guarded-by: _lock
        self._inflight_n = 0  # guarded-by: _lock
        self._stuck = False  # guarded-by: _lock
        self._batch_seq = 0  # guarded-by: _lock
        self._stop = threading.Event()
        # control-plane lifecycle, not data-plane state: start() runs
        # once from add_worker before the thread exists; shutdown()
        # is idempotent and joins.  The router serializes both.
        # mxrace: disable=unguarded-attr (control-plane: start/shutdown serialized by the router)
        self._thread: Optional[threading.Thread] = None
        self._shut = False

    # -- obs hooks (leaf-lock only: both may fire under batcher or
    #    router locks) ----------------------------------------------------
    def _on_health_transition(self, now: float, frm: str, to: str,
                              reason: str) -> None:
        self.recorder.record("health", frm=frm, to=to, reason=reason)

    def _on_evicted(self, n: int) -> None:
        self.stats.record_timeout(n)
        self.recorder.record("evicted", n=n)

    # -- admission --------------------------------------------------------
    def submit_attempt(self, payload: Any, group: Any,
                       seq_len: Optional[int],
                       deadline: Optional[float], now: float,
                       canary: bool = False,
                       trace_id: Optional[str] = None
                       ) -> InferenceRequest:
        """Admit one attempt into this worker's queue.  Client traffic
        only lands on a HEALTHY worker; canaries also probe SUSPECT
        and RECOVERING ones (that IS the recovery path).  Raises
        :class:`WorkerLost` (retriable) on refusal, :class:`ServerBusy`
        when the bounded queue is full."""
        if self.runner is None:
            raise WorkerLost(
                f"serving: worker {self.name} is decode-only — "
                f"no inference runner")
        ok = self.health.admits_canary() if canary \
            else self.health.admits()
        if not ok:
            raise WorkerLost(
                f"serving: worker {self.name} is {self.health.state} "
                f"({self.health.reason}) — not admitting")
        timeout_s = None if deadline is None \
            else max(0.0, deadline - now)
        try:
            return self.batcher.submit(payload, group=group,
                                       seq_len=seq_len,
                                       timeout_s=timeout_s,
                                       trace_id=trace_id)
        except ServerBusy as e:
            # price the refusal: the caller's retry can sleep exactly
            # the predicted drain time instead of blind backoff
            if e.retry_after_us is None:
                e.retry_after_us = self.stats.queue_eta_us()
            raise

    def submit_generate_attempt(self, freq: "FleetGenerateRequest",
                                now: float) -> "GenerateRequest":
        """Admit one GENERATION attempt (ISSUE 19).  The replay
        contract lives here: the attempt's prefix is the fleet
        request's deduped stream snapshot, so a resumed rollout
        prefills ``prompt + already-streamed tokens`` and the lane
        picks up at the exact next stream index — tokens the caller
        already saw are never re-emitted (``_note_token`` dedupes by
        index even if a worker disagrees)."""
        if self.generator is None:
            raise WorkerLost(
                f"serving: worker {self.name} has no decode plane — "
                f"cannot host generation")
        if not self.health.admits():
            raise WorkerLost(
                f"serving: worker {self.name} is {self.health.state} "
                f"({self.health.reason}) — not admitting")
        prefix = freq.tokens_snapshot()
        timeout_s = None if freq.deadline is None \
            else max(0.0, freq.deadline - now)
        try:
            return self.generator.submit(
                freq.prompt, max_tokens=freq.max_tokens,
                eos_id=freq.eos_id, top_k=freq.top_k, seed=freq.seed,
                prefix=prefix, timeout_s=timeout_s,
                trace_id=freq.trace_id, on_token=freq._note_token)
        except ServerBusy as e:
            # price a decode refusal in TOKENS, not batches: the ETA
            # is lanes-freeing time, which scales with max_tokens
            if e.retry_after_us is None:
                e.retry_after_us = self.stats.token_eta_us(
                    max(1, freq.max_tokens - len(prefix)))
            raise

    # -- execution ---------------------------------------------------------
    def pump(self, now: Optional[float] = None) -> bool:
        """Deterministic single-step execution: assemble at most one
        ready batch and run it inline.  Returns True if a batch was
        dispatched.  The threaded loop and the router's sync tick both
        funnel through `_dispatch`, so the policy is identical."""
        now = self._clock() if now is None else now
        with self._lock:
            if self._stuck or self._inflight_t is not None:
                return False
            k = self._batch_seq
        if self._stop.is_set() or \
                self.health.state == WorkerState.DEAD or \
                (self.faults is not None and self.faults.wedged(k)):
            return False            # a dead worker executes nothing
        batch = self.batcher.poll(now)
        if batch is None:
            return False
        self._dispatch(batch, now)
        return True

    def pump_generate(self, now: Optional[float] = None) -> bool:
        """One decode step of the continuous-batching loop (ISSUE 19):
        admit joiners at the step boundary, run one fused decode step
        across every occupied lane, emit tokens.  Returns True if the
        step did any work.  The threaded loop and the router's sync
        tick both funnel through ``GenerateBatcher.step``, so
        join/evict policy is identical in both modes."""
        if self.generator is None:
            return False
        now = self._clock() if now is None else now
        with self._lock:
            if self._stuck:
                return False
        if self._stop.is_set() or \
                self.health.state == WorkerState.DEAD:
            return False
        if self.generator.drain():
            return False
        try:
            out = self.generator.step(now)
        except Exception as e:  # noqa: BLE001 — decode-step failure:
            # lanes keep their state; health decides if it's terminal
            self.health.exec_fail(now)
            self.recorder.record("gen_exec_fail", error=str(e))
            logger.debug("fleet worker %s: decode step failed (%s)",
                         self.name, e)
            return False
        if out["admitted"] or out["active"]:
            self.health.exec_ok(now)
        return bool(out["admitted"] or out["active"])

    def _dispatch(self, batch, now: float) -> None:
        with self._lock:
            k = self._batch_seq
            self._batch_seq += 1
            self._inflight_t = now
            self._inflight_n = len(batch.requests)
        # obs queue-wait spans: submit → dequeue, per traced request.
        # Emitted before execution so a mid-flight kill still leaves
        # the wait on record (the worker-clock time base, so the
        # deterministic fake-clock tests see exact phase timings).
        if profiler.is_active():
            for r in batch.requests:
                if r.trace_id is not None and r.t_dequeue is not None:
                    obs.span(obs.SPAN_QUEUE_WAIT, r.t_submit * 1e6,
                             (r.t_dequeue - r.t_submit) * 1e6,
                             trace_id=r.trace_id, worker=self.name)
        try:
            if self.faults is not None:
                self.faults.before_batch(k)
            mutate = self.faults.mutator(k) \
                if self.faults is not None else None
            bucket, _ = self.runner.run_requests(
                batch.requests, now=self._clock(), mutate=mutate)
        except HangSignal:
            # the dispatch would block forever: leave the batch
            # registered in-flight (liveness will notice) and park —
            # from the outside this IS a hung executable
            with self._lock:
                self._stuck = True
            self.stats.bump("hangs")
            self.recorder.record("fault", fault="hang", batch_seq=k,
                                 n=len(batch.requests))
            return
        except WorkerCrashed as e:
            with self._lock:
                self._inflight_t = None
                self._inflight_n = 0
            self.health.crashed(now, str(e))
            self.stats.bump("crashes")
            self.recorder.record("fault", fault="crash", batch_seq=k,
                                 n=len(batch.requests), error=str(e))
            # requests stay incomplete; the router observes DEAD and
            # closes the batcher, which fails them to their watchers
            return
        except Exception as e:  # noqa: BLE001 — transient execution
            with self._lock:    # failure: requeue-once, stay alive
                self._inflight_t = None
                self._inflight_n = 0
            n = self.batcher.requeue(batch.requests, now=self._clock())
            if n:
                self.stats.bump("requeues", n)
            self.health.exec_fail(now)
            self.recorder.record("exec_fail", batch_seq=k,
                                 requeued=n, error=str(e))
            logger.debug("fleet worker %s: batch failed (%s), "
                         "requeued %d", self.name, e, n)
            return
        with self._lock:
            self._inflight_t = None
            self._inflight_n = 0
        # obs execute spans: dispatch → completion on the worker clock
        if profiler.is_active():
            t_end = self._clock()
            for r in batch.requests:
                if r.trace_id is not None:
                    obs.span(obs.SPAN_EXECUTE, now * 1e6,
                             (t_end - now) * 1e6,
                             trace_id=r.trace_id, worker=self.name,
                             batch=len(batch.requests),
                             bucket=str(bucket))
        self.health.exec_ok(now)
        self.stats.record_batch(len(batch.requests), bucket[0])
        for r in batch.requests:
            if r.latency_us is not None:
                self.stats.record_completion(r.latency_us,
                                             r.queue_us or 0.0)
        self.stats.maybe_log()

    # -- liveness signals --------------------------------------------------
    def inflight_age(self, now: float) -> Optional[float]:
        with self._lock:
            return None if self._inflight_t is None \
                else now - self._inflight_t

    def queued_age(self, now: float) -> Optional[float]:
        ages = [self.batcher.oldest_waiting_age(now)]
        if self.generator is not None:
            ages.append(self.generator.oldest_waiting_age(now))
        ages = [a for a in ages if a is not None]
        return max(ages) if ages else None

    def outstanding(self) -> int:
        with self._lock:
            inflight = self._inflight_n
        n = self.batcher.depth + inflight
        if self.generator is not None:
            # live decode lanes count as outstanding work so drain()
            # waits for every stream to finish, not just one-shots
            n += self.generator.depth + len(self.generator.active())
        return n

    # -- threaded mode -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"mxtpu-fleet-{self.name}")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                stuck, k = self._stuck, self._batch_seq
            if stuck or self.health.state == WorkerState.DEAD or \
                    (self.faults is not None
                     and self.faults.wedged(k)):
                # a hung/wedged worker: the thread parks; the router's
                # liveness check is what reaps it
                self._stop.wait(0.02)
                continue
            gen_busy = self.pump_generate()
            # a busy decode plane polls tightly (every lane step emits
            # a token); an idle one parks on the one-shot queue
            batch = self.batcher.wait_next(
                timeout=0.002 if gen_busy else 0.05)
            if batch is None:
                continue
            self._dispatch(batch, self._clock())

    def shutdown(self, error: Optional[BaseException] = None) -> None:
        """Stop the thread (if any) and fail every queued + in-flight
        request with WorkerLost so no waiter hangs.  Idempotent."""
        if self._shut:
            return
        self._shut = True
        self._stop.set()
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=1.0)
        self.batcher.close(error=error)
        if self.generator is not None:
            # every live lane fails with its partial-generation state
            # attached (WorkerLost.partial) — the router's replay path
            # folds that into the fleet request before re-dispatch
            self.generator.close(error=error)

    # -- drain handoff -----------------------------------------------------
    def handoff(self) -> Dict[str, Any]:
        """The donor metadata a replacement warms from: which buckets
        this worker's ladder actually compiled (see
        ``ModelRunner.ladder_metadata``)."""
        meta = {} if self.runner is None \
            else self.runner.ladder_metadata()
        if self.generator is not None:
            meta = dict(meta)
            meta["generate"] = self.generator.runner.ladder_metadata()
        return meta


class _Pending:
    """One parked (re)dispatch: due time + the fleet request."""
    __slots__ = ("due", "freq")

    def __init__(self, due: float, freq: FleetRequest):
        self.due = due
        self.freq = freq


class FleetRouter:
    """Front-end router over N :class:`FleetWorker`\\ s.  See module
    docstring for the full contract.

    >>> router = FleetRouter(clock=..., threaded=False,
    ...                      canary={"data": x}, canary_expect=[y])
    >>> router.add_worker(FleetWorker(runner, "w0", clock=...))
    >>> req = router.submit({"data": x}, timeout_s=1.0)
    >>> router.tick(now)   # deterministic mode: crank the loop
    >>> req.result(timeout=0)
    """

    def __init__(self, *, clock=time.monotonic, threaded: bool = True,
                 canary: Optional[Dict[str, np.ndarray]] = None,
                 canary_expect: Optional[List[np.ndarray]] = None,
                 canary_seq_len: Optional[int] = None,
                 canary_interval_s: Optional[float] = None,
                 canary_timeout_s: Optional[float] = None,
                 retry_max: Optional[int] = None,
                 backoff_base_us: Optional[int] = None,
                 backoff_cap_us: Optional[int] = None,
                 jitter: Optional[float] = None,
                 hedge_after_us: Optional[int] = None,
                 max_pending: Optional[int] = None,
                 tick_s: Optional[float] = None,
                 classes: Optional[List[PriorityClass]] = None,
                 admission: Optional[bool] = None,
                 admission_margin: Optional[float] = None,
                 seed: int = 0, log_every_s: float = 10.0):
        self._clock = clock
        self._threaded = threaded
        self._lock = threading.Lock()
        self._workers: Dict[str, FleetWorker] = {}  # guarded-by: _lock
        self._order: List[str] = []  # guarded-by: _lock
        self._rr = 0  # guarded-by: _lock
        self._pending: List[_Pending] = []  # guarded-by: _lock
        self._live: List[tuple] = []  # guarded-by: _lock
        self._dead_handled: set = set()  # guarded-by: _lock
        self._next_canary: Dict[str, float] = {}  # guarded-by: _lock
        # completion events from attempt watchers; leaf lock ONLY —
        # watchers fire under batcher locks (see module lock order)
        self._evlock = threading.Lock()
        self._events: deque = deque()  # guarded-by: _evlock
        self._canary = canary
        self._canary_expect = canary_expect
        self._canary_seq_len = canary_seq_len
        g = knobs.get
        self._canary_interval_s = canary_interval_s \
            if canary_interval_s is not None \
            else g("MXTPU_FLEET_CANARY_INTERVAL_S")
        self._canary_timeout_s = canary_timeout_s \
            if canary_timeout_s is not None \
            else g("MXTPU_FLEET_CANARY_TIMEOUT_S")
        self._retry_max = retry_max if retry_max is not None \
            else g("MXTPU_FLEET_RETRY_MAX")
        self._backoff_base_us = backoff_base_us \
            if backoff_base_us is not None \
            else g("MXTPU_FLEET_BACKOFF_BASE_US")
        self._backoff_cap_us = backoff_cap_us \
            if backoff_cap_us is not None \
            else g("MXTPU_FLEET_BACKOFF_CAP_US")
        self._jitter = jitter if jitter is not None \
            else g("MXTPU_FLEET_JITTER")
        self._hedge_after_us = hedge_after_us \
            if hedge_after_us is not None \
            else g("MXTPU_FLEET_HEDGE_AFTER_US")
        self._max_pending = max_pending if max_pending is not None \
            else g("MXTPU_FLEET_MAX_PENDING")
        self._tick_s = tick_s if tick_s is not None \
            else g("MXTPU_FLEET_TICK_S")
        # -- priority/fairness + admission control (ISSUE 11) ---------
        cls_list = classes if classes is not None \
            else parse_classes(g("MXTPU_FLEET_CLASSES"))
        if not cls_list:
            cls_list = [PriorityClass("default")]
        self._classes: Dict[str, PriorityClass] = \
            {c.name: c for c in cls_list}
        if len(self._classes) != len(cls_list):
            raise MXNetError("serving: duplicate priority class names")
        self._default_class = "default" if "default" in self._classes \
            else max(cls_list, key=lambda c: c.weight).name
        # guarded-by: _lock
        self._wrr_credit: Dict[str, float] = \
            {n: 0.0 for n in self._classes}
        # in-system (admitted, not completed) requests per class.
        # Leaf lock: decrements fire from completion hooks that may
        # run under a batcher lock (see module lock order).
        self._class_lock = threading.Lock()
        # guarded-by: _class_lock
        self._class_n: Dict[str, int] = \
            {n: 0 for n in self._classes}
        self._admission = admission if admission is not None \
            else g("MXTPU_FLEET_ADMISSION")
        self._admission_margin = admission_margin \
            if admission_margin is not None \
            else g("MXTPU_FLEET_ADMISSION_MARGIN")
        # control-plane hooks (e.g. Autoscaler.tick) run at the END of
        # every tick with NO router lock held
        self._controllers: List[Callable[[float], None]] = []  # guarded-by: _lock
        self._slo = None                # guarded-by: _lock
        self.recorder = obs.flight("fleet/router", clock=clock)
        self._rng = random.Random(seed)
        self.stats = ServingStats(name="fleet", clock=clock,
                                  log_every_s=log_every_s)
        # set when a fleet request fails terminally; tick() checks it
        # outside locks and dumps flight recorders when
        # MXTPU_OBS_DUMP_ON_ERROR asks for it
        self._dump_terminal = False  # guarded-by: _lock
        self._closed = False          # guarded-by: _lock
        self._stop = threading.Event()
        self._ticker: Optional[threading.Thread] = None
        if threaded:
            self._ticker = threading.Thread(
                target=self._tick_loop, daemon=True,
                name="mxtpu-fleet-router")
            self._ticker.start()

    # -- fleet membership --------------------------------------------------
    def add_worker(self, worker: FleetWorker,
                   warm_from: Optional[Dict[str, Any]] = None
                   ) -> Optional[str]:
        """Attach a worker.  ``warm_from`` is a donor's
        :meth:`FleetWorker.handoff` — the replacement pre-compiles the
        donor's bucket working set before its first canary.  With no
        donor metadata, any ladder buckets present in the persistent
        compile cache (ISSUE 13) are warmed from disk instead, so a
        replacement after preemption still serves its first request
        with zero data-path compiles.  All workers must share the
        bucket ladder (same batching groups).  Returns how the worker
        was actually warmed — ``"donor"``, ``"disk_cache"``, or None
        (cold) — so callers (the Autoscaler) can label their events
        without re-probing the cache."""
        warmed = None
        if warm_from is not None:
            if worker.runner is not None and \
                    warm_from.get("compiled_buckets") is not None:
                worker.runner.warm_from(warm_from)
                warmed = "donor"
            if worker.generator is not None and \
                    warm_from.get("generate") is not None:
                worker.generator.runner.warm_from(
                    warm_from["generate"])
                warmed = "donor"
        if warmed is None:
            # one ladder probe per plane: warm_from_disk() returns the
            # buckets it warmed (empty when no cache / no entries)
            hit = worker.runner is not None and \
                bool(worker.runner.warm_from_disk())
            if worker.generator is not None and \
                    worker.generator.runner.warm_from_disk():
                hit = True
            warmed = "disk_cache" if hit else None
        with self._lock:
            if self._closed:
                raise WorkerLost("serving: fleet router is closed")
            if worker.name in self._workers:
                raise MXNetError(
                    f"serving: fleet already has worker "
                    f"{worker.name!r}")
            if self._order:
                r0 = self._workers[self._order[0]].runner
                r = worker.runner
                if r is not None and r0 is not None and (
                        r.max_batch_size != r0.max_batch_size or
                        r.seq_buckets != r0.seq_buckets):
                    raise MXNetError(
                        "serving: fleet workers must share the bucket "
                        "ladder (max_batch_size/seq_buckets)")
                g0 = self._workers[self._order[0]].generator
                g = worker.generator
                if g is not None and g0 is not None and (
                        g.runner.max_lanes != g0.runner.max_lanes or
                        g.runner.prompt_buckets !=
                        g0.runner.prompt_buckets):
                    raise MXNetError(
                        "serving: fleet workers must share the decode "
                        "ladder (max_lanes/prompt_buckets)")
            self._workers[worker.name] = worker
            self._order.append(worker.name)
            self._next_canary[worker.name] = self._clock()
        if self._threaded:
            worker.start()
        return warmed

    def drain(self, name: str, now: Optional[float] = None
              ) -> Dict[str, Any]:
        """Preemption-safe retirement: stop new admissions on
        ``name``; its queue flushes and in-flight work completes on
        the next ticks (bounded by the liveness deadline — a hung
        drain is reaped like any hang).  Returns the handoff metadata
        a replacement warms from."""
        now = self._clock() if now is None else now
        with self._lock:
            worker = self._require_locked(name)
        worker.health.drain(now)
        self.stats.bump("drains")
        return worker.handoff()

    def kill(self, name: str, now: Optional[float] = None) -> None:
        """Operator/preemption kill: the worker is DEAD immediately;
        its outstanding requests are stolen and retried on the next
        tick (deadline permitting) — never dropped."""
        now = self._clock() if now is None else now
        with self._lock:
            worker = self._require_locked(name)
        worker.health.crashed(now, "killed (preemption)")

    def _require_locked(self, name: str) -> FleetWorker:
        w = self._workers.get(name)
        if w is None:
            raise MXNetError(f"serving: fleet has no worker {name!r}")
        return w

    def workers(self) -> Dict[str, str]:
        with self._lock:
            return {n: w.health.state
                    for n, w in self._workers.items()}

    def members(self) -> List[FleetWorker]:
        """Worker objects in attach order (controller read surface)."""
        with self._lock:
            return [self._workers[n] for n in self._order]

    def pending_depth(self) -> int:
        """Requests parked in the router backlog right now."""
        with self._lock:
            return len(self._pending)

    def add_controller(self, fn: Callable[[float], None]) -> None:
        """Register a control-plane hook (e.g. ``Autoscaler.tick``)
        called at the END of every tick with ``now``, no router lock
        held — the hook may call :meth:`add_worker` / :meth:`drain`."""
        with self._lock:
            self._controllers.append(fn)

    def attach_slo(self, engine) -> None:
        """Attach an :class:`~mxtpu.obs.SLOEngine`: its ``tick`` runs
        as a controller (end of every router tick, no router lock)
        and its snapshot joins :meth:`fleet_stats` /
        :meth:`postmortem`.  A no-op for the ``MXTPU_OBS=0`` null
        engine — nothing is registered, ticks stay untouched."""
        if not getattr(engine, "enabled", True):
            return
        with self._lock:
            self._slo = engine
        self.add_controller(engine.tick)

    # -- request path ------------------------------------------------------
    def submit(self, payload: Dict[str, np.ndarray], *,
               seq_len: Optional[int] = None,
               timeout_s: Optional[float] = None,
               priority: Optional[str] = None) -> FleetRequest:
        """Route one request into the fleet.  Returns a
        :class:`FleetRequest` future; raises :class:`ServerBusy` when
        the router's pending buffer is full, the class quota is
        exhausted, or admission control predicts the deadline is
        already infeasible (``retry_after_us`` carries the predicted
        queue ETA in every case)."""
        now = self._clock()
        cname = self._default_class if priority is None else priority
        cls = self._classes.get(cname)
        if cls is None:
            raise MXNetError(
                f"serving: unknown priority class {cname!r} "
                f"(have {sorted(self._classes)})")
        with self._lock:
            if self._closed:
                raise WorkerLost("serving: fleet router is closed")
            if not self._order:
                raise MXNetError("serving: fleet has no workers")
            r0 = next((self._workers[n].runner for n in self._order
                       if self._workers[n].runner is not None), None)
            if r0 is None:
                raise MXNetError("serving: fleet has no inference-"
                                 "capable worker (runner)")
            if len(self._pending) >= self._max_pending:
                self._shed_locked(cls, now, "backlog")
                raise ServerBusy(
                    f"serving: fleet pending buffer full "
                    f"({self._max_pending}); retry with backoff",
                    retry_after_us=self._fleet_eta_locked(cls))
            if cls.quota is not None:
                with self._class_lock:
                    n_cls = self._class_n.get(cls.name, 0)
                if n_cls >= cls.quota:
                    self._shed_locked(cls, now, "quota",
                                      in_system=n_cls)
                    raise ServerBusy(
                        f"serving: class {cls.name!r} quota "
                        f"({cls.quota}) exhausted",
                        retry_after_us=self._fleet_eta_locked(cls))
            if self._admission and timeout_s is not None:
                eta_us = self._fleet_eta_locked(cls)
                budget_us = timeout_s * 1e6
                if eta_us is not None and \
                        self._admission_margin * eta_us > budget_us:
                    self._shed_locked(cls, now, "admission",
                                      eta_us=round(eta_us, 1),
                                      budget_us=round(budget_us, 1))
                    raise ServerBusy(
                        f"serving: predicted queue ETA {eta_us:.0f}us "
                        f"exceeds the {budget_us:.0f}us deadline "
                        f"budget for class {cls.name!r} — shed at "
                        f"submit", retry_after_us=eta_us)
        group = r0.seq_bucket_for(seq_len)
        freq = FleetRequest(payload, group, seq_len, now,
                            None if timeout_s is None
                            else now + timeout_s,
                            trace_id=obs.new_trace_id()
                            if profiler.is_active() else None,
                            priority=cls.name)
        freq._on_done = self._note_request_done
        with self._class_lock:
            self._class_n[cls.name] = \
                self._class_n.get(cls.name, 0) + 1
        if freq.trace_id is not None:
            obs.span(obs.SPAN_SUBMIT, now * 1e6, 0.0,
                     trace_id=freq.trace_id, group=str(group),
                     cls=cls.name)
        with self._lock:
            if not self._dispatch_locked(freq, now):
                self._park_locked(freq, now, now)
        return freq

    def infer(self, payload: Dict[str, np.ndarray], *,
              seq_len: Optional[int] = None,
              timeout_s: Optional[float] = None) -> Any:
        """Blocking convenience wrapper (threaded mode)."""
        req = self.submit(payload, seq_len=seq_len, timeout_s=timeout_s)
        return req.result(timeout=None if timeout_s is None
                          else timeout_s + 5.0)

    def submit_generate(self, prompt: Sequence[int], *,
                        max_tokens: Optional[int] = None,
                        eos_id: Optional[int] = None,
                        top_k: int = 1, seed: int = 0,
                        timeout_s: Optional[float] = None,
                        priority: Optional[str] = None,
                        on_token: Optional[Callable[[int, int], None]]
                        = None) -> FleetGenerateRequest:
        """Route one streamed GENERATION into the fleet (ISSUE 19).
        Returns a :class:`FleetGenerateRequest`; ``on_token(tok,
        index)`` fires exactly once per stream index across every
        retry/steal.  Rides the same priority classes, backlog cap,
        and admission control as :meth:`submit`, except the admission
        ETA is TOKEN-aware: prefill queue ETA plus ``max_tokens``
        decode steps priced from the per-token histogram."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise MXNetError("serving: generate needs a non-empty "
                             "prompt")
        if max_tokens is None:
            max_tokens = knobs.get("MXTPU_GEN_MAX_TOKENS")
        max_tokens = int(max_tokens)
        if max_tokens < 1:
            raise MXNetError("serving: generate needs max_tokens >= 1")
        now = self._clock()
        cname = self._default_class if priority is None else priority
        cls = self._classes.get(cname)
        if cls is None:
            raise MXNetError(
                f"serving: unknown priority class {cname!r} "
                f"(have {sorted(self._classes)})")
        with self._lock:
            if self._closed:
                raise WorkerLost("serving: fleet router is closed")
            if not self._order:
                raise MXNetError("serving: fleet has no workers")
            if not any(self._workers[n].generator is not None
                       for n in self._order):
                raise MXNetError("serving: fleet has no decode-capable "
                                 "worker (gen_runner)")
            if len(self._pending) >= self._max_pending:
                self._shed_locked(cls, now, "backlog")
                raise ServerBusy(
                    f"serving: fleet pending buffer full "
                    f"({self._max_pending}); retry with backoff",
                    retry_after_us=self._fleet_eta_locked(cls))
            if cls.quota is not None:
                with self._class_lock:
                    n_cls = self._class_n.get(cls.name, 0)
                if n_cls >= cls.quota:
                    self._shed_locked(cls, now, "quota",
                                      in_system=n_cls)
                    raise ServerBusy(
                        f"serving: class {cls.name!r} quota "
                        f"({cls.quota}) exhausted",
                        retry_after_us=self._fleet_eta_locked(cls))
            if self._admission and timeout_s is not None:
                # per-token admission: a rollout is only feasible if
                # the queue wait PLUS the whole decode fits the budget
                eta_us = self._gen_eta_locked(cls, max_tokens)
                budget_us = timeout_s * 1e6
                if eta_us is not None and \
                        self._admission_margin * eta_us > budget_us:
                    self._shed_locked(cls, now, "admission",
                                      eta_us=round(eta_us, 1),
                                      budget_us=round(budget_us, 1),
                                      tokens=max_tokens)
                    raise ServerBusy(
                        f"serving: predicted generation ETA "
                        f"{eta_us:.0f}us ({max_tokens} tokens) exceeds "
                        f"the {budget_us:.0f}us deadline budget for "
                        f"class {cls.name!r} — shed at submit",
                        retry_after_us=eta_us)
        freq = FleetGenerateRequest(
            prompt, max_tokens=max_tokens, eos_id=eos_id, top_k=top_k,
            seed=seed, t_submit=now,
            deadline=None if timeout_s is None else now + timeout_s,
            trace_id=obs.new_trace_id()
            if profiler.is_active() else None,
            priority=cls.name, on_token=on_token)
        freq._on_done = self._note_request_done
        with self._class_lock:
            self._class_n[cls.name] = \
                self._class_n.get(cls.name, 0) + 1
        if freq.trace_id is not None:
            obs.span(obs.SPAN_SUBMIT, now * 1e6, 0.0,
                     trace_id=freq.trace_id, cls=cls.name,
                     kind="generate", prompt_len=len(prompt),
                     max_tokens=max_tokens)
        with self._lock:
            if not self._dispatch_locked(freq, now):
                self._park_locked(freq, now, now)
        return freq

    def generate(self, prompt: Sequence[int], *,
                 max_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None, top_k: int = 1,
                 seed: int = 0, timeout_s: Optional[float] = None,
                 on_token: Optional[Callable[[int, int], None]] = None
                 ) -> List[int]:
        """Blocking convenience wrapper (threaded mode): the full
        generated token list."""
        req = self.submit_generate(
            prompt, max_tokens=max_tokens, eos_id=eos_id, top_k=top_k,
            seed=seed, timeout_s=timeout_s, on_token=on_token)
        return req.result(timeout=None if timeout_s is None
                          else timeout_s + 5.0)

    # -- admission control (ISSUE 11) --------------------------------------
    def _shed_locked(self, cls: PriorityClass, now: float, kind: str,
                     **detail: Any) -> None:
        """Account one shed verdict (backlog / quota / admission):
        counters, flight recorder, and a ``fleet/shed`` span."""
        self.stats.record_rejected()
        self.stats.bump(f"shed_{kind}")
        self.recorder.record("shed", reason=kind, cls=cls.name,
                             **detail)
        if profiler.is_active():
            obs.span(obs.SPAN_SHED, now * 1e6, 0.0, cat="fleet",
                     kind=kind, cls=cls.name, **detail)

    def _fleet_eta_locked(self, cls: PriorityClass) -> Optional[float]:
        """Predicted queue wait for a new request of ``cls``: only
        same-or-higher-priority in-system traffic counts as "ahead"
        (WRR serves it first), spread over the admitting workers, each
        priced by its own service-time histogram — the best (lowest)
        endpoint wins, matching where dispatch would place it.  None
        until some worker has a histogram (cold fleet admits
        optimistically)."""
        admitting = [self._workers[n] for n in self._order
                     if self._workers[n].health.admits()]
        if not admitting:
            return None
        with self._class_lock:
            ahead = sum(n for c, n in self._class_n.items()
                        if self._classes[c].weight >= cls.weight)
        share = ahead / len(admitting)
        best: Optional[float] = None
        for w in admitting:
            e = w.stats.queue_eta_us(depth=share)
            if e is None:
                return None     # a cold worker: no histogram — admit
            if best is None or e < best:
                best = e
        return best

    def _gen_eta_locked(self, cls: PriorityClass,
                        max_tokens: int) -> Optional[float]:
        """Per-token admission ETA (ISSUE 19): queue wait (class-aware,
        as in :meth:`_fleet_eta_locked`) PLUS the decode time for
        ``max_tokens`` steps priced from the per-token latency
        histogram, minimized over decode-capable admitting workers.
        None while any candidate is cold — a cold fleet admits
        optimistically and lets real traffic build the histogram."""
        admitting = [self._workers[n] for n in self._order
                     if self._workers[n].generator is not None
                     and self._workers[n].health.admits()]
        if not admitting:
            return None
        with self._class_lock:
            ahead = sum(n for c, n in self._class_n.items()
                        if self._classes[c].weight >= cls.weight)
        share = ahead / len(admitting)
        best: Optional[float] = None
        for w in admitting:
            q = w.stats.queue_eta_us(depth=share)
            t = w.stats.token_eta_us(max_tokens)
            if t is None:
                return None     # cold decode plane — admit
            e = (q or 0.0) + t
            if best is None or e < best:
                best = e
        return best

    def _note_request_done(self, freq: FleetRequest) -> None:
        # FleetRequest._notify_done hook — fires outside _wlock, may
        # run under a batcher lock; touches only the class leaf lock
        with self._class_lock:
            n = self._class_n.get(freq.priority, 0)
            if n > 0:
                self._class_n[freq.priority] = n - 1

    # -- dispatch core -----------------------------------------------------
    def _pick_locked(self, freq: Optional[FleetRequest]
                     ) -> Optional[FleetWorker]:
        """Round-robin over HEALTHY workers, preferring one this
        request has not tried yet ("retry elsewhere")."""
        healthy = [n for n in self._order
                   if self._workers[n].health.admits()]
        if not healthy:
            return None
        tried = set(freq.tried) if freq is not None else ()
        fresh = [n for n in healthy if n not in tried]
        pool = fresh or healthy
        name = pool[self._rr % len(pool)]
        self._rr += 1
        return self._workers[name]

    def _dispatch_locked(self, freq: FleetRequest, now: float,
                         hedge: bool = False) -> bool:
        """Try to place one attempt; False = no worker took it (park
        it).  Called with ``_lock`` held."""
        is_gen = isinstance(freq, FleetGenerateRequest)
        if is_gen and len(freq.tokens_snapshot()) >= freq.max_tokens:
            # the dead worker's partial state already finished the
            # stream — nothing left to replay, complete directly
            if freq._complete(freq.tokens_snapshot(), now):
                freq.finish_reason = freq.finish_reason or "length"
                self.stats.record_completion(
                    (now - freq.t_submit) * 1e6, 0.0)
                freq._notify_done()
            return True
        for _ in range(len(self._order)):
            worker = self._pick_locked(freq)
            if worker is None:
                return False
            try:
                if is_gen:
                    attempt = worker.submit_generate_attempt(freq, now)
                else:
                    attempt = worker.submit_attempt(
                        freq.payload, freq.group, freq.seq_len,
                        freq.deadline, now, trace_id=freq.trace_id)
            except (WorkerLost, ServerBusy) as e:
                # this worker refused; round-robin advances, try next.
                # Keep the refusal: a ServerBusy's retry_after_us hint
                # lets _park_locked price the wait.
                freq.last_error = e
                continue
            freq.tried.append(worker.name)
            if hedge:
                freq.hedges += 1
            if freq.trace_id is not None:
                if is_gen and freq.requeues > 0:
                    obs.span(obs.SPAN_REPLAY, now * 1e6, 0.0,
                             trace_id=freq.trace_id,
                             worker=worker.name,
                             resumed=len(freq.tokens_snapshot()))
                if hedge:
                    obs.span(obs.SPAN_HEDGE, now * 1e6, 0.0,
                             trace_id=freq.trace_id,
                             worker=worker.name)
                elif freq.retries > 0:
                    obs.span(obs.SPAN_REDISPATCH, now * 1e6, 0.0,
                             trace_id=freq.trace_id,
                             worker=worker.name, retry=freq.retries)
            self._live.append((freq, attempt, worker.name, now,
                               hedge))
            attempt.add_done_callback(
                self._watcher(freq, attempt, worker.name, hedge))
            return True
        return False

    def _watcher(self, freq: FleetRequest, attempt: InferenceRequest,
                 wname: str, hedge: bool):
        """Attempt-completion hook.  May fire under a batcher lock:
        touches only the fleet request, stats, and the event deque
        (leaf locks) — never the router lock."""
        def cb() -> None:
            now = self._clock()
            if attempt._error is None:
                value = attempt._value
                if isinstance(freq, FleetGenerateRequest):
                    # the stream channel already deduped every token;
                    # the ledger snapshot IS the authoritative result
                    # (identical to attempt._value on a clean run,
                    # still complete across a mid-stream steal)
                    freq.finish_reason = getattr(
                        attempt, "finish_reason", None)
                    value = freq.tokens_snapshot()
                if freq._complete(value, now, hedge=hedge):
                    self.stats.record_completion(
                        (now - freq.t_submit) * 1e6,
                        (attempt.queue_us or 0.0))
                    if hedge:
                        self.stats.bump("hedges_won")
                    freq._notify_done()
            else:
                with self._evlock:
                    self._events.append(
                        ("attempt_failed", freq, wname,
                         attempt._error))
        return cb

    def _backoff_s(self, n_retry: int) -> float:
        base = min(float(self._backoff_cap_us),
                   float(self._backoff_base_us) * (2 ** (n_retry - 1)))
        return base * (1.0 + self._jitter * self._rng.random()) / 1e6

    def _park_locked(self, freq: FleetRequest, now: float,
                     due: float) -> None:
        """Park a request that found no worker.  When the refusal
        carried a ``retry_after_us`` ETA hint, wait exactly that long
        (capped at the backoff ceiling) instead of retrying every
        tick against a queue we know is full."""
        e = freq.last_error
        hint = getattr(e, "retry_after_us", None)
        if hint:
            due = max(due, now + min(float(hint),
                                     float(self._backoff_cap_us)) / 1e6)
        self._pending.append(_Pending(due, freq))

    def _wrr_next_locked(self, active: Any) -> str:
        """Smooth weighted round-robin over the class names in
        ``active``: each pick adds every active class's weight to its
        credit, serves the max, and charges it the round total —
        interleaves ~weight-proportionally with no starvation.
        Deterministic: sorted names, strictly-greater comparison."""
        names = sorted(active)
        total = 0.0
        best = names[0]
        for n in names:
            w = self._classes[n].weight
            total += w
            self._wrr_credit[n] = self._wrr_credit.get(n, 0.0) + w
            if self._wrr_credit[n] > self._wrr_credit[best]:
                best = n
        self._wrr_credit[best] -= total
        return best

    def _handle_attempt_failed_locked(self, freq: FleetRequest,
                                      wname: str, error: BaseException,
                                      now: float) -> None:
        if freq.done():
            return              # a hedge already won (or terminal)
        freq.last_error = error
        retriable = bool(getattr(error, "retriable", False))
        if freq.deadline is not None and now >= freq.deadline:
            if freq._fail(RequestTimeout(
                    "serving: deadline expired before a retry could "
                    "be placed"), now):
                self.stats.record_timeout()
                freq._notify_done()
            self._dump_terminal = True
            return
        if not retriable or freq.retries >= self._retry_max:
            if freq._fail(error, now):
                freq._notify_done()
            self._dump_terminal = True
            return
        freq.retries += 1
        self.stats.bump("retries")
        if isinstance(error, WorkerLost):
            # the attempt died WITH its worker: this is the
            # requeue-never-drop path, counted separately
            freq.requeues += 1
            self.stats.bump("requeues")
            if isinstance(freq, FleetGenerateRequest) and \
                    getattr(error, "partial", None):
                # fold the dead lane's partial-generation state into
                # the replay ledger: tokens the stream never delivered
                # (MXTPU_GEN_STREAM=0) reach the caller here, and the
                # next attempt's prefix resumes past them — replay
                # never double-bills already-emitted tokens
                freq._merge_partial(error.partial)
            if freq.trace_id is not None:
                obs.span(obs.SPAN_STEAL, now * 1e6, 0.0,
                         trace_id=freq.trace_id, worker=wname)
        hint = getattr(error, "retry_after_us", None)
        if hint:
            # the worker priced its own queue: sleep the predicted
            # drain time (capped), not blind exponential backoff
            due = now + min(float(hint),
                            float(self._backoff_cap_us)) / 1e6
        else:
            due = now + self._backoff_s(freq.retries)
        if freq.trace_id is not None:
            obs.span(obs.SPAN_BACKOFF, now * 1e6, (due - now) * 1e6,
                     trace_id=freq.trace_id, retry=freq.retries)
        self._pending.append(_Pending(due, freq))

    # -- canaries ----------------------------------------------------------
    def _canary_due_locked(self, now: float) -> List[FleetWorker]:
        if self._canary is None or self._canary_interval_s <= 0:
            return []
        due = []
        for name in self._order:
            w = self._workers[name]
            if w.runner is None:
                continue        # decode-only worker: no canary payload
            if not w.health.admits_canary():
                continue
            if now >= self._next_canary.get(name, now):
                self._next_canary[name] = now + self._canary_interval_s
                due.append(w)
        return due

    def _send_canary(self, worker: FleetWorker, now: float) -> None:
        try:
            attempt = worker.submit_attempt(
                self._canary, self._canary_group(), self._canary_seq_len,
                now + self._canary_timeout_s, now, canary=True)
        except ServerBusy:
            # a full queue means the worker is saturated with real
            # traffic, not broken — skip this round (liveness
            # deadlines still catch a wedged queue)
            return
        except WorkerLost:
            with self._evlock:
                self._events.append(("canary", worker.name, False,
                                     "refused"))
            return
        expect = self._canary_expect

        def cb() -> None:
            if attempt._error is not None:
                ok, why = False, f"error: {attempt._error}"
            elif expect is None:
                ok, why = True, "completed"
            else:
                try:
                    ok = len(attempt._value) == len(expect) and all(
                        np.allclose(np.asarray(got), np.asarray(want),
                                    rtol=1e-4, atol=1e-5)
                        for got, want in zip(attempt._value, expect))
                    why = "match" if ok else "result CORRUPT " \
                        "(mismatch vs expected canary output)"
                except Exception as e:  # noqa: BLE001
                    ok, why = False, f"compare failed: {e}"
            with self._evlock:
                self._events.append(("canary", worker.name, ok, why))
        attempt.add_done_callback(cb)

    def _canary_group(self) -> Any:
        with self._lock:
            r0 = next((self._workers[n].runner for n in self._order
                       if self._workers[n].runner is not None), None)
        return None if r0 is None \
            else r0.seq_bucket_for(self._canary_seq_len)

    # -- the tick ----------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """One scheduling round: canaries → (sync) pump → liveness →
        reap the dead → process completion events → re-dispatch due
        retries → hedge slow attempts.  In threaded mode a background
        ticker calls this every ``tick_s``; deterministic tests call
        it directly with the fake clock."""
        now = self._clock() if now is None else now
        with self._lock:
            canary_due = self._canary_due_locked(now)
        for w in canary_due:
            self._send_canary(w, now)
        with self._lock:
            workers = [self._workers[n] for n in self._order]
        if not self._threaded:
            for w in workers:
                for _ in range(64):     # bounded drain of ready work
                    if not w.pump(now):
                        break
                # exactly ONE decode step per worker per tick: joiners
                # land at step boundaries, so a hand-stepped clock sees
                # deterministic join/evict ordering (lane accounting in
                # the continuous-batching tests depends on this)
                w.pump_generate(now)
        # liveness + death reaping
        for w in workers:
            w.health.liveness(now, w.inflight_age(now),
                              w.queued_age(now))
            if w.health.state == WorkerState.DRAINING and \
                    w.outstanding() == 0:
                w.health.drained(now)
                self.stats.bump("drains_completed")
            if w.health.state == WorkerState.DEAD:
                with self._lock:
                    if w.name in self._dead_handled:
                        continue
                    self._dead_handled.add(w.name)
                if not w.health.retired:
                    self.stats.bump("deaths")
                    logger.warning(
                        "fleet: worker %s is DEAD (%s) — stealing "
                        "outstanding requests", w.name, w.health.reason)
                # flight-recorder postmortem: the death event plus an
                # automatic dump of everything the ring still holds
                w.recorder.record("death", reason=w.health.reason,
                                  retired=w.health.retired,
                                  outstanding=w.outstanding())
                w.recorder.dump(
                    reason=f"worker {w.name} DEAD: {w.health.reason}",
                    path=obs.dump_on_error_path() or None)
                # closing the batcher fails queued+inflight with
                # WorkerLost → watchers enqueue retry events below
                w.shutdown(error=None if w.health.retired else
                           WorkerLost(f"serving: worker {w.name} died "
                                      f"({w.health.reason})"))
        # completion / canary events
        while True:
            with self._evlock:
                if not self._events:
                    break
                ev = self._events.popleft()
            if ev[0] == "attempt_failed":
                with self._lock:
                    self._handle_attempt_failed_locked(
                        ev[1], ev[2], ev[3], now)
            elif ev[0] == "canary":
                _, wname, ok, why = ev
                with self._lock:
                    w = self._workers.get(wname)
                if w is None:
                    continue
                w.recorder.record("canary", ok=ok, why=why)
                if not ok and "CORRUPT" in why:
                    # silent corruption is a correctness failure: it
                    # feeds the availability SLO's "wrong" leg
                    self.stats.bump("wrong_results")
                if ok:
                    w.health.canary_ok(now)
                else:
                    prev = w.health.state
                    w.health.canary_fail(now, f"canary ({why})")
                    if prev != w.health.state:
                        logger.warning(
                            "fleet: worker %s %s → %s: %s", wname,
                            prev, w.health.state, why)
        # due retries / parked dispatches
        with self._lock:
            # a live attempt stuck on a slow worker must still honor
            # the caller's deadline — fail the fleet request now (the
            # stale attempt, whenever it surfaces, finds it done)
            for entry in self._live:
                freq = entry[0]
                if not freq.done() and freq.deadline is not None \
                        and now > freq.deadline:
                    if freq._fail(RequestTimeout(
                            "serving: deadline expired with the "
                            "attempt still in flight"), now):
                        self.stats.record_timeout()
                        freq._notify_done()
            pending, self._pending = self._pending, []
            due_by_class: Dict[str, deque] = {}
            for p in pending:
                if p.freq.done():
                    continue
                if p.freq.deadline is not None and \
                        now > p.freq.deadline:
                    if p.freq._fail(RequestTimeout(
                            "serving: deadline expired while waiting "
                            "for a fleet worker"), now):
                        self.stats.record_timeout()
                        p.freq._notify_done()
                    continue
                if p.due > now:
                    self._pending.append(p)
                else:
                    due_by_class.setdefault(
                        p.freq.priority, deque()).append(p)
            # weighted round-robin over the due backlog: classes
            # interleave by weight (FIFO within a class), so a hot
            # tenant cannot starve the others
            while due_by_class:
                cname = self._wrr_next_locked(due_by_class)
                q = due_by_class[cname]
                p = q.popleft()
                if not q:
                    del due_by_class[cname]
                if not self._dispatch_locked(p.freq, now):
                    self._park_locked(p.freq, now, p.due)
            # hedging: a slow single IN-FLIGHT attempt gets a second
            # chance on another worker; first completion wins.  An
            # entry whose attempt already finished (either way) is out
            # of hedging scope — retries own that path.
            self._live = [e for e in self._live
                          if not e[0].done() and not e[1].done()]
            if self._hedge_after_us > 0:
                for freq, attempt, wname, t0, hedge in list(self._live):
                    if hedge or freq.hedges > 0:
                        continue
                    if isinstance(freq, FleetGenerateRequest):
                        # never hedge a stream: two lanes decoding the
                        # same rollout would double-emit tokens
                        continue
                    if (now - t0) * 1e6 >= self._hedge_after_us:
                        if self._dispatch_locked(freq, now,
                                                 hedge=True):
                            self.stats.bump("hedges")
            dump_terminal, self._dump_terminal = \
                self._dump_terminal, False
        if dump_terminal and obs.dump_on_error_path() is not None:
            obs.dump_all(reason="fleet request failed terminally",
                         path=obs.dump_on_error_path() or None)
        # control-plane hooks (autoscaler etc.) run LAST, with no
        # router lock held — they may call add_worker/drain freely
        with self._lock:
            controllers = list(self._controllers)
        for fn in controllers:
            try:
                fn(now)
            except Exception:   # noqa: BLE001 — a broken controller
                logger.exception("fleet: controller failed")  # ≠ outage
        self.stats.maybe_log()

    def _tick_loop(self) -> None:
        while not self._stop.wait(self._tick_s):
            try:
                self.tick()
            except Exception:   # noqa: BLE001 — the ticker must never
                logger.exception("fleet: tick failed")  # die silently

    # -- observability -----------------------------------------------------
    def fleet_stats(self) -> Dict[str, Any]:
        """Fleet-level aggregation: router counters (retries,
        requeues, hedges won, drains, deaths + rolling end-to-end
        percentiles) plus one per-worker block (state machine snapshot
        + that worker's ServingStats)."""
        snap = self.stats.snapshot()
        with self._lock:
            workers = dict(self._workers)
            snap["pending"] = len(self._pending)
        with self._class_lock:
            class_n = dict(self._class_n)
        snap["classes"] = {
            n: {"weight": c.weight, "quota": c.quota,
                "in_system": class_n.get(n, 0)}
            for n, c in self._classes.items()}
        snap["workers"] = {
            n: {**w.health.snapshot(), **w.stats.snapshot()}
            for n, w in workers.items()}
        states = [w.health.state for w in workers.values()]
        snap["healthy_workers"] = sum(
            1 for s in states if s == WorkerState.HEALTHY)
        snap["total_workers"] = len(states)
        with self._lock:
            slo = self._slo
        if slo is not None:
            snap["slo"] = slo.snapshot()
        return snap

    def postmortem(self, name: str) -> Dict[str, Any]:
        """Everything known about one worker, dead or alive: health
        state machine snapshot + full transition log, serving stats,
        and the flight-recorder ring (health transitions, canary
        verdicts, faults, evictions) — the single dict an operator
        reads after ``kill``/death to answer *why*."""
        with self._lock:
            w = self._require_locked(name)
            slo = self._slo
        doc = {
            "worker": name,
            "health": w.health.snapshot(),
            "transitions": list(w.health.transitions),
            "stats": w.stats.snapshot(),
            "flight": w.recorder.snapshot(),
        }
        if slo is not None:
            # the SLO/error-budget table at the moment of the
            # postmortem — which alerts were firing while this worker
            # was dying answers the operator's "did users notice?"
            doc["slo"] = slo.snapshot()
        return doc

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            workers = list(self._workers.values())
            pending = self._pending
            self._pending = []
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=2.0)
        now = self._clock()
        for p in pending:
            if p.freq._fail(WorkerLost(
                    "serving: fleet router closed"), now):
                p.freq._notify_done()
        for w in workers:
            w.shutdown()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
