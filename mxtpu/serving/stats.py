"""Serving observability — rolling latency percentiles, queue depth,
batch fill-rate and request rate, exposed three ways (ISSUE 4 tentpole
item 4): a ``stats()`` snapshot dict, a Speedometer-style periodic log
line (SURVEY.md §5.5 — the reference's ``mx.callback.Speedometer``
printed samples/sec every N batches; here req/sec + percentiles every
``log_every_s`` seconds of traffic), and chrome-trace spans emitted
through :func:`mxtpu.profiler.record_span` by the server worker so
serving batches show up next to training ops in trace dumps.

Everything is O(1) per event under one lock: percentiles come from a
bounded ring of recent latencies (default 2048 — at serving rates this
is seconds of traffic, enough for a rolling p99 without unbounded
growth), rates from a deque of completion timestamps.

ISSUE 8: every instance also publishes to the ``mxtpu.obs`` metrics
registry (counters/gauges/histograms labeled ``endpoint=<name>``, the
fleet ``bump()`` counters as ``mxtpu_fleet_events_total{kind=...}``) —
the process-wide Prometheus/JSON surface.  With ``MXTPU_OBS=0`` the
wiring is a cached-bool branch (guards-style zero overhead); the local
snapshot()/log-line behaviour is identical either way.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from .. import obs

__all__ = ["ServingStats"]

logger = logging.getLogger("mxtpu.serving")

# queue_eta_us sorts at most this many recent service-time samples —
# bounds the admission-path cost independently of the stats window
_ETA_SAMPLE = 256


# one quantile implementation for the whole tree (ISSUE 14 satellite):
# the nearest-rank math lives in obs.metrics next to bucket_quantile
_percentile = obs.percentile


class ServingStats:
    """Per-endpoint rolling counters.  One instance per registered
    (model, version); the server updates it from its worker threads,
    ``snapshot()`` is safe from any thread."""

    def __init__(self, name: str = "", window: int = 2048,
                 rate_window_s: float = 30.0,
                 log_every_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._lock = threading.Lock()
        self._clock = clock
        self._lat_us = deque(maxlen=window)  # guarded-by: _lock
        self._queue_us = deque(maxlen=window)  # guarded-by: _lock
        self._done_ts = deque()  # guarded-by: _lock
        # generation rings (ISSUE 19): time-to-first-token and
        # per-token decode latency
        self._ttft_us = deque(maxlen=window)  # guarded-by: _lock
        self._tok_us = deque(maxlen=window)  # guarded-by: _lock
        self.tokens_emitted = 0  # guarded-by: _lock
        self._rate_window_s = rate_window_s
        self._log_every_s = log_every_s
        self._last_log = clock()  # guarded-by: _lock
        # monotonically increasing totals
        self.completed = 0  # guarded-by: _lock
        self.timed_out = 0  # guarded-by: _lock
        self.rejected = 0  # guarded-by: _lock
        self.batches = 0  # guarded-by: _lock
        self.padded_slots = 0  # guarded-by: _lock
        self.batched_requests = 0  # guarded-by: _lock
        self.queue_depth = 0  # guarded-by: _lock
        self.peak_queue_depth = 0  # guarded-by: _lock
        # open-ended fleet counters (retries, requeues, hedges_won,
        # drains, deaths, ...) — bump() increments, snapshot() exposes
        # them under "extras", maybe_log() appends the nonzero ones to
        # the Speedometer line (extended, not duplicated)
        self.extras: Dict[str, int] = {}  # guarded-by: _lock
        # mxtpu.obs registry wiring — one labeled child per instrument,
        # resolved once here; _obs gates the hot paths (cached bool)
        self._obs = obs.enabled()
        ep = name or "default"
        self._m_completed = obs.counter(
            "mxtpu_serving_completed_total",
            "Requests completed per endpoint.",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_timeout = obs.counter(
            "mxtpu_serving_timeout_total",
            "Requests failed on an expired deadline.",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_rejected = obs.counter(
            "mxtpu_serving_rejected_total",
            "Requests shed at the edge (ServerBusy).",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_batches = obs.counter(
            "mxtpu_serving_batches_total",
            "Micro-batches executed.",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_batched = obs.counter(
            "mxtpu_serving_batched_requests_total",
            "Real (non-padding) examples across executed batches.",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_padded = obs.counter(
            "mxtpu_serving_padded_slots_total",
            "Padding slots across executed batches.",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_depth = obs.gauge(
            "mxtpu_serving_queue_depth",
            "Current batcher queue depth.",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_latency = obs.histogram(
            "mxtpu_serving_latency_seconds",
            "End-to-end request latency.",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_queue_wait = obs.histogram(
            "mxtpu_serving_queue_wait_seconds",
            "Submit-to-dequeue wait.",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_ttft = obs.histogram(
            "mxtpu_serving_ttft_seconds",
            "Submit-to-first-token latency of generation requests "
            "(LatencySLO metric= target).",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_token = obs.histogram(
            "mxtpu_serving_token_seconds",
            "Per-token decode-step latency (LatencySLO metric= "
            "target).",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_tokens = obs.counter(
            "mxtpu_serving_tokens_total",
            "Tokens emitted by generation endpoints.",
            labels=("endpoint",)).labels(endpoint=ep)
        self._m_fleet = obs.counter(
            "mxtpu_fleet_events_total",
            "Fleet counters (the ServingStats.bump keys: retries, "
            "requeues, hedges, drains, deaths, ...).",
            labels=("endpoint", "kind"))

    # -- event hooks (called by batcher/server) -------------------------
    def record_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth
            if depth > self.peak_queue_depth:
                self.peak_queue_depth = depth
        if self._obs:
            self._m_depth.set(depth)

    def record_rejected(self, n: int = 1) -> None:
        with self._lock:
            self.rejected += n
        if self._obs:
            self._m_rejected.inc(n)

    def record_timeout(self, n: int = 1) -> None:
        with self._lock:
            self.timed_out += n
        if self._obs:
            self._m_timeout.inc(n)

    def bump(self, key: str, n: int = 1) -> None:
        """Increment a named fleet counter (``retries``, ``requeues``,
        ``hedges_won``, ``drains``, ``deaths``, ...)."""
        with self._lock:
            self.extras[key] = self.extras.get(key, 0) + n
        if self._obs:
            self._m_fleet.labels(endpoint=self.name or "default",
                                 kind=key).inc(n)

    def record_batch(self, n_real: int, capacity: int) -> None:
        with self._lock:
            self.batches += 1
            self.batched_requests += n_real
            self.padded_slots += max(0, capacity - n_real)
        if self._obs:
            self._m_batches.inc()
            self._m_batched.inc(n_real)
            self._m_padded.inc(max(0, capacity - n_real))

    def record_completion(self, latency_us: float,
                          queue_us: float = 0.0) -> None:
        now = self._clock()
        with self._lock:
            self.completed += 1
            self._lat_us.append(latency_us)
            self._queue_us.append(queue_us)
            self._done_ts.append(now)
            horizon = now - self._rate_window_s
            while self._done_ts and self._done_ts[0] < horizon:
                self._done_ts.popleft()
        if self._obs:
            self._m_completed.inc()
            self._m_latency.observe(latency_us / 1e6)
            self._m_queue_wait.observe(queue_us / 1e6)

    def record_ttft(self, ttft_us: float) -> None:
        """Time-to-first-token of one generation request."""
        with self._lock:
            self._ttft_us.append(ttft_us)
        if self._obs:
            self._m_ttft.observe(ttft_us / 1e6)

    def record_token(self, tok_us: float, n: int = 1) -> None:
        """One (or ``n`` same-latency) emitted decode tokens."""
        with self._lock:
            self._tok_us.append(tok_us)
            self.tokens_emitted += n
        if self._obs:
            self._m_token.observe(tok_us / 1e6)
            self._m_tokens.inc(n)

    # -- views ----------------------------------------------------------
    def token_eta_us(self, n_tokens: float,
                     percentile: float = 95.0) -> Optional[float]:
        """Predicted decode time for ``n_tokens`` more tokens at this
        endpoint's observed per-token service rate — the generation
        term of per-token-aware admission control (ISSUE 19): a
        generation request's feasibility is queue ETA *plus* this.
        None until a token has been emitted (cold: no prediction)."""
        with self._lock:
            if not self._tok_us:
                return None
            toks = sorted(list(self._tok_us)[-_ETA_SAMPLE:])
        return _percentile(toks, percentile) * max(0.0,
                                                   float(n_tokens))

    def queue_eta_us(self, depth: Optional[float] = None,
                     percentile: float = 95.0) -> Optional[float]:
        """Predicted wait for a request entering this endpoint's queue
        now: histogram-derived per-batch service time × queued batches
        ahead (depth / mean batch fill), plus the request's own batch.
        This is the admission-control signal (ISSUE 11): unlike raw
        queue length it is deadline-comparable, so a doomed request
        can be shed at submit time.

        ``depth`` overrides the live queue depth (the fleet router
        passes its own class-aware backlog); ``percentile`` picks the
        service-time rank (p95 default — admission should be
        pessimistic about stragglers).  Returns ``None`` until at
        least one batch has completed (a cold endpoint has no
        histogram — callers treat that as "no prediction", not zero).
        """
        with self._lock:
            if not self._lat_us or not self.batches:
                return None
            # service time = end-to-end latency minus queue wait, per
            # completed request; recent window keeps the sort cheap on
            # the admission path
            serv = sorted(
                max(0.0, l - q) for l, q in
                zip(list(self._lat_us)[-_ETA_SAMPLE:],
                    list(self._queue_us)[-_ETA_SAMPLE:]))
            s = _percentile(serv, percentile)
            fill = max(1.0, self.batched_requests / self.batches)
            d = float(self.queue_depth) if depth is None \
                else max(0.0, float(depth))
            return s * (1.0 + d / fill)

    def requests_per_sec(self) -> float:
        with self._lock:
            return self._rps_locked(self._clock())

    def _rps_locked(self, now: float) -> float:
        # Prune on the read path too (ISSUE 8 satellite): after an
        # idle period the ring otherwise still holds — and counts —
        # completions far outside the rate window.
        horizon = now - self._rate_window_s
        while self._done_ts and self._done_ts[0] < horizon:
            self._done_ts.popleft()
        if not self._done_ts:
            return 0.0
        span = max(now - self._done_ts[0], 1e-6)
        return len(self._done_ts) / span

    def snapshot(self) -> Dict:
        """One coherent stats dict (the ``stats()`` surface of the
        serving layer)."""
        with self._lock:
            lat = sorted(self._lat_us)
            queued = sorted(self._queue_us)
            ttft = sorted(self._ttft_us)
            toks = sorted(self._tok_us)
            cap = self.batched_requests + self.padded_slots
            gen = {}
            if ttft or toks:
                gen = {"generate": {
                    "tokens_emitted": self.tokens_emitted,
                    "ttft_ms": {
                        "p50": round(_percentile(ttft, 50) / 1e3, 3),
                        "p95": round(_percentile(ttft, 95) / 1e3, 3),
                        "n": len(ttft)},
                    "token_ms": {
                        "p50": round(_percentile(toks, 50) / 1e3, 3),
                        "p95": round(_percentile(toks, 95) / 1e3, 3),
                        "n": len(toks)},
                }}
            return {
                **gen,
                "completed": self.completed,
                "timed_out": self.timed_out,
                "rejected": self.rejected,
                "batches": self.batches,
                "requests_per_sec": round(
                    self._rps_locked(self._clock()), 2),
                "latency_ms": {
                    "p50": round(_percentile(lat, 50) / 1e3, 3),
                    "p95": round(_percentile(lat, 95) / 1e3, 3),
                    "p99": round(_percentile(lat, 99) / 1e3, 3),
                    "n": len(lat),
                },
                "queue_ms": {
                    "p50": round(_percentile(queued, 50) / 1e3, 3),
                    "p99": round(_percentile(queued, 99) / 1e3, 3),
                },
                "batch_fill_rate": round(
                    self.batched_requests / cap, 4) if cap else None,
                "mean_batch_size": round(
                    self.batched_requests / self.batches, 2)
                if self.batches else None,
                "queue_depth": self.queue_depth,
                "peak_queue_depth": self.peak_queue_depth,
                "extras": dict(self.extras),
            }

    def maybe_log(self) -> Optional[str]:
        """Speedometer-style throttled log line — call after each batch;
        emits at most once per ``log_every_s``.  Returns the line when
        one was emitted (tests hook this)."""
        now = self._clock()
        with self._lock:
            if now - self._last_log < self._log_every_s:
                return None
            self._last_log = now
            lat = sorted(self._lat_us)
            cap = self.batched_requests + self.padded_slots
            line = (f"Serving [{self.name}] "
                    f"{self._rps_locked(now):.1f} req/sec\t"
                    f"p50={_percentile(lat, 50) / 1e3:.2f}ms "
                    f"p95={_percentile(lat, 95) / 1e3:.2f}ms "
                    f"p99={_percentile(lat, 99) / 1e3:.2f}ms\t"
                    f"fill={self.batched_requests / cap if cap else 0.0:.2f} "
                    f"queue={self.queue_depth} "
                    f"(peak {self.peak_queue_depth}) "
                    f"timeout={self.timed_out} busy={self.rejected}")
            extras = " ".join(f"{k}={v}" for k, v in
                              sorted(self.extras.items()) if v)
            if extras:
                line += " " + extras
        logger.info(line)
        return line
