# mxlint: hot-path
"""ModelRunner — AOT-compiled bucketed inference executors sharing one
weight upload (ISSUE 4 tentpole item 1).

The TPU-native analog of the reference's C predict API over per-bucket
shared-weight executors (``MXPredReshape``† / ``BucketingModule``†,
SURVEY.md §3): a deployed model (``Module.save_checkpoint`` / gluon
``export`` artifacts, parsed through the same ``c_predict`` binding
path) is compiled ONCE PER SHAPE BUCKET — a powers-of-two batch ladder
crossed with optional sequence-length buckets for token models — into
XLA executables via ``jax.jit(..).lower(..).compile()``.  Weights are
uploaded to the device once and the SAME committed buffers feed every
bucket executable (the ``MXPredReshape`` zero-copy contract, asserted
by test); input buffers are donated on accelerator backends so the
padded batch staging buffer is recycled into the executable's
workspace.

Why buckets instead of dynamic shapes: XLA compiles static shapes.  A
pow2 batch ladder caps the number of programs at log2(max_batch) per
sequence bucket while bounding padding waste at <2x in the worst case
and ~1.3x expected under uniform fill — the same trade the reference's
``BucketingModule`` made for variable-length RNNs.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from .. import guards
from .. import knobs
from .. import obs
from .. import profiler
from .batcher import InferenceRequest

__all__ = ["ModelRunner", "batch_ladder"]


def batch_ladder(max_batch_size: int) -> Tuple[int, ...]:
    """Powers-of-two ladder 1,2,4,… capped at ``max_batch_size`` (the
    cap itself is always a rung so full batches never pad)."""
    if max_batch_size < 1:
        raise MXNetError("max_batch_size must be >= 1")
    rungs = []
    b = 1
    while b < max_batch_size:
        rungs.append(b)
        b *= 2
    rungs.append(max_batch_size)
    return tuple(rungs)


class ModelRunner:
    """Load-once, compile-per-bucket, run-many inference engine.

    Parameters
    ----------
    symbol : mxtpu.symbol.Symbol
        The inference graph (deployment artifact).
    params : dict name -> numpy/NDArray
        Trained weights (``arg:``/``aux:`` prefixes already stripped).
    input_specs : dict name -> per-example shape tuple
        Shapes EXCLUDE the batch axis.  A ``None`` entry marks the
        variable (sequence) axis of a token model and requires
        ``seq_buckets``; e.g. ``{"data": (None,)}`` for token ids.
    input_dtypes : dict name -> dtype, optional (default float32)
    seq_buckets : ascending ints, optional
        Sequence-length rungs for every ``None`` axis.
    max_batch_size : int, optional (env MXTPU_SERVING_MAX_BATCH, 32)
    device : jax device, optional — one runner binds ONE device; build
        one runner per replica for data-parallel serving and let
        ``InferenceServer`` round-robin across them.
    pad_value : scalar used for sequence padding (default 0).
    cache : "auto" | None | mxtpu.cache.ExecutableCache
        The persistent executable cache (ISSUE 13).  "auto" (default)
        uses the knob-configured process cache (inert unless
        ``MXTPU_CACHE_DIR`` is set); None opts this runner out; an
        explicit :class:`~mxtpu.cache.ExecutableCache` pins one (fleet
        tests share a tmpdir cache this way).  Every bucket compile
        becomes load-or-compile: a verified disk hit skips tracing AND
        compilation, a miss compiles and serializes for the next
        process.
    """

    def __init__(self, symbol, params: Dict[str, Any],
                 input_specs: Dict[str, Tuple],
                 input_dtypes: Optional[Dict[str, Any]] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 max_batch_size: Optional[int] = None,
                 device=None, pad_value: float = 0,
                 donate: Optional[bool] = None, cache: Any = "auto",
                 amp=None, quant=None):
        import jax

        # policy-driven AMP (mxtpu.amp): weights upload bf16 (half the
        # serving HBM), re-enter the graph in f32, and only the
        # policy's allow-listed contractions compute in bf16.
        # MXTPU_AMP=0 kills it; off-path programs are bit-identical.
        from .. import amp as _amp_mod
        self._amp = _amp_mod.resolve(amp)
        # policy-driven INT8 quantization (mxtpu.quant): after a
        # calibrate() pass records activation thresholds, every
        # bucket compiles with the policy's allow-listed contractions
        # as s8xs8 GEMMs accumulating in i32.  MXTPU_QUANT=0 kills
        # it; off-path programs are bit-identical.
        from .. import quant as _quant_mod
        self._quant = _quant_mod.resolve(quant)
        self._quant_scales: Optional[Dict[str, float]] = None
        self._symbol = symbol
        self._input_names = list(input_specs)
        self._input_specs = {k: tuple(v) for k, v in input_specs.items()}
        self._input_dtypes = {
            k: np.dtype((input_dtypes or {}).get(k, np.float32))
            for k in input_specs}
        # Serving knobs (mxtpu/knobs.py, README "Serving"): the env
        # defaults feed every runner that does not pass explicit
        # values, so a deployment can be retuned without code changes.
        self.max_batch_size = int(
            max_batch_size if max_batch_size is not None
            else knobs.get("MXTPU_SERVING_MAX_BATCH"))
        self.batch_buckets = batch_ladder(self.max_batch_size)
        self.seq_buckets = tuple(sorted(int(s) for s in seq_buckets)) \
            if seq_buckets else None
        has_var = any(None in spec for spec in self._input_specs.values())
        if has_var and not self.seq_buckets:
            raise MXNetError(
                "serving: input_specs contain a variable (None) axis — "
                "pass seq_buckets")
        self._pad_value = pad_value
        self._device = device if device is not None else jax.devices()[0]
        if donate is None:
            donate = knobs.get("MXTPU_SERVING_DONATE")
        # _donate records the INTENT (what mxmem's donation-missed
        # rule audits); the CPU backend, where XLA drops donation,
        # is gated at the jit site in _entry so compiled programs
        # stay byte-identical there.
        self._donate = bool(donate)  # mxlint: disable=host-sync

        # -- one weight upload, shared by every bucket executable ------
        known = set(symbol.list_inputs())
        self._param_names = tuple(
            n for n in params if n in known and n not in input_specs)
        missing = known - set(self._param_names) - set(input_specs)
        if missing:
            raise MXNetError(
                f"serving: graph inputs {sorted(missing)} have neither "
                f"a param nor an input_spec")
        if self._amp:
            # bf16 weight storage: aux-named params (BN running
            # stats) stay f32 — their EMA magnitudes need the
            # mantissa; everything else halves its upload + HBM
            import jax.numpy as jnp
            from ..symbol import _is_aux_name

            def _stage(n):
                v = self._as_np(params[n])
                if v.dtype == np.float32 and not _is_aux_name(n):
                    v = v.astype(jnp.bfloat16)
                return jax.device_put(v, self._device)

            self._param_vals = tuple(_stage(n)
                                     for n in self._param_names)
        else:
            self._param_vals = tuple(
                jax.device_put(self._as_np(params[n]), self._device)
                for n in self._param_names)
        # lowering must pin THIS replica's device, or every runner
        # would compile (and expect buffers) on jax.devices()[0]
        self._sharding = jax.sharding.SingleDeviceSharding(self._device)
        self._param_structs = tuple(
            jax.ShapeDtypeStruct(v.shape, v.dtype,
                                 sharding=self._sharding)
            for v in self._param_vals)

        # _Endpoint worker threads race through _entry()/warmup() when
        # a server front-loads compiles while requests stream in; the
        # compile cache and its timing ledger are lock-protected so a
        # bucket is compiled exactly once.
        self._lock = threading.Lock()
        self._entries: Dict[Tuple, Any] = {}  # guarded-by: _lock
        self.compile_seconds: Dict[Tuple, float] = {}  # guarded-by: _lock
        self._guards = guards.enabled()
        # One compile per ladder rung is the design; anything past the
        # ladder (+ slack for explicit extra warmup buckets) is churn.
        self._entry_label = f"ModelRunner[{type(symbol).__name__}]"
        self._churn = guards.ChurnDetector(
            self._entry_label, limit=len(self.buckets()) + 4)
        # mxtpu.obs wiring (cached bool; no-op singletons when off):
        # compile events feed the registry AND the "compile" flight
        # recorder so a postmortem shows every cache miss with timing.
        self._obs = obs.enabled()
        self._m_compile = obs.counter(
            "mxtpu_serving_compile_total",
            "Bucket executables actually compiled by XLA (cold "
            "builds only — disk-cache hits count in "
            "mxtpu_compile_cache_hit_total instead).",
            labels=("entry",)).labels(entry=self._entry_label)
        # source=cold|disk makes the cold-vs-warm split machine-
        # readable (ISSUE 13 satellite): "cold" paid XLA, "disk"
        # paid a verified deserialize off the persistent cache.
        _h = obs.histogram(
            "mxtpu_serving_compile_seconds",
            "Per-bucket entry build wall time (source=cold: XLA "
            "compile; source=disk: verified load from the persistent "
            "cache).", labels=("entry", "source"))
        self._m_compile_s = {
            src: _h.labels(entry=self._entry_label, source=src)
            for src in ("cold", "disk")}
        # the disk-hit counter next to ChurnDetector's
        # mxtpu_compile_cache_miss_total: of the in-process misses,
        # how many the persistent cache absorbed.
        self._m_cache_hit = obs.counter(
            "mxtpu_compile_cache_hit_total",
            "In-process compile-cache misses served from the "
            "persistent disk cache instead of XLA.",
            labels=("entry",)).labels(entry=self._entry_label)

        # ISSUE 13: the persistent executable cache + this runner's
        # model fingerprint (what was compiled: graph, input/param
        # signatures, donation — weights are runtime inputs, so one
        # entry serves every checkpoint of the same architecture).
        from .. import cache as cache_mod
        self._cache = cache_mod.default_cache() if cache == "auto" \
            else cache
        self._fingerprint = ""
        if self._cache is not None:
            self._fingerprint = self._model_fingerprint()

    @staticmethod
    def _as_np(v):
        # mxlint: sync-point — host-side param ingest, pre-upload
        return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)

    # -- deployment-artifact constructors -------------------------------
    @classmethod
    def from_export(cls, symbol_file: str, params_file: str, **kwargs
                    ) -> "ModelRunner":
        """Load gluon ``HybridBlock.export`` / ``Module.save_checkpoint``
        artifacts (``-symbol.json`` + ``-NNNN.params``), parsing the
        params blob through the c_predict binding path."""
        from .. import symbol as sym_mod
        from ..c_predict import _params_from_bytes
        with open(symbol_file) as f:
            symbol = sym_mod.load_json(f.read())
        with open(params_file, "rb") as f:
            params = _params_from_bytes(f.read())
        return cls(symbol, params, **kwargs)

    @classmethod
    def from_checkpoint(cls, prefix: str, epoch: int, **kwargs
                        ) -> "ModelRunner":
        """``prefix-symbol.json`` + ``prefix-{epoch:04d}.params``."""
        return cls.from_export(f"{prefix}-symbol.json",
                               f"{prefix}-{epoch:04d}.params", **kwargs)

    # -- buckets ---------------------------------------------------------
    def bucket_for(self, n: int, seq_len: Optional[int] = None) -> Tuple:
        """Smallest (batch_bucket, seq_bucket) ladder rung covering a
        batch of ``n`` examples of length ``seq_len``."""
        if n < 1:
            raise MXNetError("serving: empty batch")
        if n > self.max_batch_size:
            raise MXNetError(
                f"serving: batch {n} exceeds max_batch_size "
                f"{self.max_batch_size}")
        b = next(r for r in self.batch_buckets if r >= n)
        if self.seq_buckets is None:
            return (b, None)
        if seq_len is None:
            raise MXNetError("serving: token model needs seq_len")
        if seq_len > self.seq_buckets[-1]:
            raise MXNetError(
                f"serving: seq_len {seq_len} exceeds largest bucket "
                f"{self.seq_buckets[-1]}")
        s = next(r for r in self.seq_buckets if r >= seq_len)
        return (b, s)

    def seq_bucket_for(self, seq_len: Optional[int]) -> Optional[int]:
        """The batcher's grouping key: requests sharing a seq bucket
        may batch together; batch-size bucketing happens at dispatch."""
        if self.seq_buckets is None:
            return None
        return self.bucket_for(1, seq_len)[1]

    def buckets(self) -> List[Tuple]:
        """The full ladder (what ``warmup()`` compiles)."""
        seqs = self.seq_buckets or (None,)
        return [(b, s) for s in seqs for b in self.batch_buckets]

    def _concrete_shape(self, name: str, batch: int,
                        seq: Optional[int]) -> Tuple[int, ...]:
        return (batch,) + tuple(seq if d is None else int(d)
                                for d in self._input_specs[name])

    # -- persistent cache keys (ISSUE 13) --------------------------------
    def _model_fingerprint(self) -> str:
        """sha256 over everything that shapes the compiled program
        EXCEPT the bucket: graph json, input specs/dtypes, param
        signatures, donation, pad semantics.  Weight VALUES are
        excluded on purpose — they are runtime arguments, so the same
        entry warms every checkpoint of this architecture."""
        import hashlib
        import json as _json
        # canonicalize gensym'd op-node names ("broadcast_mul7" — a
        # process-global counter) so two independently constructed
        # copies of the same graph fingerprint identically; edges and
        # heads are index-based, so op names are cosmetic.  Input
        # ("null") nodes keep their real names — they ARE semantics.
        graph = _json.loads(self._symbol.tojson())
        for i, node in enumerate(graph.get("nodes", ())):
            if node.get("op") not in (None, "null"):
                node["name"] = f"_op{i}"
        fp = {
            "symbol": graph,
            "inputs": {n: [list(self._input_specs[n]),
                           str(self._input_dtypes[n])]
                       for n in self._input_names},
            "params": [[n, list(v.shape), str(v.dtype)]
                       for n, v in zip(self._param_names,
                                       self._param_vals)],
            "donate": self._donate, "pad_value": self._pad_value,
        }
        if self._amp:
            # key only when ON: every pre-AMP cache entry (and the
            # MXTPU_AMP=0 path) keeps its fingerprint unchanged
            fp["amp"] = True
        if self._quant:
            # the calibrated thresholds are trace-baked constants, so
            # they ARE part of what was compiled — recalibration must
            # miss.  Keyed only when ON (same rule as amp).
            fp["quant"] = sorted(
                (self._quant_scales or {}).items()) or True
        blob = _json.dumps(fp, sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:24]

    def _cache_key(self, bucket: Tuple):
        """The persistent-cache key of one bucket executable: model
        fingerprint x concrete bucket shape x single-device topology
        (+ the environment components ExecutableCache.key adds — jax
        version, backend, contract hash, salt)."""
        batch, seq = bucket
        shapes = {n: list(self._concrete_shape(n, batch, seq))
                  for n in self._input_names}
        extra = {}
        if self._quant:
            # explicit `quant` key component on top of the fingerprint
            # scales: a quantized executable can NEVER be loaded by an
            # unquantized runner, or vice versa (tests/test_cache.py)
            extra["quant"] = "int8"
        return self._cache.key(
            model=self._fingerprint, shape=str(sorted(shapes.items())),
            mesh="1dev", device=getattr(self._device, "device_kind",
                                        "unknown"), **extra)

    def cached_buckets(self) -> List[Tuple]:
        """The subset of this runner's ladder present in the
        persistent cache right now (existence probe only; loads are
        verified later) — what the fleet consults before deciding a
        donor-less replacement can warm from disk."""
        if self._cache is None:
            return []
        return [b for b in self.buckets()
                if self._cache.contains(self._cache_key(b))]

    def warm_from_disk(self) -> Dict[Tuple, float]:
        """Warm every ladder bucket the persistent cache holds (a
        poisoned/stale entry quarantines and recompiles inside
        ``_entry`` — still off the data path).  Returns per-bucket
        build seconds; empty dict when there is no cache or no
        entries."""
        hits = self.cached_buckets()
        if not hits:
            return {}
        return self.warmup(hits)

    # -- INT8 calibration (mxtpu.quant, ISSUE 18) -------------------------
    def calibrate(self, batches: Sequence[Dict[str, Any]],
                  mode: Optional[str] = None,
                  num_batches: Optional[int] = None,
                  collector=None) -> Dict[str, float]:
        """Post-training calibration: run representative ``batches``
        (dicts of batched host arrays, one per input) EAGERLY through
        the deployed graph, observing every candidate contraction's
        activations with the chosen collector (``mode``: minmax |
        entropy; default the MXTPU_QUANT_CALIB knob).  The resulting
        per-tensor |x| thresholds arm the quantized trace path of
        every subsequent bucket compile, and re-fingerprint the
        persistent-cache identity (thresholds are trace-baked
        constants).  Deterministic given fixed batches — byte-equal
        threshold tables across runs.  Must run before warmup()."""
        import jax.numpy as jnp
        from .. import autograd
        from .. import quant as _quant_mod
        from ..ndarray.ndarray import NDArray
        from ..symbol import _eval_symbol
        if not self._quant:
            raise MXNetError(
                "serving: calibrate() on a non-quantized runner — "
                "pass quant=True (or MXTPU_QUANT=1), and note "
                "MXTPU_QUANT=0 overrides both")
        with self._lock:
            if self._entries:
                raise MXNetError(
                    "serving: calibrate() after buckets compiled — "
                    "calibration changes every program; calibrate "
                    "before warmup()")
        if num_batches is None:
            _, num_batches = _quant_mod.calib_config()
        if collector is None:
            collector = _quant_mod.make_collector(mode)
        # params enter in f32 exactly as _pure_fn re-enters them, so
        # the observed activations match the traced graph's
        param_nd = {
            n: NDArray(v.astype(jnp.float32)
                       if (jnp.issubdtype(v.dtype, jnp.floating)
                           and v.dtype != jnp.float32) else v,
                       None, _placed=True)
            for n, v in zip(self._param_names, self._param_vals)}
        prev_rec = autograd.set_recording(False)
        prev_train = autograd.set_training(False)
        try:
            for i, batch in enumerate(batches):
                if i >= num_batches:
                    break
                bindings = dict(param_nd)
                for n in self._input_names:
                    # mxlint: sync-point — host batch staging, offline
                    arr = np.asarray(batch[n], self._input_dtypes[n])
                    bindings[n] = NDArray(arr, None)
                with _quant_mod.calibrating(collector):
                    _eval_symbol(self._symbol, bindings)
        finally:
            autograd.set_training(prev_train)
            autograd.set_recording(prev_rec)
        self._quant_scales = collector.thresholds()  # mxrace: disable=unguarded-attr (pre-serving setup: calibrate raises once any bucket compiled, so no concurrent reader exists yet and the table is immutable afterwards)
        if not self._quant_scales:
            raise MXNetError(
                "serving: calibration observed no quantizable "
                "contraction — the graph has no FullyConnected/"
                "Convolution on f32 inputs")
        if self._cache is not None:
            self._fingerprint = self._model_fingerprint()  # mxrace: disable=unguarded-attr (same setup phase: re-fingerprint before any compile/serve thread can read it)
        return dict(self._quant_scales)

    def quant_scales(self) -> Optional[Dict[str, float]]:
        """The calibrated activation-threshold table (None before
        :meth:`calibrate`)."""
        return dict(self._quant_scales) \
            if self._quant_scales is not None else None

    # -- AOT compile ------------------------------------------------------
    def _pure_fn(self):
        """Pure (traceable) interpretation of the symbol: (input_vals,
        param_vals) -> tuple of raw outputs, inference mode (no
        recording, training=False — dropout is identity)."""
        import contextlib
        import jax.numpy as jnp
        from .. import amp as _amp_mod
        from .. import autograd
        from .. import quant as _quant_mod
        from ..ndarray.ndarray import NDArray
        from ..symbol import _eval_symbol
        sym = self._symbol
        in_names = tuple(self._input_names)
        p_names = self._param_names
        amp_on = self._amp
        quant_on = self._quant
        if quant_on and self._quant_scales is None:
            raise MXNetError(
                "serving: quantized runner has no calibrated scales — "
                "run calibrate(batches) before compiling buckets")
        quant_scales = self._quant_scales

        def fn(input_vals, param_vals):
            if amp_on:
                # AMP entry upcast (the TrainStep rule): bf16 weights
                # re-enter the graph in f32 so only the policy's
                # allow-listed contractions — cast back down inside
                # the autocast scope — ever compute in bf16; XLA
                # folds the convert pair at the weight→dot edges
                param_vals = tuple(
                    v.astype(jnp.float32)
                    if (jnp.issubdtype(v.dtype, jnp.floating)
                        and v.dtype != jnp.float32)
                    else v for v in param_vals)
            bindings = {}
            for n, v in zip(in_names, input_vals):
                bindings[n] = NDArray(v, None, _placed=True)
            for n, v in zip(p_names, param_vals):
                bindings[n] = NDArray(v, None, _placed=True)
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(False)
            # scope nesting: quant outermost — a contraction with a
            # recorded scale becomes an int8 GEMM; anything it leaves
            # on the float path still gets amp's bf16 cast when both
            # passes are on
            scope = contextlib.ExitStack()
            if quant_on:
                scope.enter_context(_quant_mod.quantize(quant_scales))
            if amp_on:
                scope.enter_context(_amp_mod.autocast())
            try:
                with scope:
                    outs = _eval_symbol(sym, bindings)
            finally:
                autograd.set_training(prev_train)
                autograd.set_recording(prev_rec)
            return tuple(o.data for o in outs)

        return fn

    def _entry(self, bucket: Tuple):
        """Compile (once) and return the bucket's XLA executable.
        Holding ``_lock`` across the compile trades warmup parallelism
        for the exactly-once contract: two worker threads hitting the
        same cold bucket would otherwise both pay the compile and one
        executable would be silently dropped."""
        with self._lock:
            entry = self._entries.get(bucket)
            if entry is not None:
                return entry
            import jax
            if self._guards:
                self._churn.note_compile(bucket)
            batch, seq = bucket
            in_structs = tuple(
                jax.ShapeDtypeStruct(self._concrete_shape(n, batch, seq),
                                     self._input_dtypes[n],
                                     sharding=self._sharding)
                for n in self._input_names)
            t0 = time.perf_counter()
            # ISSUE 13: load-or-compile through the persistent cache.
            # A verified disk hit skips tracing AND compilation; any
            # corrupt/truncated/stale entry quarantines inside
            # load() and we fall through to the cold path.
            from mxtpu import analysis
            compiled, source, ckey, cmeta = None, "cold", None, {}
            if self._cache is not None:
                ckey = self._cache_key(bucket)
                compiled, cmeta = self._cache.load(ckey, with_meta=True)  # mxlint: sync-point — disk, pre-serving
                if compiled is not None:
                    source = "disk"
            if compiled is None:
                with profiler.Task(f"serving:compile:b{batch}"
                                   f"{'' if seq is None else f's{seq}'}"):
                    # donation applied only where XLA honors it; on
                    # cpu it is a silent no-op, so skipping it keeps
                    # that backend's programs byte-identical
                    apply_donate = (self._donate and
                                    jax.default_backend() != "cpu")
                    jitted = jax.jit(
                        self._pure_fn(),
                        donate_argnums=(0,) if apply_donate else ())
                    compiled = jitted.lower(in_structs,
                                            self._param_structs).compile()
                # MXTPU_HLO_AUDIT: static hygiene pass over every
                # bucket executable as it is born (warmup() therefore
                # audits the whole ladder) — no host transfers, no f64
                # creep, no layout-bracketed custom calls.  Audit
                # BEFORE the store so a program that fails a raising
                # audit never reaches disk.
                analysis.maybe_audit(compiled,
                                     label=f"ModelRunner{bucket}")
                if ckey is not None:
                    # serialize for the next process, stamped with
                    # this process's audit modes; failures degrade to
                    # a flight-recorder event inside store()
                    self._cache.store(ckey, compiled,
                                      meta=analysis.audit_stamp())
            elif analysis.needs_reaudit(cmeta):
                # the audit knobs are per-process: the writer audited
                # less strictly than this process asks for (or not at
                # all), so the reloaded program is audited here
                analysis.maybe_audit(compiled,
                                     label=f"ModelRunner{bucket}")
            self.compile_seconds[bucket] = time.perf_counter() - t0
            entry = {"compiled": compiled, "in_structs": in_structs}
            self._entries[bucket] = entry
            if self._obs:
                if source == "cold":
                    # actual XLA compiles only — disk hits are entry
                    # builds but not compiles (dashboards read this
                    # as compile volume)
                    self._m_compile.inc()
                else:
                    self._m_cache_hit.inc()
                self._m_compile_s[source].observe(
                    self.compile_seconds[bucket])
                obs.flight("compile").record(
                    "compile_miss", entry=self._entry_label,
                    bucket=str(bucket), source=source,
                    seconds=round(self.compile_seconds[bucket], 4))
            return entry

    def warmup(self, buckets: Optional[Sequence[Tuple]] = None
               ) -> Dict[Tuple, float]:
        """Pre-compile the ladder (or a subset) so no production request
        pays a compile; returns per-bucket compile seconds."""
        with guards.no_implicit_transfers(self._guards):
            for bucket in (buckets if buckets is not None
                           else self.buckets()):
                self._entry(tuple(bucket))
        with self._lock:
            return dict(self.compile_seconds)

    # -- execution --------------------------------------------------------
    def _pad_stack(self, rows: List[Dict[str, np.ndarray]],
                   bucket: Tuple) -> Tuple:
        """Per-example input dicts -> padded device-ready arrays of the
        bucket's shape.  Batch padding repeats row 0 (keeps values in
        the embedding/index domain — zeros could be out-of-vocab for
        some models, row 0 never is); sequence padding uses
        ``pad_value``."""
        import jax
        batch, seq = bucket
        vals = []
        for name in self._input_names:
            shape = self._concrete_shape(name, batch, seq)
            dt = self._input_dtypes[name]
            buf = np.empty(shape, dt)
            for i, row in enumerate(rows):
                # mxlint: sync-point — staging host rows, not device data
                ex = np.asarray(row[name], dt)
                if ex.shape != shape[1:]:
                    # sequence-pad every None axis up to the bucket
                    pads, slices = [], []
                    for d, (want, got) in enumerate(
                            zip(shape[1:], ex.shape)):
                        if got > want:
                            raise MXNetError(
                                f"serving: input {name!r} axis {d} size "
                                f"{got} exceeds bucket {want}")
                        pads.append((0, want - got))
                        slices.append(slice(0, got))
                    ex = np.pad(ex, pads, constant_values=self._pad_value)
                buf[i] = ex
            if len(rows) < batch:
                buf[len(rows):] = buf[0]
            vals.append(jax.device_put(buf, self._device))
        return tuple(vals)

    def run_raw(self, input_vals: Tuple, bucket: Tuple) -> Tuple:
        """One executable dispatch on pre-padded device arrays — the
        back-to-back path bench.py measures batcher overhead against."""
        entry = self._entry(bucket)
        if self._guards:
            self._churn.note_call()
        with guards.no_implicit_transfers(self._guards):
            return entry["compiled"](input_vals, self._param_vals)

    def infer(self, inputs: Dict[str, np.ndarray],
              seq_len: Optional[int] = None) -> List[np.ndarray]:
        """Synchronous batched inference: ``inputs`` carry a leading
        batch axis; pads to the covering bucket, runs, slices back.
        Returns host numpy arrays (one per graph output)."""
        names = self._input_names
        # mxlint: sync-point — inputs are caller-supplied host arrays
        n = int(np.asarray(inputs[names[0]]).shape[0])
        if seq_len is None and self.seq_buckets is not None:
            seq_len = int(np.asarray(inputs[names[0]]).shape[1])  # mxlint: sync-point
        bucket = self.bucket_for(n, seq_len)
        rows = [{name: np.asarray(inputs[name])[i] for name in names}  # mxlint: sync-point
                for i in range(n)]
        vals = self._pad_stack(rows, bucket)
        outs = self.run_raw(vals, bucket)
        # mxlint: sync-point — the one deliberate D2H: materialize outputs
        return [np.asarray(o)[:n] for o in outs]

    def run_requests(self, requests: List[InferenceRequest],
                     now: Optional[float] = None,
                     mutate=None) -> Tuple:
        """Server path: execute one assembled same-group batch and
        scatter each request its OWN output rows (sequence axis trimmed
        back to the request's true length).  Returns (bucket, outputs)
        for stats.  ``mutate`` (host outputs -> host outputs) is the
        fault-injection seam — mxtpu.serving.faults corrupts results
        here so canary-based detection is exercised deterministically;
        production callers leave it None."""
        n = len(requests)
        seq = requests[0].group if self.seq_buckets is not None else None
        bucket = self.bucket_for(n, seq)
        # obs phase spans (pad/scatter, execute) — gated BEFORE any
        # timing/args work so the profiler-off path is one bool read
        active = profiler.is_active()
        tids = [r.trace_id for r in requests
                if r.trace_id is not None] if active else []
        t0 = profiler._now_us() if active else 0.0
        vals = self._pad_stack([r.payload for r in requests], bucket)
        if active:
            t1 = profiler._now_us()
            obs.span(obs.SPAN_PAD_SCATTER, t0, t1 - t0, cat="serving",
                     trace_ids=tids, bucket=str(bucket), batch=n)
        outs = self.run_raw(vals, bucket)
        # mxlint: sync-point — deliberate D2H before scattering rows
        host = [np.asarray(o) for o in outs]
        if active:
            obs.span(obs.SPAN_RUN, t1, profiler._now_us() - t1,
                     cat="serving", trace_ids=tids,
                     bucket=str(bucket), batch=n)
        if mutate is not None:
            host = mutate(host)
        done_t = time.monotonic() if now is None else now
        for i, r in enumerate(requests):
            row_outs = []
            for o in host:
                row = o[i]
                # un-pad the sequence axis (axis 0 of the per-example
                # view) when this output still carries the bucket length
                if (seq is not None and r.seq_len is not None
                        and row.ndim >= 1 and row.shape[0] == seq
                        and r.seq_len < seq):
                    row = row[:r.seq_len]
                row_outs.append(row)
            r._complete(row_outs, done_t)
        return bucket, host

    # -- introspection ----------------------------------------------------
    def program_artifact(self, bucket: Tuple):
        """``(hlo_text, mem_stats)`` of one bucket's compiled
        executable (compiling it if cold) — what tools/hlocheck
        summarizes into the serving contract."""
        from mxtpu import analysis
        compiled = self._entry(tuple(bucket))["compiled"]
        return compiled.as_text(), analysis.mem_stats(compiled)

    def program_summary(self, bucket: Tuple):
        """Contract-shaped static summary (``mxtpu.analysis``) of one
        bucket's compiled executable."""
        from mxtpu import analysis
        text, mem = self.program_artifact(bucket)
        return analysis.summarize(text, mem)

    def memory_summary(self, buckets: Optional[Sequence[Tuple]] = None):
        """The sanctioned memory view (``mxtpu.analysis.memflow``) of
        this runner's bucket ladder (largest bucket by default):
        per-program HBM decomposition with weights attributed, plus
        any memory hazard findings — what tests and operators read
        instead of raw ``memory_analysis()`` grepping (mxlint
        ``mem-hygiene``)."""
        from mxtpu.analysis import memflow
        if buckets is None:
            buckets = [self.buckets()[-1]]
        record = memflow.runner_record(self, buckets=buckets)
        budgets = memflow.load_budgets(
            memflow.REPO_ROOT / "contracts")
        return memflow.summary_view(record, budgets)

    def lowered_program_text(self, bucket: Tuple) -> str:
        """PRE-optimization HLO (with source metadata) of one
        bucket's program — lowers only, never compiles, so mxprec can
        ledger a cold ladder without paying warmup."""
        import jax
        from mxtpu import analysis
        batch, seq = tuple(bucket)
        in_structs = tuple(
            jax.ShapeDtypeStruct(
                self._concrete_shape(n, batch, seq),
                self._input_dtypes[n], sharding=self._sharding)
            for n in self._input_names)
        return analysis.lowered_text(self._pure_fn(), in_structs,
                                     self._param_structs)

    def num_compiled(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- fleet handoff (ISSUE 7: preemption-safe draining) ---------------
    def ladder_metadata(self) -> Dict[str, Any]:
        """What a draining worker hands its replacement: the ladder
        shape plus WHICH buckets were actually compiled (traffic-driven
        subset) and what each cost — so the replacement warms exactly
        the donor's working set instead of the full cross product."""
        with self._lock:
            compiled = sorted(self._entries)
            secs = dict(self.compile_seconds)
        return {"max_batch_size": self.max_batch_size,
                "seq_buckets": list(self.seq_buckets)
                if self.seq_buckets is not None else None,
                "compiled_buckets": [list(b) for b in compiled],
                "compile_seconds": {str(k): v for k, v in secs.items()},
                "weight_bytes": self.weight_bytes()}

    def warm_from(self, metadata: Dict[str, Any]) -> Dict[Tuple, float]:
        """Warm this (replacement) runner from a donor's
        :meth:`ladder_metadata` — compiles the donor's bucket set,
        restricted to buckets this runner's own ladder actually has
        (a replacement with a different ladder warms the
        intersection)."""
        own = set(self.buckets())
        donor = [tuple(b) for b in metadata.get("compiled_buckets", [])]
        return self.warmup([b for b in donor if b in own])

    def weight_buffers(self) -> Tuple:
        """The committed device arrays every bucket executable reads —
        tests assert these stay the SAME buffers across buckets (the
        MXPredReshape zero-copy contract)."""
        return self._param_vals

    def weight_bytes(self) -> int:
        return int(sum(v.nbytes for v in self._param_vals))
