"""Fleet control plane (ISSUE 11 tentpole): metrics-driven
autoscaling, plus the priority/fairness vocabulary the router's
admission control and weighted-round-robin dispatch consume.

The fleet (router.py) gave N workers health states, draining and
``add_worker``; obs gave per-endpoint queue depth, fill rate and
latency histograms.  This module closes the loop:

* :class:`PriorityClass` / :func:`parse_classes` — traffic classes on
  :class:`~.router.FleetRequest`: ``weight`` sets the router's
  weighted-round-robin dispatch share (no tenant starves), ``quota``
  bounds in-system requests per class (one hot tenant cannot own the
  whole pending buffer).  Admission control is class-aware: a
  request's predicted ETA counts only same-or-higher-priority backlog,
  so a brownout sheds low-priority traffic first — see
  ``FleetRouter.submit``.
* :class:`Autoscaler` — scales worker replicas from registry signals
  (mean outstanding per healthy worker including the router backlog,
  and the histogram-derived ``queue_eta_us``) with hysteresis bands
  (``breach_ticks`` consecutive over/under-band evaluations before
  acting), a cooldown between actions, **drain-based scale-down**
  (``FleetRouter.drain``: in-flight work always completes; the victim
  retires, it is never killed) and **warm-handoff scale-up**
  (``add_worker(w, warm_from=donor.handoff())``: the replica
  pre-compiles the donor's bucket working set before taking traffic —
  zero cold compiles on the data path).  The handoff of the most
  recently drained worker is kept, so a scale-up with no live donor
  (burst after scale-to-floor) still warms from the last retiree;
  with no donor AND no cached handoff the replica warms from the
  persistent compile cache when it holds ladder entries (ISSUE 13,
  ``mxtpu/cache.py``) — the ``scale_up`` flight-recorder event's
  ``donor`` field says which path fired (a worker name,
  ``"last_handoff"``, ``"disk_cache"``, or ``None`` for cold).

Determinism: the autoscaler is tick-driven on the injected clock —
``router.add_controller(scaler.tick)`` makes the router's own tick
drive it (threaded and deterministic modes alike), or tests call
``tick(now)`` directly.  Every decision is recorded to the
``fleet/autoscaler`` flight recorder and emitted as a
``fleet/scale`` trace span, so each verdict is reconstructable
post-mortem.

Lock order: :class:`Autoscaler` reads fleet signals (worker stats,
batcher depths) holding NO lock, then updates its own decision state
under ``Autoscaler._lock`` (a leaf — it acquires nothing inside), and
only then acts on the router with no autoscaler lock held.  The
router-side class state is on ``FleetRouter._class_lock`` (leaf; see
router.py's lock-order contract).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..base import MXNetError
from .. import knobs
from .. import obs
from .. import profiler
from .health import WorkerState

__all__ = ["PriorityClass", "parse_classes", "Autoscaler"]

logger = logging.getLogger("mxtpu.serving.fleet")


class PriorityClass:
    """One traffic class.  ``weight`` is the weighted-round-robin
    dispatch share (higher = served first out of the router backlog,
    and counted as "ahead" by lower classes' admission ETA); ``quota``
    bounds the class's in-system (admitted, not yet completed)
    requests — ``None`` means only the router-wide ``max_pending``
    bound applies."""

    __slots__ = ("name", "weight", "quota")

    def __init__(self, name: str, weight: float = 1.0,
                 quota: Optional[int] = None):
        if not name:
            raise MXNetError("serving: priority class needs a name")
        if weight <= 0:
            raise MXNetError(
                f"serving: priority class {name!r} weight must be "
                f"positive, got {weight}")
        if quota is not None and quota < 1:
            raise MXNetError(
                f"serving: priority class {name!r} quota must be "
                f">= 1, got {quota}")
        self.name = str(name)
        self.weight = float(weight)
        self.quota = None if quota is None else int(quota)

    def __repr__(self) -> str:
        return (f"PriorityClass({self.name!r}, weight={self.weight}, "
                f"quota={self.quota})")


def parse_classes(spec: str) -> List[PriorityClass]:
    """Parse the ``MXTPU_FLEET_CLASSES`` knob:
    ``name:weight[:quota],...`` (e.g. ``gold:8,bulk:1:64``).  Empty
    spec → empty list (the router then runs one ``default`` class)."""
    out: List[PriorityClass] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        try:
            weight = float(bits[1]) if len(bits) > 1 and bits[1] \
                else 1.0
            quota = int(bits[2]) if len(bits) > 2 and bits[2] else None
        except ValueError as e:
            raise MXNetError(
                f"serving: bad class spec {part!r} "
                f"(want name:weight[:quota]): {e}") from None
        out.append(PriorityClass(bits[0], weight, quota))
    return out


class Autoscaler:
    """Metrics-driven replica controller for one :class:`FleetRouter`.

    >>> scaler = Autoscaler(router, make_worker, min_workers=1,
    ...                     max_workers=3, up_depth=4.0,
    ...                     breach_ticks=2, cooldown_s=0.5)
    >>> router.add_controller(scaler.tick)   # router tick drives it

    ``make_worker(name)`` must return a fresh, un-attached
    :class:`~.router.FleetWorker` sharing the fleet's bucket ladder.
    """

    def __init__(self, router, make_worker: Callable[[str], Any], *,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 up_depth: Optional[float] = None,
                 down_depth: Optional[float] = None,
                 up_eta_us: Optional[float] = None,
                 breach_ticks: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 name_prefix: str = "auto",
                 clock: Optional[Callable[[], float]] = None,
                 slo=None, burn_scale: Optional[bool] = None):
        self._router = router
        self._make_worker = make_worker
        g = knobs.get
        # optional SLO coupling (ISSUE 14): when MXTPU_FLEET_AUTOSCALE
        # _BURN is on AND an engine is supplied, a firing burn-rate
        # alert counts as an overload tick.  Both default off, so the
        # decision loop is bit-identical to the pre-SLO autoscaler.
        self._slo = slo
        self.burn_scale = bool(burn_scale) if burn_scale is not None \
            else bool(g("MXTPU_FLEET_AUTOSCALE_BURN"))
        self.min_workers = min_workers if min_workers is not None \
            else g("MXTPU_FLEET_AUTOSCALE_MIN")
        self.max_workers = max_workers if max_workers is not None \
            else g("MXTPU_FLEET_AUTOSCALE_MAX")
        self.up_depth = up_depth if up_depth is not None \
            else g("MXTPU_FLEET_AUTOSCALE_UP_DEPTH")
        self.down_depth = down_depth if down_depth is not None \
            else g("MXTPU_FLEET_AUTOSCALE_DOWN_DEPTH")
        self.up_eta_us = up_eta_us if up_eta_us is not None \
            else g("MXTPU_FLEET_AUTOSCALE_UP_ETA_US")
        self.breach_ticks = breach_ticks if breach_ticks is not None \
            else g("MXTPU_FLEET_AUTOSCALE_BREACH_TICKS")
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else g("MXTPU_FLEET_AUTOSCALE_COOLDOWN_S")
        if self.min_workers < 1 or self.max_workers < self.min_workers:
            raise MXNetError(
                f"serving: autoscaler wants 1 <= min <= max, got "
                f"min={self.min_workers} max={self.max_workers}")
        self.name_prefix = name_prefix
        self._clock = clock if clock is not None \
            else getattr(router, "_clock", time.monotonic)
        self.recorder = obs.flight("fleet/autoscaler",
                                   clock=self._clock)
        self._lock = threading.Lock()
        self._breach_up = 0       # guarded-by: _lock
        self._breach_down = 0     # guarded-by: _lock
        self._last_action_t: Optional[float] = None  # guarded-by: _lock
        self._seq = 0             # guarded-by: _lock
        self._scale_ups = 0       # guarded-by: _lock
        self._scale_downs = 0     # guarded-by: _lock
        # handoff metadata of the most recently drained worker — the
        # warm source for a scale-up with no live donor
        self._last_handoff: Optional[Dict[str, Any]] = None  # guarded-by: _lock

    # -- the decision loop -------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One evaluation: read fleet signals (no lock held), update
        the hysteresis bands under the autoscaler lock, then act on
        the router lock-free.  Returns the action taken ("up"/"down")
        or None — tests key off it."""
        now = self._clock() if now is None else now
        members = self._router.members()
        healthy = [w for w in members
                   if w.health.state == WorkerState.HEALTHY]
        live = [w for w in members
                if w.health.state != WorkerState.DEAD]
        pending = self._router.pending_depth()
        if healthy:
            depth_per = (sum(w.outstanding() for w in healthy)
                         + pending) / len(healthy)
            eta_us = max((e for e in (w.stats.queue_eta_us()
                                      for w in healthy)
                          if e is not None), default=0.0)
        else:
            depth_per, eta_us = 0.0, 0.0
        burning: list = []
        if self.burn_scale and self._slo is not None:
            burning = self._slo.firing()
        overload = bool(healthy) and (
            depth_per > self.up_depth
            or (self.up_eta_us > 0 and eta_us > self.up_eta_us)
            or bool(burning))
        underload = bool(healthy) and pending == 0 \
            and depth_per < self.down_depth
        action: Optional[str] = None
        seq = 0
        with self._lock:
            self._breach_up = self._breach_up + 1 if overload else 0
            self._breach_down = self._breach_down + 1 if underload \
                else 0
            cooling = self._last_action_t is not None and \
                now - self._last_action_t < self.cooldown_s
            if not cooling:
                if len(live) < self.min_workers:
                    # below floor (deaths, not load): repair is not a
                    # band decision, it just happens
                    action = "up"
                elif self._breach_up >= self.breach_ticks and \
                        len(live) < self.max_workers:
                    action = "up"
                elif self._breach_down >= self.breach_ticks and \
                        len(healthy) > self.min_workers:
                    action = "down"
            if action is not None:
                self._last_action_t = now
                self._breach_up = self._breach_down = 0
                if action == "up":
                    self._seq += 1
                    self._scale_ups += 1
                    seq = self._seq
                else:
                    self._scale_downs += 1
        if action == "up":
            self._scale_up(now, seq, healthy, depth_per, eta_us,
                           pending, burning)
        elif action == "down":
            self._scale_down(now, healthy, depth_per)
        return action

    # -- actions (no autoscaler lock held) ---------------------------------
    def _scale_up(self, now: float, seq: int, healthy: list,
                  depth_per: float, eta_us: float,
                  pending: int, burning: list = ()) -> None:
        donor = healthy[0] if healthy else None
        if donor is not None:
            meta = donor.handoff()
        else:
            with self._lock:
                meta = self._last_handoff
        worker = self._make_worker(f"{self.name_prefix}{seq}")
        # ``add_worker`` warms from the donor metadata when present,
        # else from the persistent compile cache (ISSUE 13) when that
        # holds ladder entries; its return value says which path
        # ACTUALLY fired (no second cache probe, no label that can
        # disagree with what was warmed).
        warmed = self._router.add_worker(worker, warm_from=meta)
        if donor is not None:
            warm_src = donor.name
        elif meta is not None:
            warm_src = "last_handoff"
        else:
            warm_src = warmed  # "disk_cache" or None (cold)
        self._router.stats.bump("scale_ups")
        detail: Dict[str, Any] = dict(
            worker=worker.name, donor=warm_src,
            depth_per=round(depth_per, 2),
            eta_us=round(eta_us, 1), pending=pending)
        if burning:
            # only present when the SLO gate contributed — existing
            # scenario events stay byte-identical with the knob off
            detail["burn_slos"] = list(burning)
        self.recorder.record("scale_up", **detail)
        if profiler.is_active():
            obs.span(obs.SPAN_SCALE, now * 1e6, 0.0, cat="fleet",
                     direction="up", worker=worker.name,
                     depth_per=round(depth_per, 2),
                     eta_us=round(eta_us, 1))
        logger.info("fleet autoscaler: scale UP -> %s (depth/worker "
                    "%.2f, eta %.0fus, pending %d)", worker.name,
                    depth_per, eta_us, pending)

    def _scale_down(self, now: float, healthy: list,
                    depth_per: float) -> None:
        victim = min(healthy, key=lambda w: (w.outstanding(), w.name))
        meta = self._router.drain(victim.name, now)
        with self._lock:
            self._last_handoff = meta
        self._router.stats.bump("scale_downs")
        self.recorder.record("scale_down", worker=victim.name,
                             depth_per=round(depth_per, 2),
                             outstanding=victim.outstanding())
        if profiler.is_active():
            obs.span(obs.SPAN_SCALE, now * 1e6, 0.0, cat="fleet",
                     direction="down", worker=victim.name,
                     depth_per=round(depth_per, 2))
        logger.info("fleet autoscaler: scale DOWN, draining %s "
                    "(depth/worker %.2f)", victim.name, depth_per)

    # -- observability -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "min_workers": self.min_workers,
                "max_workers": self.max_workers,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "breach_up": self._breach_up,
                "breach_down": self._breach_down,
                "last_action_t": self._last_action_t,
                "warm_handoff_cached": self._last_handoff is not None,
                "burn_scale": self.burn_scale,
            }
