"""Deterministic fault-injection harness for the serving fleet
(ISSUE 7 tentpole item d).

Faults are *scripted*, keyed on the worker's dispatch counter (batch
``k`` = the k-th batch that worker ever dispatched, canaries included)
— the same determinism discipline as the batcher's injected clock: a
test that scripts ``CrashAt(at_batch=1)`` sees the crash on exactly
the second dispatch, every run, no sleeps, no races.  The plan is
consulted by :class:`~.router.FleetWorker` at its dispatch seam, so
every recovery path in the router/health machinery is exercised by
tier-1 tests instead of only showing up in a soak:

* :class:`Hang` — the dispatched batch never completes (the worker
  thread is stuck in the executable).  Detected by the in-flight
  liveness deadline; outstanding requests are stolen and retried.
* :class:`SlowStart` — the first ``first_n`` dispatches fail with a
  retriable startup error (cold replica, weights still loading).
  A RECOVERING worker keeps failing canaries until warm.
* :class:`CrashAt` — dispatch ``k`` raises :class:`WorkerCrashed`
  (preemption / OOM-kill).  DEAD immediately; in-flight requeued.
* :class:`Corrupt` — dispatches from ``k`` on return silently wrong
  results (bit-flip, bad DMA).  No exception anywhere — only a
  canary comparing against its expected output can catch it.
* :class:`QueueWedge` — from dispatch ``k`` on, the worker stops
  pulling from its queue while still accepting submissions.  Detected
  by the queued-request liveness age.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["WorkerCrashed", "SlowStartError", "HangSignal",
           "Fault", "Hang", "SlowStart", "CrashAt", "Corrupt",
           "SlowExec", "QueueWedge", "FaultPlan"]


class WorkerCrashed(MXNetError):
    """The worker process died mid-dispatch (preemption, OOM-kill)."""


class SlowStartError(MXNetError):
    """Transient startup failure — the replica is not warm yet."""


class HangSignal(Exception):
    """Internal sentinel: the dispatch would block forever.  The
    worker leaves the batch registered in-flight and parks — exactly
    what a hung executable looks like from the outside — instead of
    actually blocking a thread the test could never join."""


class Fault:
    """One scripted fault.  Subclasses override the hooks they need;
    ``k`` is the worker's dispatch counter (0-based)."""

    def wedged(self, k: int) -> bool:
        return False

    def before_batch(self, k: int) -> None:
        """Raise to fail/crash/hang dispatch ``k``."""

    def mutate(self, k: int,
               host: List[np.ndarray]) -> List[np.ndarray]:
        """Transform the host outputs of dispatch ``k`` (corruption)."""
        return host


class Hang(Fault):
    def __init__(self, at_batch: int = 0):
        self.at_batch = int(at_batch)

    def before_batch(self, k: int) -> None:
        if k == self.at_batch:
            raise HangSignal(f"scripted hang at batch {k}")


class SlowStart(Fault):
    def __init__(self, first_n: int = 2):
        self.first_n = int(first_n)

    def before_batch(self, k: int) -> None:
        if k < self.first_n:
            raise SlowStartError(
                f"scripted slow start: dispatch {k} of first "
                f"{self.first_n} fails (replica still warming)")


class CrashAt(Fault):
    def __init__(self, at_batch: int = 0):
        self.at_batch = int(at_batch)

    def before_batch(self, k: int) -> None:
        if k == self.at_batch:
            raise WorkerCrashed(f"scripted crash at batch {k}")


class Corrupt(Fault):
    """Silently corrupt every output from dispatch ``from_batch`` on
    (negate and offset — guaranteed to miss any expected value)."""

    def __init__(self, from_batch: int = 0):
        self.from_batch = int(from_batch)

    def mutate(self, k: int,
               host: List[np.ndarray]) -> List[np.ndarray]:
        if k < self.from_batch:
            return host
        return [np.asarray(-(h.astype(np.float64)) + 1e6)  # mxlint: disable=dtype-hygiene (fault injection wants the overflow)
                .astype(h.dtype)
                if np.issubdtype(h.dtype, np.number) else h
                for h in host]


class SlowExec(Fault):
    """Deterministic service time on the fake clock: each dispatch
    from ``from_batch`` on advances the injected test clock by
    ``service_s`` before the batch runs, so completions carry real
    (nonzero) service-time samples.  This is how the control-plane
    scenarios (ISSUE 11) get a meaningful latency histogram — the
    signal ``queue_eta_us`` and the autoscaler read — without any
    wall-clock sleeps.  ``advance`` is the test clock's ``advance``
    callable; production clocks have no such hook, which is the point:
    this fault is harness-only."""

    def __init__(self, service_s: float,
                 advance: Callable[[float], None],
                 from_batch: int = 0):
        self.service_s = float(service_s)
        self.advance = advance
        self.from_batch = int(from_batch)

    def before_batch(self, k: int) -> None:
        if k >= self.from_batch:
            self.advance(self.service_s)


class QueueWedge(Fault):
    """From dispatch ``after_batches`` on, the worker stops pulling
    batches (its queue wedges) while submissions keep landing."""

    def __init__(self, after_batches: int = 0):
        self.after_batches = int(after_batches)

    def wedged(self, k: int) -> bool:
        return k >= self.after_batches


class FaultPlan:
    """A deterministic script: the union of its faults, consulted by
    the worker at each dispatch.  ``fired`` records what actually
    triggered, so tests can assert the scenario ran."""

    def __init__(self, *faults: Fault):
        self.faults = list(faults)
        self.fired: List[str] = []

    def wedged(self, k: int) -> bool:
        for f in self.faults:
            if f.wedged(k):
                if not self.fired or self.fired[-1] != "wedge":
                    self.fired.append("wedge")
                return True
        return False

    def before_batch(self, k: int) -> None:
        for f in self.faults:
            try:
                f.before_batch(k)
            except Exception:
                self.fired.append(f"{type(f).__name__.lower()}@{k}")
                raise

    def mutator(self, k: int) -> Optional[
            Callable[[List[np.ndarray]], List[np.ndarray]]]:
        muts = [f for f in self.faults
                if type(f).mutate is not Fault.mutate]
        if not muts:
            return None

        def apply(host: List[np.ndarray]) -> List[np.ndarray]:
            out = host
            for f in muts:
                before = out
                out = f.mutate(k, out)
                if out is not before:
                    self.fired.append(
                        f"{type(f).__name__.lower()}@{k}")
            return out

        return apply
