"""Deterministic fault-injection harness for the serving fleet
(ISSUE 7 tentpole item d).

Faults are *scripted*, keyed on the worker's dispatch counter (batch
``k`` = the k-th batch that worker ever dispatched, canaries included)
— the same determinism discipline as the batcher's injected clock: a
test that scripts ``CrashAt(at_batch=1)`` sees the crash on exactly
the second dispatch, every run, no sleeps, no races.  The plan is
consulted by :class:`~.router.FleetWorker` at its dispatch seam, so
every recovery path in the router/health machinery is exercised by
tier-1 tests instead of only showing up in a soak:

* :class:`Hang` — the dispatched batch never completes (the worker
  thread is stuck in the executable).  Detected by the in-flight
  liveness deadline; outstanding requests are stolen and retried.
* :class:`SlowStart` — the first ``first_n`` dispatches fail with a
  retriable startup error (cold replica, weights still loading).
  A RECOVERING worker keeps failing canaries until warm.
* :class:`CrashAt` — dispatch ``k`` raises :class:`WorkerCrashed`
  (preemption / OOM-kill).  DEAD immediately; in-flight requeued.
* :class:`Corrupt` — dispatches from ``k`` on return silently wrong
  results (bit-flip, bad DMA).  No exception anywhere — only a
  canary comparing against its expected output can catch it.
* :class:`QueueWedge` — from dispatch ``k`` on, the worker stops
  pulling from its queue while still accepting submissions.  Detected
  by the queued-request liveness age.

The persistent compile cache (ISSUE 13, ``mxtpu/cache.py``) extends
the harness with *cache faults*, keyed on the cache's own store
counter ``k`` (the k-th entry that cache ever committed) and consulted
by :class:`~mxtpu.cache.ExecutableCache` at its write seams — same
determinism, so every recovery path is reproducible in tier-1 in both
the threaded and the sync fleet modes:

* :class:`CorruptEntry` — flip a payload byte of stored entry ``k``
  (bit-rot / bad DMA).  Caught by the load-time checksum; the entry
  is quarantined and the caller recompiles.
* :class:`TruncateEntry` — cut stored entry ``k`` in half (crash
  mid-copy).  Caught structurally; quarantine + recompile.
* :class:`StaleKey` — rewrite a key component of stored entry ``k``
  keeping the checksum VALID (an entry from an old jax / old
  contracts).  Caught by key revalidation; quarantine + recompile.
* :class:`ReadOnlyDir` — stores from ``k`` on fail with
  ``PermissionError`` (read-only cache root / EROFS).  Degrades to
  plain compile with a flight-recorder event; never an error.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["WorkerCrashed", "SlowStartError", "HangSignal",
           "Fault", "Hang", "SlowStart", "CrashAt", "Corrupt",
           "SlowExec", "QueueWedge", "CorruptEntry", "TruncateEntry",
           "StaleKey", "ReadOnlyDir", "FaultPlan"]


class WorkerCrashed(MXNetError):
    """The worker process died mid-dispatch (preemption, OOM-kill)."""


class SlowStartError(MXNetError):
    """Transient startup failure — the replica is not warm yet."""


class HangSignal(Exception):
    """Internal sentinel: the dispatch would block forever.  The
    worker leaves the batch registered in-flight and parks — exactly
    what a hung executable looks like from the outside — instead of
    actually blocking a thread the test could never join."""


class Fault:
    """One scripted fault.  Subclasses override the hooks they need;
    ``k`` is the worker's dispatch counter (0-based)."""

    def wedged(self, k: int) -> bool:
        return False

    def before_batch(self, k: int) -> None:
        """Raise to fail/crash/hang dispatch ``k``."""

    def mutate(self, k: int,
               host: List[np.ndarray]) -> List[np.ndarray]:
        """Transform the host outputs of dispatch ``k`` (corruption)."""
        return host

    # -- compile-cache seams (mxtpu/cache.py; ``k`` is the cache's
    #    store counter, not the dispatch counter) ----------------------
    def before_cache_write(self, k: int) -> None:
        """Raise (OSError family) to deny committing entry ``k``."""

    def on_entry_written(self, k: int, path) -> bool:
        """Mutate the just-committed entry file ``k`` on disk; return
        True if this fault touched it (recorded in ``fired``)."""
        return False


class Hang(Fault):
    def __init__(self, at_batch: int = 0):
        self.at_batch = int(at_batch)

    def before_batch(self, k: int) -> None:
        if k == self.at_batch:
            raise HangSignal(f"scripted hang at batch {k}")


class SlowStart(Fault):
    def __init__(self, first_n: int = 2):
        self.first_n = int(first_n)

    def before_batch(self, k: int) -> None:
        if k < self.first_n:
            raise SlowStartError(
                f"scripted slow start: dispatch {k} of first "
                f"{self.first_n} fails (replica still warming)")


class CrashAt(Fault):
    def __init__(self, at_batch: int = 0):
        self.at_batch = int(at_batch)

    def before_batch(self, k: int) -> None:
        if k == self.at_batch:
            raise WorkerCrashed(f"scripted crash at batch {k}")


class Corrupt(Fault):
    """Silently corrupt every output from dispatch ``from_batch`` on
    (negate and offset — guaranteed to miss any expected value)."""

    def __init__(self, from_batch: int = 0):
        self.from_batch = int(from_batch)

    def mutate(self, k: int,
               host: List[np.ndarray]) -> List[np.ndarray]:
        if k < self.from_batch:
            return host
        return [np.asarray(-(h.astype(np.float64)) + 1e6)  # mxlint: disable=dtype-hygiene (fault injection wants the overflow)
                .astype(h.dtype)
                if np.issubdtype(h.dtype, np.number) else h
                for h in host]


class SlowExec(Fault):
    """Deterministic service time on the fake clock: each dispatch
    from ``from_batch`` on advances the injected test clock by
    ``service_s`` before the batch runs, so completions carry real
    (nonzero) service-time samples.  This is how the control-plane
    scenarios (ISSUE 11) get a meaningful latency histogram — the
    signal ``queue_eta_us`` and the autoscaler read — without any
    wall-clock sleeps.  ``advance`` is the test clock's ``advance``
    callable; production clocks have no such hook, which is the point:
    this fault is harness-only."""

    def __init__(self, service_s: float,
                 advance: Callable[[float], None],
                 from_batch: int = 0):
        self.service_s = float(service_s)
        self.advance = advance
        self.from_batch = int(from_batch)

    def before_batch(self, k: int) -> None:
        if k >= self.from_batch:
            self.advance(self.service_s)


class QueueWedge(Fault):
    """From dispatch ``after_batches`` on, the worker stops pulling
    batches (its queue wedges) while submissions keep landing."""

    def __init__(self, after_batches: int = 0):
        self.after_batches = int(after_batches)

    def wedged(self, k: int) -> bool:
        return k >= self.after_batches


class CorruptEntry(Fault):
    """Flip a payload byte of the ``at_store``-th committed cache
    entry — structurally intact, the load-time checksum must catch
    it (quarantine + recompile, never executed)."""

    def __init__(self, at_store: int = 0):
        self.at_store = int(at_store)

    def on_entry_written(self, k: int, path) -> bool:
        if k != self.at_store:
            return False
        from mxtpu import cache
        cache.poison_corrupt(path)
        return True


class TruncateEntry(Fault):
    """Cut the ``at_store``-th committed cache entry in half (crash
    mid-copy / partial write on a non-atomic filesystem)."""

    def __init__(self, at_store: int = 0):
        self.at_store = int(at_store)

    def on_entry_written(self, k: int, path) -> bool:
        if k != self.at_store:
            return False
        from mxtpu import cache
        cache.poison_truncate(path)
        return True


class StaleKey(Fault):
    """Rewrite one key component of the ``at_store``-th committed
    entry keeping its checksum valid — what an entry from an old jax
    or old contracts looks like; key revalidation must catch it."""

    def __init__(self, at_store: int = 0, component: str = "jax",
                 value: str = "0.0.0-stale"):
        self.at_store = int(at_store)
        self.component = component
        self.value = value

    def on_entry_written(self, k: int, path) -> bool:
        if k != self.at_store:
            return False
        from mxtpu import cache
        cache.poison_stale(path, self.component, self.value)
        return True


class ReadOnlyDir(Fault):
    """Cache stores from ``from_store`` on fail with
    ``PermissionError`` — a read-only cache root (EROFS container
    mount), scripted rather than chmod'd because uid-0 test runners
    ignore mode bits.  The cache must degrade to plain compile."""

    def __init__(self, from_store: int = 0):
        self.from_store = int(from_store)

    def before_cache_write(self, k: int) -> None:
        if k >= self.from_store:
            raise PermissionError(
                f"scripted read-only cache dir at store {k}")


class FaultPlan:
    """A deterministic script: the union of its faults, consulted by
    the worker at each dispatch.  ``fired`` records what actually
    triggered, so tests can assert the scenario ran."""

    def __init__(self, *faults: Fault):
        self.faults = list(faults)
        self.fired: List[str] = []

    def wedged(self, k: int) -> bool:
        for f in self.faults:
            if f.wedged(k):
                if not self.fired or self.fired[-1] != "wedge":
                    self.fired.append("wedge")
                return True
        return False

    def before_batch(self, k: int) -> None:
        for f in self.faults:
            try:
                f.before_batch(k)
            except Exception:
                self.fired.append(f"{type(f).__name__.lower()}@{k}")
                raise

    def before_cache_write(self, k: int) -> None:
        """Cache write seam (ExecutableCache.store): a fault raising
        here denies committing entry ``k``."""
        for f in self.faults:
            try:
                f.before_cache_write(k)
            except Exception:
                self.fired.append(f"{type(f).__name__.lower()}@{k}")
                raise

    def entry_written(self, k: int, path) -> None:
        """Post-commit seam: faults mutate the entry file in place
        (the next verified load must quarantine it)."""
        for f in self.faults:
            if f.on_entry_written(k, path):
                self.fired.append(f"{type(f).__name__.lower()}@{k}")

    def mutator(self, k: int) -> Optional[
            Callable[[List[np.ndarray]], List[np.ndarray]]]:
        muts = [f for f in self.faults
                if type(f).mutate is not Fault.mutate]
        if not muts:
            return None

        def apply(host: List[np.ndarray]) -> List[np.ndarray]:
            out = host
            for f in muts:
                before = out
                out = f.mutate(k, out)
                if out is not before:
                    self.fired.append(
                        f"{type(f).__name__.lower()}@{k}")
            return out

        return apply
