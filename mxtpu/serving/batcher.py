"""Dynamic micro-batcher (ISSUE 4 tentpole item 2).

A bounded request queue with ``max_batch_size`` / ``max_queue_delay_us``
batch assembly.  The batching *policy* is pure and clock-injected —
``submit(..)`` + ``poll(now)`` never touch wall time or threads, so
unit tests drive it deterministically; the server wraps it in worker
threads via ``wait_next()``.

Safety contract (acceptance criteria):
- the queue is bounded: ``submit`` past ``max_queue`` raises
  :class:`ServerBusy` — load sheds at the edge, memory never grows
  unboundedly;
- a request whose deadline passed is failed with
  :class:`RequestTimeout`, both while queued (dropped at poll) and when
  its batch finishes late (checked at completion) — a caller that timed
  out can never read a stale/late result;
- requests only ever batch with same-``group`` requests (the shape
  bucket), so pad/scatter cannot mix shapes.

Degradation to batch=1 when traffic is sparse falls out of the flush
rule: a lone request flushes after ``max_queue_delay_us`` and runs in
the smallest bucket.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional

from ..base import MXNetError
from .. import profiler
from ..obs import trace as _trace

__all__ = ["RetriableError", "ServerBusy", "RequestTimeout",
           "WorkerLost", "InferenceRequest", "Batch", "DynamicBatcher"]


class RetriableError(MXNetError):
    """Common base of the serving error taxonomy (ISSUE 7): every
    request-path error carries a ``retriable`` attribute so a caller
    (or the fleet router) can distinguish "retry elsewhere / later"
    from "give up".  Subclasses with ``retriable = False`` are
    terminal — retrying cannot help."""
    retriable = True


class ServerBusy(RetriableError):
    """Backpressure: the bounded request queue is full, a class quota
    is exhausted, or admission control predicted a deadline miss.
    Retriable — back off and resubmit, or route to another worker.

    ``retry_after_us``, when set, is the predicted queue ETA at the
    rejecting endpoint (``ServingStats.queue_eta_us``): the earliest
    resubmit that could plausibly succeed.  The fleet router parks a
    rejected dispatch for exactly this long instead of exponential
    guessing (ISSUE 11 satellite); external callers should do the
    same."""

    def __init__(self, msg: str = "",
                 retry_after_us: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_us = retry_after_us


class RequestTimeout(RetriableError):
    """The request's deadline expired before a result was available.
    Terminal: the deadline is gone no matter where you retry."""
    retriable = False


class WorkerLost(RetriableError):
    """The worker/batcher holding this request died or shut down
    before completing it.  Retriable — the same payload may well
    succeed on another worker (the fleet router does exactly that).

    ``partial``, when set, carries the partial-generation state of a
    request that died mid-decode (ISSUE 19): prompt + already-emitted
    tokens + the ORIGINAL ``t_submit``/``deadline``, so a replay on a
    surviving worker resumes the stream instead of restarting it, and
    deadline accounting spans the kill (never double-billed — the
    replay inherits the first attempt's clock, it does not reset
    it)."""

    def __init__(self, msg: str = "", partial: Optional[dict] = None):
        super().__init__(msg)
        self.partial = partial


class InferenceRequest:
    """Submit-side future.  ``result()`` blocks for the outcome;
    completion is one-shot — whichever of {result, timeout, error}
    lands first wins and later writes are ignored (a tiny per-request
    lock arbitrates concurrent completers: a hung worker coming back
    to life races the router failing it with :class:`WorkerLost`).

    ``add_done_callback`` lets the fleet router observe attempt
    outcomes without polling; callbacks may fire while a batcher lock
    is held, so they must only touch leaf state (the router appends to
    an event deque)."""

    __slots__ = ("payload", "group", "seq_len", "t_submit", "deadline",
                 "_event", "_value", "_error", "t_dequeue", "t_done",
                 "requeues", "trace_id", "_wlock", "_watchers")

    def __init__(self, payload: Any, group: Any = None,
                 seq_len: Optional[int] = None,
                 t_submit: float = 0.0,
                 deadline: Optional[float] = None,
                 trace_id: Optional[str] = None):
        self.payload = payload
        self.group = group
        self.seq_len = seq_len
        self.t_submit = t_submit
        self.deadline = deadline
        self.trace_id = trace_id   # obs: minted at the submit edge
        self.t_dequeue: Optional[float] = None
        # outcome fields are event-sequenced, not lock-shared: written
        # under _wlock strictly before _event.set(), read by callers
        # only after _event.wait() — the Event is the happens-before
        # edge, so no single lock covers both sides by design.
        # mxrace: disable=unguarded-attr (event-sequenced via _event)
        self.t_done: Optional[float] = None
        self.requeues = 0          # times this re-entered a queue
        self._event = threading.Event()
        # mxrace: disable=unguarded-attr (event-sequenced via _event)
        self._value: Any = None
        # mxrace: disable=unguarded-attr (event-sequenced via _event)
        self._error: Optional[BaseException] = None
        self._wlock = threading.Lock()
        self._watchers: List[Callable[[], None]] = []  # guarded-by: _wlock

    # -- completion (batcher/server side) -------------------------------
    def _finish(self, value: Any, error: Optional[BaseException],
                now: float) -> bool:
        with self._wlock:
            if self._event.is_set():
                return False
            self._value = value
            self._error = error
            self.t_done = now
            self._event.set()
            watchers, self._watchers = self._watchers, []
        for fn in watchers:
            try:
                fn()
            except Exception:   # noqa: BLE001 — a watcher must never
                pass            # poison the completing worker
        return True

    def _complete(self, value: Any, now: float) -> bool:
        """Deliver a result — unless the deadline already passed, in
        which case the caller gets RequestTimeout, never a late
        payload."""
        if self.deadline is not None and now > self.deadline:
            return self._fail(RequestTimeout(
                f"serving: request missed its deadline by "
                f"{(now - self.deadline) * 1e3:.2f} ms"), now)
        return self._finish(value, None, now)

    def _fail(self, error: BaseException, now: float) -> bool:
        return self._finish(None, error, now)

    def add_done_callback(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` (no args) once the request completes — or
        immediately if it already has."""
        with self._wlock:
            if not self._event.is_set():
                self._watchers.append(fn)
                return
        fn()

    # -- caller side ----------------------------------------------------
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise RequestTimeout(
                "serving: result() wait timed out (request still "
                "in flight)")
        if self._error is not None:
            raise self._error
        return self._value

    @property
    def latency_us(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1e6

    @property
    def queue_us(self) -> Optional[float]:
        if self.t_dequeue is None:
            return None
        return (self.t_dequeue - self.t_submit) * 1e6


def _lost_for(req: InferenceRequest,
              err: BaseException) -> BaseException:
    """The WorkerLost a dying batcher hands one request: a request
    that can describe its partial-generation progress
    (``partial_state()`` — GenerateRequest does) gets a per-request
    error carrying that state so the fleet layer can replay it
    without restarting the stream or resetting its deadline clock."""
    state_fn = getattr(req, "partial_state", None)
    if state_fn is None:
        return err
    try:
        partial = state_fn()
    except Exception:  # noqa: BLE001 — a broken state provider must
        return err     # not mask the loss itself
    if partial is None:
        return err
    return WorkerLost(str(err) or "serving: worker lost mid-"
                      "generation", partial=partial)


class Batch:
    """One assembled micro-batch: same-group requests, FIFO order."""

    __slots__ = ("requests", "group")

    def __init__(self, requests: List[InferenceRequest], group: Any):
        self.requests = requests
        self.group = group

    def __len__(self) -> int:
        return len(self.requests)


class DynamicBatcher:
    """Bounded FIFO + flush policy.

    Flush rule, evaluated against the oldest queued request (per
    group): dispatch when the group has ``max_batch_size`` requests
    waiting, OR when the oldest has waited ``max_queue_delay_us``.
    FIFO head priority keeps tail latency bounded under mixed-shape
    traffic: the assembled batch is always the one the *oldest*
    request belongs to.
    """

    def __init__(self, max_batch_size: int = 32,
                 max_queue_delay_us: float = 2000.0,
                 max_queue: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_timeout: Optional[Callable[[int], None]] = None,
                 on_depth: Optional[Callable[[int], None]] = None):
        if max_batch_size < 1:
            raise MXNetError("max_batch_size must be >= 1")
        self.max_batch_size = int(max_batch_size)
        self.max_queue_delay_us = float(max_queue_delay_us)
        self.max_queue = int(max_queue) if max_queue is not None \
            else 8 * self.max_batch_size
        self._clock = clock
        self._cond = threading.Condition()
        self._queue: List[InferenceRequest] = []  # guarded-by: _cond
        # dispatched (pulled into a Batch) but not yet completed —
        # what close() must fail so no waiter hangs on a dead worker
        self._inflight: List[InferenceRequest] = []  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        self._on_timeout = on_timeout
        self._on_depth = on_depth
        self._peak_depth = 0  # guarded-by: _cond

    # -- submit side ----------------------------------------------------
    def submit(self, payload: Any, *, group: Any = None,
               seq_len: Optional[int] = None,
               timeout_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> InferenceRequest:
        """Enqueue one request; raises :class:`ServerBusy` when the
        bounded queue is full (explicit rejection, never unbounded
        growth).  ``trace_id`` (obs) rides the request through
        assembly into the runner's phase spans."""
        now = self._clock()
        req = InferenceRequest(
            payload, group=group, seq_len=seq_len, t_submit=now,
            deadline=None if timeout_s is None else now + timeout_s,
            trace_id=trace_id)
        with self._cond:
            if self._closed:
                raise WorkerLost(
                    "serving: batcher is closed (worker shut down or "
                    "lost) — resubmit elsewhere")
            if len(self._queue) >= self.max_queue:
                raise ServerBusy(
                    f"serving: queue full ({self.max_queue} waiting); "
                    f"retry with backoff")
            self._queue.append(req)
            self._note_depth_locked()
            self._cond.notify()
        return req

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def peak_depth(self) -> int:
        """Locked snapshot — the raw attr races the submit path
        (mxrace guarded-by-violation when read bare)."""
        with self._cond:
            return self._peak_depth

    def _note_depth_locked(self) -> None:
        d = len(self._queue)
        if d > self._peak_depth:
            self._peak_depth = d
        if self._on_depth is not None:
            self._on_depth(d)

    # -- policy (pure, clock-injected) ----------------------------------
    def _expire_locked(self, now: float) -> None:
        expired = [r for r in self._queue
                   if r.deadline is not None and now > r.deadline]
        if not expired:
            return
        self._queue = [r for r in self._queue if r not in expired]
        self._note_depth_locked()
        # stat BEFORE the event-set wakes any result() waiter: a
        # caller observing its RequestTimeout must already find the
        # timeout counted in stats() (mxrace-exposed ordering race)
        if self._on_timeout is not None:
            self._on_timeout(len(expired))
        for r in expired:
            r._fail(RequestTimeout(
                "serving: deadline expired while queued"), now)

    def _poll_locked(self, now: float) -> Optional[Batch]:
        self._expire_locked(now)
        if not self._queue:
            return None
        head = self._queue[0]
        group = [r for r in self._queue if r.group == head.group]
        full = len(group) >= self.max_batch_size
        overdue = (now - head.t_submit) * 1e6 >= self.max_queue_delay_us
        if not (full or overdue):
            return None
        take = group[:self.max_batch_size]
        taken = set(map(id, take))
        self._queue = [r for r in self._queue if id(r) not in taken]
        self._note_depth_locked()
        for r in take:
            r.t_dequeue = now
        # register in-flight (reaping completed ones keeps it bounded)
        self._inflight = [r for r in self._inflight if not r.done()]
        self._inflight.extend(take)
        return Batch(take, head.group)

    def requeue(self, requests: List[InferenceRequest],
                now: Optional[float] = None) -> int:
        """Return the not-yet-done requests of a FAILED batch execution
        to the queue — each request re-enters AT MOST ONCE, with its
        original deadline and ``t_submit`` (so ``queue_us`` accounting
        stays honest: it spans submit → final dequeue).  A request
        whose deadline already passed expires as :class:`RequestTimeout`
        (it must not loop); one that already burned its requeue — or
        arriving after close — fails as :class:`WorkerLost` so the
        fleet layer can retry it on another worker.  Returns the number
        actually requeued."""
        now = self._clock() if now is None else now
        requeued: List[InferenceRequest] = []
        expired: List[InferenceRequest] = []
        lost: List[InferenceRequest] = []
        with self._cond:
            processed = set(map(id, requests))
            self._inflight = [r for r in self._inflight
                              if id(r) not in processed]
            for r in requests:
                if r.done():
                    continue
                if r.deadline is not None and now > r.deadline:
                    expired.append(r)
                elif r.requeues >= 1 or self._closed:
                    lost.append(r)
                else:
                    r.requeues += 1
                    r.t_dequeue = None
                    requeued.append(r)
            # stat BEFORE the event-set wakes any result() waiter —
            # same ordering contract as _expire_locked
            if expired and self._on_timeout is not None:
                self._on_timeout(len(expired))
            for r in expired:
                r._fail(RequestTimeout(
                    "serving: deadline expired before the failed "
                    "batch could requeue"), now)
            for r in lost:
                r._fail(_lost_for(r, WorkerLost(
                    "serving: batch execution failed "
                    + ("again after a requeue"
                       if r.requeues else "and the batcher is "
                       "closed"))), now)
            if requeued:
                # back to the FRONT: they were the oldest waiters and
                # FIFO head priority is what bounds tail latency
                self._queue[0:0] = requeued
                self._note_depth_locked()
                self._cond.notify_all()
        if requeued and profiler.is_active():
            for r in requeued:
                if r.trace_id is not None:
                    _trace.span(_trace.SPAN_REQUEUE, now * 1e6, 0.0,
                                trace_id=r.trace_id,
                                requeues=r.requeues)
        return len(requeued)

    def oldest_waiting_age(self, now: Optional[float] = None
                           ) -> Optional[float]:
        """Age of the oldest QUEUED request — the queue-wedge liveness
        signal: on a healthy worker this stays under the assembly
        delay, on a wedged one it grows without bound."""
        with self._cond:
            if not self._queue:
                return None
            return (self._clock() if now is None else now) \
                - self._queue[0].t_submit

    def poll(self, now: Optional[float] = None) -> Optional[Batch]:
        """Non-blocking assembly decision at time ``now`` (defaults to
        the injected clock).  Returns a Batch when the flush rule fires,
        else None.  This is the whole policy — tests call it directly
        with a hand-stepped clock."""
        with self._cond:
            return self._poll_locked(
                self._clock() if now is None else now)

    def _next_event_locked(self, now: float) -> Optional[float]:
        """Seconds until the next time-driven state change (flush of
        the current head, or earliest deadline) — how long a worker may
        sleep without missing a flush."""
        if not self._queue:
            return None
        head = self._queue[0]
        wake = head.t_submit + self.max_queue_delay_us / 1e6
        for r in self._queue:
            if r.deadline is not None and r.deadline < wake:
                wake = r.deadline
        return max(0.0, wake - now)

    # -- thread side (server workers) -----------------------------------
    def wait_next(self, timeout: Optional[float] = None
                  ) -> Optional[Batch]:
        """Block until a batch is ready (or ``timeout``).  Used by
        server worker threads; the policy itself stays in ``poll``."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                now = self._clock()
                if self._closed:
                    return None
                batch = self._poll_locked(now)
                if batch is not None:
                    return batch
                wait = self._next_event_locked(now)
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None \
                        else min(wait, remaining)
                # a flush can only become due by time passing or a new
                # submit — both bounded by `wait` (None = submit only)
                self._cond.wait(wait if wait is None or wait > 0
                                else 1e-4)

    def close(self, error: Optional[BaseException] = None) -> None:
        """Fail everything still queued AND still in flight with a
        terminal-for-this-worker :class:`WorkerLost` (retriable
        elsewhere), and wake all waiters.  Nothing may be left blocked
        in ``result()`` after a worker dies — this is the ISSUE 7
        no-hung-waiters contract.  ``error`` overrides the default
        WorkerLost (e.g. the router passes the death reason)."""
        with self._cond:
            self._closed = True
            now = self._clock()
            err = error if error is not None else WorkerLost(
                "serving: batcher closed — worker lost before the "
                "request completed")
            for r in self._queue:
                r._fail(_lost_for(r, err), now)
            self._queue.clear()
            for r in self._inflight:
                if not r.done():
                    r._fail(_lost_for(r, err), now)
            self._inflight = []
            self._note_depth_locked()
            self._cond.notify_all()
