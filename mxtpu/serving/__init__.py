"""mxtpu.serving — dynamic-batching TPU inference serving (ISSUE 4),
the fault-tolerant serving fleet (ISSUE 7), and the fleet control
plane (ISSUE 11: autoscaling, predictive admission, priority classes).

The TPU-native equivalent of the reference's C predict API +
``BucketingModule`` deployment story (SURVEY.md §3), grown into a
serving layer:

- :class:`ModelRunner` (runner.py): loads ``export``/``save_checkpoint``
  artifacts, AOT-compiles one donated-buffer XLA executable per
  (batch, seq) shape bucket; weights upload once and are shared by
  every bucket (``MXPredReshape``† zero-copy contract).
- :class:`DynamicBatcher` (batcher.py): bounded queue,
  ``max_batch_size``/``max_queue_delay_us`` assembly, per-request
  deadlines, :class:`ServerBusy` backpressure — policy is pure and
  clock-injected (deterministically testable).
- :class:`InferenceServer` (server.py): name→version→runner registry,
  worker threads per model, round-robin across device replicas.
- :class:`ServingStats` (stats.py): rolling p50/p95/p99, queue depth,
  batch fill-rate, req/sec; Speedometer-style log line; chrome-trace
  spans via ``mxtpu.profiler``.
- :class:`GenerateRunner` / :class:`GenerateBatcher` (generate.py,
  ISSUE 19): KV-cache incremental decode — AOT-compiled prefill
  executables per (batch, prompt-bucket) plus ONE decode-step
  executable over a preallocated slot-paged KV cache, continuous
  batching (join/evict at step boundaries), token streaming, and
  deterministic seeded sampling keyed by absolute position (identical
  across runs AND across a replay-on-steal).
- :class:`FleetRouter` / :class:`FleetWorker` (router.py): front-end
  router over N workers — canary health checks driving the
  :class:`WorkerHealth` state machine (health.py), retry with capped
  exponential backoff + hedging, preemption-safe draining with
  compiled-ladder handoff, and requeue-never-drop on worker death.
- :mod:`faults` (faults.py): deterministic scripted fault injection
  (hang, slow-start, crash-at-k, corruption, queue wedge, slow-exec)
  for tier-1 recovery-path tests.
- :mod:`controlplane` (controlplane.py): :class:`Autoscaler` (replica
  scaling from queue depth + ``queue_eta_us`` with hysteresis,
  cooldown, drain-based scale-down and warm-handoff scale-up) and
  :class:`PriorityClass` (weighted-round-robin dispatch shares +
  per-class quotas consumed by ``FleetRouter``'s admission control).

Error taxonomy: :class:`RetriableError` is the base; ``ServerBusy``
and ``WorkerLost`` are retriable, ``RequestTimeout`` is terminal
(``retriable`` attribute says which).

Knobs (also README "Serving" / "Serving fleet"):
``MXTPU_SERVING_*`` and ``MXTPU_FLEET_*``.
"""
from .batcher import (Batch, DynamicBatcher, InferenceRequest,
                      RequestTimeout, RetriableError, ServerBusy,
                      WorkerLost)
from .controlplane import Autoscaler, PriorityClass, parse_classes
from .faults import (CorruptEntry, CrashAt, Corrupt, Fault, FaultPlan,
                     Hang, QueueWedge, ReadOnlyDir, SlowExec,
                     SlowStart, SlowStartError, StaleKey,
                     TruncateEntry, WorkerCrashed)
from .generate import (GenerateBatcher, GenerateRequest,
                       GenerateRunner, sample_token)
from .health import WorkerHealth, WorkerState
from .router import (FleetGenerateRequest, FleetRequest, FleetRouter,
                     FleetWorker)
from .runner import ModelRunner, batch_ladder
from .server import InferenceServer
from .stats import ServingStats

__all__ = ["ModelRunner", "InferenceServer", "DynamicBatcher",
           "ServingStats", "InferenceRequest", "Batch", "ServerBusy",
           "RequestTimeout", "RetriableError", "WorkerLost",
           "batch_ladder",
           "GenerateRunner", "GenerateBatcher", "GenerateRequest",
           "sample_token",
           "FleetRouter", "FleetWorker", "FleetRequest",
           "FleetGenerateRequest",
           "WorkerHealth", "WorkerState",
           "Autoscaler", "PriorityClass", "parse_classes",
           "Fault", "FaultPlan", "Hang", "SlowStart", "CrashAt",
           "Corrupt", "QueueWedge", "WorkerCrashed", "SlowStartError",
           "SlowExec", "CorruptEntry", "TruncateEntry", "StaleKey",
           "ReadOnlyDir"]
