"""mxtpu.serving — dynamic-batching TPU inference serving (ISSUE 4).

The TPU-native equivalent of the reference's C predict API +
``BucketingModule`` deployment story (SURVEY.md §3), grown into a
serving layer:

- :class:`ModelRunner` (runner.py): loads ``export``/``save_checkpoint``
  artifacts, AOT-compiles one donated-buffer XLA executable per
  (batch, seq) shape bucket; weights upload once and are shared by
  every bucket (``MXPredReshape``† zero-copy contract).
- :class:`DynamicBatcher` (batcher.py): bounded queue,
  ``max_batch_size``/``max_queue_delay_us`` assembly, per-request
  deadlines, :class:`ServerBusy` backpressure — policy is pure and
  clock-injected (deterministically testable).
- :class:`InferenceServer` (server.py): name→version→runner registry,
  worker threads per model, round-robin across device replicas.
- :class:`ServingStats` (stats.py): rolling p50/p95/p99, queue depth,
  batch fill-rate, req/sec; Speedometer-style log line; chrome-trace
  spans via ``mxtpu.profiler``.

Knobs (also README "Serving"): ``MXTPU_SERVING_MAX_BATCH``,
``MXTPU_SERVING_MAX_DELAY_US``, ``MXTPU_SERVING_MAX_QUEUE``,
``MXTPU_SERVING_DONATE``.
"""
from .batcher import (Batch, DynamicBatcher, InferenceRequest,
                      RequestTimeout, ServerBusy)
from .runner import ModelRunner, batch_ladder
from .server import InferenceServer
from .stats import ServingStats

__all__ = ["ModelRunner", "InferenceServer", "DynamicBatcher",
           "ServingStats", "InferenceRequest", "Batch", "ServerBusy",
           "RequestTimeout", "batch_ladder"]
