"""Per-worker health state machine for the serving fleet (ISSUE 7).

Pure and clock-free: every method takes ``now`` explicitly, so the
router drives one instance per worker from its tick loop and the tests
drive it from a hand-stepped clock.  The machine is deliberately
small — five states, a handful of signals — and every transition is
recorded with its reason so ``stats()`` can explain *why* a worker
stopped taking traffic.

States (README "Fleet & fault tolerance" has the diagram)::

    HEALTHY ──fail──▶ SUSPECT ──fail×dead_after──▶ DEAD
       ▲                │ ok                         │ recover()
       └────────────────┘                            ▼
    RECOVERING ◀──────────── canary ok ───────── (replacement)
       │
       └─▶ HEALTHY            any ──drain()──▶ DRAINING ──▶ DEAD
                                                      (retired)

Signals and their sources:

* ``canary_ok`` / ``canary_fail`` — the router's periodic canary
  inference (result compared against the expected output, so silent
  corruption is a canary *failure*).  Canary verdicts are
  authoritative: they are the only signal that recovers a SUSPECT
  worker or kills one outright (``dead_after`` consecutive failures).
* ``exec_ok`` / ``exec_fail`` — real batch outcomes.  A failed batch
  makes a HEALTHY worker SUSPECT (stop routing new traffic there);
  a successful batch recovers it only when canaries are disabled
  (``exec_recovers=True``) — with canaries on, recovery waits for a
  verified canary so a worker returning corrupt-but-no-exception
  results cannot launder itself back to HEALTHY.
* ``liveness`` — dispatched-batch / queued-request age, checked by the
  router each tick: past ``liveness_s`` the worker is SUSPECT (hang or
  queue wedge), past ``2 * liveness_s`` it is DEAD and the router
  steals its outstanding requests.
* ``crashed`` — the worker raised :class:`~.faults.WorkerCrashed` (or
  the operator killed it): DEAD immediately.
* ``drain`` / ``drained`` — preemption-safe retirement: DRAINING stops
  new admissions but keeps executing; ``drained`` marks the flush
  complete (``retired=True`` distinguishes a graceful exit from a
  death in the fleet stats).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

__all__ = ["WorkerState", "WorkerHealth"]


class WorkerState:
    """String-valued worker states (str constants, not enum, so they
    serialize straight into ``stats()`` snapshots)."""
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    DRAINING = "draining"
    DEAD = "dead"
    RECOVERING = "recovering"

    ALL = (HEALTHY, SUSPECT, DRAINING, DEAD, RECOVERING)


class WorkerHealth:
    """One worker's state machine.  Not thread-safe by itself — the
    router mutates it only under its own lock (or single-threaded in
    deterministic tests)."""

    def __init__(self, name: str = "", *, liveness_s: float = 2.0,
                 dead_after: int = 3, start_recovering: bool = False,
                 exec_recovers: bool = False,
                 on_transition: Optional[
                     Callable[[float, str, str, str], None]] = None):
        self.name = name
        self.liveness_s = float(liveness_s)
        self.dead_after = int(dead_after)
        self.exec_recovers = bool(exec_recovers)
        self.state = WorkerState.RECOVERING if start_recovering \
            else WorkerState.HEALTHY
        self.retired = False          # True only via drain()+drained()
        self.failures = 0             # consecutive, reset by canary_ok
        self.reason = "start-recovering" if start_recovering else ""
        # bounded transition log: (now, from, to, reason)
        self.transitions: List[Tuple[float, str, str, str]] = []
        # observer hook (obs flight recorder): called after every real
        # transition with (now, from, to, reason); must be cheap and
        # must not call back into this machine
        self._on_transition = on_transition

    # -- transition core -------------------------------------------------
    def _to(self, now: float, state: str, reason: str) -> bool:
        if self.state == state:
            return False
        if self.state == WorkerState.DEAD and \
                state != WorkerState.RECOVERING:
            return False              # dead is terminal (bar recover())
        prev = self.state
        self.transitions.append((now, prev, state, reason))
        del self.transitions[:-32]
        self.state = state
        self.reason = reason
        if self._on_transition is not None:
            self._on_transition(now, prev, state, reason)
        return True

    # -- canary verdicts (authoritative) ---------------------------------
    def canary_ok(self, now: float) -> None:
        self.failures = 0
        if self.state in (WorkerState.SUSPECT, WorkerState.RECOVERING):
            self._to(now, WorkerState.HEALTHY, "canary ok")

    def canary_fail(self, now: float, reason: str = "canary") -> None:
        self.failures += 1
        if self.state == WorkerState.HEALTHY:
            self._to(now, WorkerState.SUSPECT, f"{reason} failed")
        elif self.state == WorkerState.SUSPECT and \
                self.failures >= self.dead_after:
            self._to(now, WorkerState.DEAD,
                     f"{self.failures} consecutive {reason} failures")
        # RECOVERING absorbs canary failures: a slow-starting worker is
        # expected to fail canaries until it warms up.

    # -- batch execution outcomes ----------------------------------------
    def exec_ok(self, now: float) -> None:
        if self.exec_recovers:        # canaries disabled: a real batch
            self.canary_ok(now)       # is the best health probe we have

    def exec_fail(self, now: float) -> None:
        if self.state == WorkerState.HEALTHY:
            self._to(now, WorkerState.SUSPECT, "batch execution failed")
        elif self.exec_recovers:      # canaries off: failures also
            self.canary_fail(now, "execution")    # count toward DEAD

    # -- liveness (hang / queue wedge), checked every tick ---------------
    def liveness(self, now: float, inflight_age: Optional[float],
                 queued_age: Optional[float]) -> None:
        """``inflight_age`` — oldest dispatched-but-unfinished batch;
        ``queued_age`` — oldest request sitting in the queue.  SUSPECT
        past ``liveness_s``, DEAD past ``2 * liveness_s`` (a DRAINING
        worker is subject too, so a drain can never hang forever)."""
        if self.state == WorkerState.DEAD:
            return
        for age, kind in ((inflight_age, "hang"),
                          (queued_age, "queue wedge")):
            if age is None:
                continue
            if age > 2 * self.liveness_s:
                self._to(now, WorkerState.DEAD,
                         f"{kind}: outstanding for {age:.3f}s "
                         f"(> 2x liveness {self.liveness_s}s)")
                return
            if age > self.liveness_s and \
                    self.state in (WorkerState.HEALTHY,
                                   WorkerState.RECOVERING):
                self._to(now, WorkerState.SUSPECT,
                         f"{kind}: outstanding for {age:.3f}s")

    # -- terminal events --------------------------------------------------
    def crashed(self, now: float, reason: str = "crashed") -> None:
        self._to(now, WorkerState.DEAD, reason)

    def drain(self, now: float, reason: str = "drain requested") -> None:
        if self.state != WorkerState.DEAD:
            self._to(now, WorkerState.DRAINING, reason)

    def drained(self, now: float) -> None:
        if self.state == WorkerState.DRAINING:
            self.retired = True
            self._to(now, WorkerState.DEAD, "drained (retired)")

    def recover(self, now: float, reason: str = "restarting") -> None:
        """DEAD → RECOVERING: a restarted/replacement worker must pass
        a canary before it takes traffic again."""
        if self.state == WorkerState.DEAD:
            self.retired = False
            self.failures = 0
            self._to(now, WorkerState.RECOVERING, reason)

    # -- routing predicates ----------------------------------------------
    def admits(self) -> bool:
        """May NEW client traffic be routed here?  Only HEALTHY —
        SUSPECT stops taking new work (that is the point of the state),
        DRAINING/DEAD/RECOVERING obviously not."""
        return self.state == WorkerState.HEALTHY

    def admits_canary(self) -> bool:
        """Canaries keep probing SUSPECT (recovery path) and
        RECOVERING (warmup path) workers."""
        return self.state in (WorkerState.HEALTHY, WorkerState.SUSPECT,
                              WorkerState.RECOVERING)

    def snapshot(self) -> dict:
        return {"state": self.state, "reason": self.reason,
                "failures": self.failures, "retired": self.retired,
                "transitions": len(self.transitions)}
