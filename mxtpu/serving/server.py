"""InferenceServer — multi-model serving front end (ISSUE 4 tentpole
item 3).

A name → version → :class:`ModelRunner` registry; each registered
(model, version) endpoint owns one :class:`DynamicBatcher`, one
:class:`ServingStats`, and a pool of worker threads that assemble
micro-batches and dispatch them ROUND-ROBIN across the endpoint's
data-parallel device replicas (one ModelRunner per device — weights
are uploaded once per replica, buckets share them, see runner.py).

Every executed batch emits a chrome-trace span through
``mxtpu.profiler.record_span`` (cat ``serving``) so serving traffic
lines up with training ops in trace dumps, and feeds the endpoint's
Speedometer-style periodic log line.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..base import MXNetError
from .. import knobs
from .. import obs
from .. import profiler
from .batcher import DynamicBatcher, InferenceRequest
from .runner import ModelRunner
from .stats import ServingStats

__all__ = ["InferenceServer"]


class _Endpoint:
    """One (model, version): runners + batcher + stats + workers."""

    def __init__(self, name: str, version: int,
                 runners: List[ModelRunner],
                 max_queue_delay_us: float, max_queue: Optional[int],
                 log_every_s: float):
        self.name = name
        self.version = version
        self.runners = runners
        r0 = runners[0]
        for r in runners[1:]:
            if r.max_batch_size != r0.max_batch_size or \
                    r.seq_buckets != r0.seq_buckets:
                raise MXNetError(
                    "serving: replica runners must share the bucket "
                    "ladder (max_batch_size/seq_buckets)")
        self.stats = ServingStats(name=f"{name}:v{version}",
                                  log_every_s=log_every_s)
        self.batcher = DynamicBatcher(
            max_batch_size=r0.max_batch_size,
            max_queue_delay_us=max_queue_delay_us,
            max_queue=max_queue,
            on_timeout=self.stats.record_timeout,
            on_depth=self.stats.record_queue_depth)
        self._rr_lock = threading.Lock()
        self._rr = 0  # guarded-by: _rr_lock
        # per-replica dispatch tally  # guarded-by: _rr_lock
        self.dispatched: Dict[int, int] = {i: 0
                                           for i in range(len(runners))}
        self._stop = threading.Event()
        self.threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"mxtpu-serve-{name}-v{version}-{i}")
            for i in range(len(runners))]

    def start(self) -> None:
        for t in self.threads:
            t.start()

    def _next_runner(self) -> int:
        with self._rr_lock:
            i = self._rr % len(self.runners)
            self._rr += 1
            self.dispatched[i] += 1
            return i

    def dispatch_counts(self) -> Dict[int, int]:
        """Locked snapshot of the per-replica dispatch tally.  stats()
        used to read ``dispatched`` bare, racing the workers'
        ``_next_runner`` increments (mxlint lock-discipline finding —
        a torn read under concurrent dict mutation)."""
        with self._rr_lock:
            return dict(self.dispatched)

    def _work(self) -> None:
        while not self._stop.is_set():
            batch = self.batcher.wait_next(timeout=0.1)
            if batch is None:
                continue
            idx = self._next_runner()
            runner = self.runners[idx]
            t0 = profiler._now_us()
            try:
                bucket, _ = runner.run_requests(batch.requests)
            except Exception:  # noqa: BLE001 — requeue the batch,
                # never kill the worker.  Each request re-enters the
                # queue exactly once (deadline intact); a second
                # failure — or an expired deadline — fails it there.
                n = self.batcher.requeue(batch.requests)
                if n:
                    self.stats.bump("requeues", n)
                continue
            dur = profiler._now_us() - t0
            tids = [r.trace_id for r in batch.requests
                    if r.trace_id is not None]
            profiler.record_span(
                f"serve/{self.name}:v{self.version}", t0, dur,
                cat="serving",
                args={"batch": len(batch.requests),
                      "bucket": list(bucket), "replica": idx,
                      "trace_ids": tids})
            self.stats.record_batch(len(batch.requests), bucket[0])
            for r in batch.requests:
                if r.latency_us is not None:
                    self.stats.record_completion(
                        r.latency_us, r.queue_us or 0.0)
            self.stats.maybe_log()

    def stop(self) -> None:
        # Order matters (ISSUE 7 no-hung-waiters fix): signal the
        # workers first and let them FINISH their current batch (those
        # results are real), THEN close the batcher — which fails
        # everything still queued and anything a stuck worker left in
        # flight with WorkerLost, so no caller blocks in result()
        # forever on a dead endpoint.
        self._stop.set()
        for t in self.threads:
            t.join(timeout=2.0)
        self.batcher.close()


class InferenceServer:
    """Multi-model dynamic-batching front end.

    >>> server = InferenceServer()
    >>> server.register("bert", runner)           # version 1
    >>> out = server.infer("bert", {"data": toks}, seq_len=40)
    >>> server.stats("bert")["latency_ms"]["p99"]
    """

    def __init__(self, log_every_s: float = 10.0):
        self._endpoints: Dict[str, Dict[int, _Endpoint]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._log_every_s = log_every_s
        self._closed = False          # guarded-by: _lock

    # -- registry ---------------------------------------------------------
    def register(self, name: str,
                 runners: Union[ModelRunner, Sequence[ModelRunner]],
                 version: int = 1,
                 max_queue_delay_us: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 warmup: bool = False) -> None:
        """Attach a model version.  ``runners`` may be a single
        ModelRunner or one per device replica (round-robin dispatch).
        ``warmup=True`` pre-compiles every replica's bucket ladder
        before the endpoint accepts traffic."""
        if isinstance(runners, ModelRunner):
            runners = [runners]
        runners = list(runners)
        if not runners:
            raise MXNetError("serving: register needs >= 1 runner")
        if max_queue_delay_us is None:
            max_queue_delay_us = knobs.get("MXTPU_SERVING_MAX_DELAY_US")
        if max_queue is None:
            mq = knobs.get("MXTPU_SERVING_MAX_QUEUE")
            if mq:  # 0 = unbounded (knob unset)
                max_queue = mq
        if warmup:
            for r in runners:
                r.warmup()
        ep = _Endpoint(name, version, runners, max_queue_delay_us,
                       max_queue, self._log_every_s)
        with self._lock:
            if self._closed:
                raise MXNetError("serving: server is closed")
            if version in self._endpoints.get(name, {}):
                raise MXNetError(
                    f"serving: {name!r} v{version} already registered")
            self._endpoints.setdefault(name, {})[version] = ep
        ep.start()

    def unregister(self, name: str,
                   version: Optional[int] = None) -> None:
        with self._lock:
            versions = self._endpoints.get(name)
            if not versions:
                raise MXNetError(f"serving: unknown model {name!r}")
            drop = list(versions) if version is None else [version]
            eps = []
            for v in drop:
                if v not in versions:
                    raise MXNetError(
                        f"serving: {name!r} has no version {v}")
                eps.append(versions.pop(v))
            if not versions:
                del self._endpoints[name]
        for ep in eps:
            ep.stop()

    def models(self) -> Dict[str, List[int]]:
        with self._lock:
            return {n: sorted(vs) for n, vs in self._endpoints.items()}

    def _endpoint(self, name: str,
                  version: Optional[int]) -> _Endpoint:
        with self._lock:
            versions = self._endpoints.get(name)
            if not versions:
                raise MXNetError(f"serving: unknown model {name!r}")
            if version is None:
                version = max(versions)   # latest by default
            ep = versions.get(version)
            if ep is None:
                raise MXNetError(
                    f"serving: {name!r} has no version {version} "
                    f"(have {sorted(versions)})")
            return ep

    # -- request path -----------------------------------------------------
    def submit(self, name: str, inputs: Dict[str, np.ndarray],
               seq_len: Optional[int] = None,
               version: Optional[int] = None,
               timeout_s: Optional[float] = None) -> InferenceRequest:
        """Async single-example submit: ``inputs`` are ONE example (no
        batch axis).  Returns a future; raises ServerBusy under
        backpressure.  ``timeout_s`` is the request deadline — expiry
        yields RequestTimeout, never a stale result."""
        with self._lock:
            if self._closed:
                raise MXNetError("serving: server is closed")
        ep = self._endpoint(name, version)
        r0 = ep.runners[0]
        if seq_len is None and r0.seq_buckets is not None:
            first = np.asarray(inputs[next(iter(r0._input_specs))])
            seq_len = int(first.shape[0])
        group = r0.seq_bucket_for(seq_len)
        try:
            return ep.batcher.submit(
                inputs, group=group, seq_len=seq_len,
                timeout_s=timeout_s,
                trace_id=obs.new_trace_id()
                if profiler.is_active() else None)
        except Exception:
            ep.stats.record_rejected()
            raise

    def infer(self, name: str, inputs: Dict[str, np.ndarray],
              seq_len: Optional[int] = None,
              version: Optional[int] = None,
              timeout_s: Optional[float] = None) -> List[np.ndarray]:
        """Blocking convenience wrapper over ``submit``."""
        req = self.submit(name, inputs, seq_len=seq_len,
                          version=version, timeout_s=timeout_s)
        # +grace so the batcher's own deadline machinery (not the
        # caller-side wait) decides timeout in the normal case
        return req.result(timeout=None if timeout_s is None
                          else timeout_s + 5.0)

    # -- observability ----------------------------------------------------
    def stats(self, name: Optional[str] = None,
              version: Optional[int] = None) -> Dict:
        """Stats snapshot: one endpoint when ``name`` is given, else
        ``{name: {version: snapshot}}`` for the whole registry."""
        if name is not None:
            ep = self._endpoint(name, version)
            snap = ep.stats.snapshot()
            snap["replicas"] = len(ep.runners)
            snap["dispatched_per_replica"] = ep.dispatch_counts()
            snap["compiled_buckets"] = [r.num_compiled()
                                        for r in ep.runners]
            return snap
        with self._lock:
            items = [(n, v) for n, vs in self._endpoints.items()
                     for v in vs]
        return {f"{n}:v{v}": self.stats(n, v) for n, v in items}

    def close(self) -> None:
        """Stop every endpoint's workers and fail anything still
        queued.  The registry stays readable: workers record a batch's
        stats AFTER delivering its results, so a snapshot taken while
        clients are unblocking can run ahead of the tally — ``stats()``
        after ``close()`` (which joins the workers) is the consistent
        final reading."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            eps = [ep for vs in self._endpoints.values()
                   for ep in vs.values()]
        for ep in eps:
            ep.stop()

    def __enter__(self) -> "InferenceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
