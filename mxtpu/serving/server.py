"""InferenceServer — multi-model serving front end (ISSUE 4 tentpole
item 3).

A name → version → :class:`ModelRunner` registry; each registered
(model, version) endpoint owns one :class:`DynamicBatcher`, one
:class:`ServingStats`, and a pool of worker threads that assemble
micro-batches and dispatch them ROUND-ROBIN across the endpoint's
data-parallel device replicas (one ModelRunner per device — weights
are uploaded once per replica, buckets share them, see runner.py).

Every executed batch emits a chrome-trace span through
``mxtpu.profiler.record_span`` (cat ``serving``) so serving traffic
lines up with training ops in trace dumps, and feeds the endpoint's
Speedometer-style periodic log line.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..base import MXNetError
from .. import knobs
from .. import obs
from .. import profiler
from .batcher import DynamicBatcher, InferenceRequest
from .generate import GenerateBatcher, GenerateRequest, GenerateRunner
from .runner import ModelRunner
from .stats import ServingStats

__all__ = ["InferenceServer"]


class _GenEndpoint:
    """One (model, version) GENERATION endpoint (ISSUE 19): a
    :class:`GenerateRunner` + one continuous-batching
    :class:`GenerateBatcher` + a stepping thread that advances the
    whole lane table one fused decode step at a time.  Requests join
    at step boundaries and stream tokens through their ``on_token``
    callbacks."""

    def __init__(self, name: str, version: int,
                 runner: GenerateRunner, max_queue: Optional[int],
                 log_every_s: float):
        self.name = name
        self.version = version
        self.runner = runner
        self.stats = ServingStats(name=f"{name}:v{version}:gen",
                                  log_every_s=log_every_s)
        self.batcher = GenerateBatcher(
            runner, max_queue=max_queue, stats=self.stats,
            on_timeout=self.stats.record_timeout)
        self._stop = threading.Event()
        self.thread = threading.Thread(
            target=self._work, daemon=True,
            name=f"mxtpu-gen-{name}-v{version}")

    def start(self) -> None:
        self.thread.start()

    def _work(self) -> None:
        while not self._stop.is_set():
            if self.batcher.drain():
                # idle: no lanes, no queue — park briefly
                self._stop.wait(0.005)
                continue
            t0 = profiler._now_us()
            try:
                out = self.batcher.step()
            except Exception:  # noqa: BLE001 — a failed decode step
                # leaves every lane's state intact; back off and retry
                # (a persistent failure surfaces as caller deadlines)
                self.stats.bump("step_failures")
                self._stop.wait(0.01)
                continue
            if out["emitted"] and profiler.is_active():
                profiler.record_span(
                    f"serve/{self.name}:v{self.version}:gen", t0,
                    profiler._now_us() - t0, cat="serving",
                    args={"lanes": out["active"],
                          "admitted": out["admitted"],
                          "tokens": out["emitted"]})
            self.stats.maybe_log()

    def stop(self) -> None:
        # same wind-down order as _Endpoint: let the stepping thread
        # finish its current step (those tokens are real), then close
        # the batcher so queued + in-lane callers all unblock
        self._stop.set()
        self.thread.join(timeout=2.0)
        self.batcher.close()


class _Endpoint:
    """One (model, version): runners + batcher + stats + workers."""

    def __init__(self, name: str, version: int,
                 runners: List[ModelRunner],
                 max_queue_delay_us: float, max_queue: Optional[int],
                 log_every_s: float):
        self.name = name
        self.version = version
        self.runners = runners
        r0 = runners[0]
        for r in runners[1:]:
            if r.max_batch_size != r0.max_batch_size or \
                    r.seq_buckets != r0.seq_buckets:
                raise MXNetError(
                    "serving: replica runners must share the bucket "
                    "ladder (max_batch_size/seq_buckets)")
        self.stats = ServingStats(name=f"{name}:v{version}",
                                  log_every_s=log_every_s)
        self.batcher = DynamicBatcher(
            max_batch_size=r0.max_batch_size,
            max_queue_delay_us=max_queue_delay_us,
            max_queue=max_queue,
            on_timeout=self.stats.record_timeout,
            on_depth=self.stats.record_queue_depth)
        self._rr_lock = threading.Lock()
        self._rr = 0  # guarded-by: _rr_lock
        # per-replica dispatch tally  # guarded-by: _rr_lock
        self.dispatched: Dict[int, int] = {i: 0
                                           for i in range(len(runners))}
        self._stop = threading.Event()
        self.threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"mxtpu-serve-{name}-v{version}-{i}")
            for i in range(len(runners))]

    def start(self) -> None:
        for t in self.threads:
            t.start()

    def _next_runner(self) -> int:
        with self._rr_lock:
            i = self._rr % len(self.runners)
            self._rr += 1
            self.dispatched[i] += 1
            return i

    def dispatch_counts(self) -> Dict[int, int]:
        """Locked snapshot of the per-replica dispatch tally.  stats()
        used to read ``dispatched`` bare, racing the workers'
        ``_next_runner`` increments (mxlint lock-discipline finding —
        a torn read under concurrent dict mutation)."""
        with self._rr_lock:
            return dict(self.dispatched)

    def _work(self) -> None:
        while not self._stop.is_set():
            batch = self.batcher.wait_next(timeout=0.1)
            if batch is None:
                continue
            idx = self._next_runner()
            runner = self.runners[idx]
            t0 = profiler._now_us()
            try:
                bucket, _ = runner.run_requests(batch.requests)
            except Exception:  # noqa: BLE001 — requeue the batch,
                # never kill the worker.  Each request re-enters the
                # queue exactly once (deadline intact); a second
                # failure — or an expired deadline — fails it there.
                n = self.batcher.requeue(batch.requests)
                if n:
                    self.stats.bump("requeues", n)
                continue
            dur = profiler._now_us() - t0
            tids = [r.trace_id for r in batch.requests
                    if r.trace_id is not None]
            profiler.record_span(
                f"serve/{self.name}:v{self.version}", t0, dur,
                cat="serving",
                args={"batch": len(batch.requests),
                      "bucket": list(bucket), "replica": idx,
                      "trace_ids": tids})
            self.stats.record_batch(len(batch.requests), bucket[0])
            for r in batch.requests:
                if r.latency_us is not None:
                    self.stats.record_completion(
                        r.latency_us, r.queue_us or 0.0)
            self.stats.maybe_log()

    def stop(self) -> None:
        # Order matters (ISSUE 7 no-hung-waiters fix): signal the
        # workers first and let them FINISH their current batch (those
        # results are real), THEN close the batcher — which fails
        # everything still queued and anything a stuck worker left in
        # flight with WorkerLost, so no caller blocks in result()
        # forever on a dead endpoint.
        self._stop.set()
        for t in self.threads:
            t.join(timeout=2.0)
        self.batcher.close()


class InferenceServer:
    """Multi-model dynamic-batching front end.

    >>> server = InferenceServer()
    >>> server.register("bert", runner)           # version 1
    >>> out = server.infer("bert", {"data": toks}, seq_len=40)
    >>> server.stats("bert")["latency_ms"]["p99"]
    """

    def __init__(self, log_every_s: float = 10.0):
        self._endpoints: Dict[str, Dict[int, _Endpoint]] = {}  # guarded-by: _lock
        # generation endpoints (ISSUE 19), same name→version shape;
        # a model may have both a batch-inference and a generation
        # registration under the same name
        self._gen: Dict[str, Dict[int, _GenEndpoint]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._log_every_s = log_every_s
        self._closed = False          # guarded-by: _lock

    # -- registry ---------------------------------------------------------
    def register(self, name: str,
                 runners: Union[ModelRunner, Sequence[ModelRunner]],
                 version: int = 1,
                 max_queue_delay_us: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 warmup: bool = False) -> None:
        """Attach a model version.  ``runners`` may be a single
        ModelRunner or one per device replica (round-robin dispatch).
        ``warmup=True`` pre-compiles every replica's bucket ladder
        before the endpoint accepts traffic."""
        if isinstance(runners, ModelRunner):
            runners = [runners]
        runners = list(runners)
        if not runners:
            raise MXNetError("serving: register needs >= 1 runner")
        if max_queue_delay_us is None:
            max_queue_delay_us = knobs.get("MXTPU_SERVING_MAX_DELAY_US")
        if max_queue is None:
            mq = knobs.get("MXTPU_SERVING_MAX_QUEUE")
            if mq:  # 0 = unbounded (knob unset)
                max_queue = mq
        if warmup:
            for r in runners:
                r.warmup()
        ep = _Endpoint(name, version, runners, max_queue_delay_us,
                       max_queue, self._log_every_s)
        with self._lock:
            if self._closed:
                raise MXNetError("serving: server is closed")
            if version in self._endpoints.get(name, {}):
                raise MXNetError(
                    f"serving: {name!r} v{version} already registered")
            self._endpoints.setdefault(name, {})[version] = ep
        ep.start()

    def register_generator(self, name: str, runner: GenerateRunner,
                           version: int = 1,
                           max_queue: Optional[int] = None,
                           warmup: bool = False) -> None:
        """Attach a GENERATION endpoint (ISSUE 19): a
        :class:`GenerateRunner` serving streamed incremental decode
        with continuous batching.  ``warmup=True`` pre-compiles the
        prefill ladder + the decode step before traffic (with a
        persistent disk cache this is all loads, zero compiles)."""
        if not isinstance(runner, GenerateRunner):
            raise MXNetError("serving: register_generator needs a "
                             "GenerateRunner")
        if max_queue is None:
            mq = knobs.get("MXTPU_SERVING_MAX_QUEUE")
            if mq:  # 0 = unbounded (knob unset)
                max_queue = mq
        if warmup:
            runner.warmup()
        ep = _GenEndpoint(name, version, runner, max_queue,
                          self._log_every_s)
        with self._lock:
            if self._closed:
                raise MXNetError("serving: server is closed")
            if version in self._gen.get(name, {}):
                raise MXNetError(
                    f"serving: generator {name!r} v{version} already "
                    f"registered")
            self._gen.setdefault(name, {})[version] = ep
        ep.start()

    def unregister(self, name: str,
                   version: Optional[int] = None) -> None:
        with self._lock:
            versions = self._endpoints.get(name)
            gversions = self._gen.get(name)
            if not versions and not gversions:
                raise MXNetError(f"serving: unknown model {name!r}")
            if version is not None and \
                    version not in (versions or {}) and \
                    version not in (gversions or {}):
                raise MXNetError(
                    f"serving: {name!r} has no version {version}")
            eps: List[Any] = []
            for reg, vs in ((self._endpoints, versions),
                            (self._gen, gversions)):
                if not vs:
                    continue
                drop = list(vs) if version is None else \
                    [v for v in (version,) if v in vs]
                for v in drop:
                    eps.append(vs.pop(v))
                if not vs:
                    del reg[name]
        for ep in eps:
            ep.stop()

    def models(self) -> Dict[str, List[int]]:
        with self._lock:
            out = {n: sorted(vs) for n, vs in self._endpoints.items()}
            for n, vs in self._gen.items():
                out[n] = sorted(set(out.get(n, [])) | set(vs))
            return out

    def _endpoint(self, name: str,
                  version: Optional[int]) -> _Endpoint:
        with self._lock:
            versions = self._endpoints.get(name)
            if not versions:
                raise MXNetError(f"serving: unknown model {name!r}")
            if version is None:
                version = max(versions)   # latest by default
            ep = versions.get(version)
            if ep is None:
                raise MXNetError(
                    f"serving: {name!r} has no version {version} "
                    f"(have {sorted(versions)})")
            return ep

    # -- request path -----------------------------------------------------
    def submit(self, name: str, inputs: Dict[str, np.ndarray],
               seq_len: Optional[int] = None,
               version: Optional[int] = None,
               timeout_s: Optional[float] = None) -> InferenceRequest:
        """Async single-example submit: ``inputs`` are ONE example (no
        batch axis).  Returns a future; raises ServerBusy under
        backpressure.  ``timeout_s`` is the request deadline — expiry
        yields RequestTimeout, never a stale result."""
        with self._lock:
            if self._closed:
                raise MXNetError("serving: server is closed")
        ep = self._endpoint(name, version)
        r0 = ep.runners[0]
        if seq_len is None and r0.seq_buckets is not None:
            first = np.asarray(inputs[next(iter(r0._input_specs))])
            seq_len = int(first.shape[0])
        group = r0.seq_bucket_for(seq_len)
        try:
            return ep.batcher.submit(
                inputs, group=group, seq_len=seq_len,
                timeout_s=timeout_s,
                trace_id=obs.new_trace_id()
                if profiler.is_active() else None)
        except Exception:
            ep.stats.record_rejected()
            raise

    def infer(self, name: str, inputs: Dict[str, np.ndarray],
              seq_len: Optional[int] = None,
              version: Optional[int] = None,
              timeout_s: Optional[float] = None) -> List[np.ndarray]:
        """Blocking convenience wrapper over ``submit``."""
        req = self.submit(name, inputs, seq_len=seq_len,
                          version=version, timeout_s=timeout_s)
        # +grace so the batcher's own deadline machinery (not the
        # caller-side wait) decides timeout in the normal case
        return req.result(timeout=None if timeout_s is None
                          else timeout_s + 5.0)

    def _gen_endpoint(self, name: str,
                      version: Optional[int]) -> _GenEndpoint:
        with self._lock:
            versions = self._gen.get(name)
            if not versions:
                raise MXNetError(
                    f"serving: no generator registered for {name!r}")
            if version is None:
                version = max(versions)   # latest by default
            ep = versions.get(version)
            if ep is None:
                raise MXNetError(
                    f"serving: generator {name!r} has no version "
                    f"{version} (have {sorted(versions)})")
            return ep

    def submit_generate(self, name: str, prompt: Sequence[int], *,
                        max_tokens: Optional[int] = None,
                        eos_id: Optional[int] = None,
                        top_k: int = 1, seed: int = 0,
                        version: Optional[int] = None,
                        timeout_s: Optional[float] = None,
                        on_token=None) -> GenerateRequest:
        """Async streamed generation (ISSUE 19): the request joins the
        endpoint's continuous batch at the next step boundary;
        ``on_token(token, index)`` fires per decoded token.  Returns a
        future whose result is the full generated token list."""
        with self._lock:
            if self._closed:
                raise MXNetError("serving: server is closed")
        ep = self._gen_endpoint(name, version)
        try:
            return ep.batcher.submit(
                prompt, max_tokens=max_tokens, eos_id=eos_id,
                top_k=top_k, seed=seed, timeout_s=timeout_s,
                on_token=on_token,
                trace_id=obs.new_trace_id()
                if profiler.is_active() else None)
        except Exception:
            ep.stats.record_rejected()
            raise

    def generate(self, name: str, prompt: Sequence[int], *,
                 max_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None, top_k: int = 1,
                 seed: int = 0, version: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 on_token=None) -> List[int]:
        """Blocking convenience wrapper over ``submit_generate``."""
        req = self.submit_generate(
            name, prompt, max_tokens=max_tokens, eos_id=eos_id,
            top_k=top_k, seed=seed, version=version,
            timeout_s=timeout_s, on_token=on_token)
        return req.result(timeout=None if timeout_s is None
                          else timeout_s + 5.0)

    # -- observability ----------------------------------------------------
    def stats(self, name: Optional[str] = None,
              version: Optional[int] = None) -> Dict:
        """Stats snapshot: one endpoint when ``name`` is given, else
        ``{name: {version: snapshot}}`` for the whole registry
        (generation endpoints under a ``:gen`` suffix)."""
        if name is not None:
            with self._lock:
                has_batch = version in self._endpoints.get(name, {}) \
                    if version is not None \
                    else bool(self._endpoints.get(name))
            if not has_batch:
                gep = self._gen_endpoint(name, version)
                snap = gep.stats.snapshot()
                snap["lanes"] = gep.runner.max_lanes
                snap["compiled_buckets"] = gep.runner.num_compiled()
                return snap
            ep = self._endpoint(name, version)
            snap = ep.stats.snapshot()
            snap["replicas"] = len(ep.runners)
            snap["dispatched_per_replica"] = ep.dispatch_counts()
            snap["compiled_buckets"] = [r.num_compiled()
                                        for r in ep.runners]
            return snap
        with self._lock:
            items = [(n, v) for n, vs in self._endpoints.items()
                     for v in vs]
            gitems = [(n, v) for n, vs in self._gen.items()
                      for v in vs]
        out = {f"{n}:v{v}": self.stats(n, v) for n, v in items}
        for n, v in gitems:
            gep = self._gen_endpoint(n, v)
            snap = gep.stats.snapshot()
            snap["lanes"] = gep.runner.max_lanes
            snap["compiled_buckets"] = gep.runner.num_compiled()
            out[f"{n}:v{v}:gen"] = snap
        return out

    def close(self) -> None:
        """Stop every endpoint's workers and fail anything still
        queued.  The registry stays readable: workers record a batch's
        stats AFTER delivering its results, so a snapshot taken while
        clients are unblocking can run ahead of the tally — ``stats()``
        after ``close()`` (which joins the workers) is the consistent
        final reading."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            eps = [ep for vs in self._endpoints.values()
                   for ep in vs.values()]
            eps += [ep for vs in self._gen.values()
                    for ep in vs.values()]
        for ep in eps:
            ep.stop()

    def __enter__(self) -> "InferenceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
