"""Legacy model API (reference ``python/mxnet/model.py``†):
checkpoint save/load in the ``prefix-symbol.json`` +
``prefix-%04d.params`` convention, plus the pre-Module ``FeedForward``
facade delegating to ``mxtpu.module.Module``."""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from .base import MXNetError
from . import ndarray as nd_mod
from .ndarray import NDArray

__all__ = ["save_checkpoint", "load_checkpoint", "FeedForward"]


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict,
                    aux_params: Dict) -> None:
    """Write ``prefix-symbol.json`` + ``prefix-{epoch:04d}.params``
    (reference ``save_checkpoint``†)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    arrays = {f"arg:{k}": v for k, v in arg_params.items()}
    arrays.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd_mod.save(f"{prefix}-{epoch:04d}.params", arrays)


def load_checkpoint(prefix: str, epoch: int):
    """Load (symbol, arg_params, aux_params) (reference
    ``load_checkpoint``†)."""
    from . import symbol as sym_mod
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    loaded = nd_mod.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tag, name = k.split(":", 1)
        if tag == "arg":
            arg_params[name] = v
        elif tag == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


class FeedForward:
    """Deprecated pre-Module trainer (reference ``FeedForward``†) —
    a thin facade over ``mxtpu.module.Module`` kept for API parity."""

    def __init__(self, symbol, ctx=None, num_epoch=None,
                 optimizer="sgd", initializer="uniform",
                 arg_params=None, aux_params=None, begin_epoch=0,
                 **kwargs):
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs
        self._mod = None

    def _module(self, data_names=("data",),
                label_names=("softmax_label",)):
        from .module import Module
        if self._mod is None:
            self._mod = Module(self.symbol, data_names=data_names,
                               label_names=label_names, context=self.ctx)
        return self._mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            batch_end_callback=None, epoch_end_callback=None,
            logger=None, **kwargs):
        mod = self._module()
        mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                optimizer=self.optimizer,
                optimizer_params=self.kwargs.get("optimizer_params",
                                                 {}),
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch or 1,
                batch_end_callback=batch_end_callback,
                epoch_end_callback=epoch_end_callback)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None):
        mod = self._module()
        return mod.predict(X, num_batch=num_batch)

    def save(self, prefix: str, epoch: Optional[int] = None) -> None:
        epoch = epoch if epoch is not None else (self.num_epoch or 0)
        save_checkpoint(prefix, epoch, self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix: str, epoch: int, **kwargs) -> "FeedForward":
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)
