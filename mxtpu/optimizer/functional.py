"""Functional (init, update) optimizer rules for the compiled train
step — the stateless form of ``kvstore_dist_server.h``†'s server-side
updates, shared by ``mxtpu.parallel.TrainStep`` and
``PipelineTrainStep``.

Every rule reuses the fused registry ops ("optimizers are ops") and
accepts ``stacked=True``: same-shape parameters ride stacked on a new
axis 0 and ONE update call handles the bundle.  ``init`` mirrors that:
``init(w, stacked=True)`` treats ``w``'s axis 0 as the stack axis, so
scalar per-parameter state (LAMB's step count ``t``) becomes a
``(n,)`` vector — one slot per stacked row.  The ZeRO-1 sharded path
(``mxtpu.parallel``) carries these stacked states dp-sharded and feeds
each device its local rows; all rules are elementwise in (w, g, state)
so the shard-local apply is exact, and LAMB's per-slice trust-ratio
norms reduce within a bucket row, which ZeRO keeps device-local by
sharding LAMB buckets on the stack axis only.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..ops.registry import get_op
from . import optimizer as _opt


def adam_bias_correction(opt, t: int) -> float:
    """The raw ``adam_update`` op does not bias-correct; fold the
    correction into the lr (single source for TrainStep AND
    PipelineTrainStep)."""
    if isinstance(opt, _opt.Adam) and t > 0:
        return float(np.sqrt(1.0 - opt.beta2 ** t) /
                     (1.0 - opt.beta1 ** t))
    return 1.0


def opt_rule(optimizer):
    """Return ``(init_state(w, stacked=False) -> tuple,
    update(w, g, state, lr, wd, stacked=False) -> (w, state))``.

    All rules are elementwise in (w, g, state) — numerically identical
    stacked or not — except LAMB, whose per-tensor trust-ratio norms
    reduce per axis-0 slice when stacked.

    Unless the optimizer opts out (``multi_precision=False``), sub-f32
    float weights get the fp32-master-weight recipe: state leaf 0 is
    an f32 copy of the weight, the base rule updates the master with
    an f32 grad, and the weight is the master downcast once per step.
    f32 weights pass through untouched, so the state structure (and
    every committed contract/checkpoint) is unchanged for them; the
    dtype dispatch is static under tracing, so no runtime cost
    either way.  ``mxprec``'s ``master-weight`` rule eval_shapes this
    exact function to flag params whose update chain drops to bf16."""
    init, update = _base_rule(optimizer)
    if optimizer.multi_precision is False:
        return init, update
    return _multi_precision_rule(init, update)


def _needs_master(w) -> bool:
    # NOT dt.kind — numpy classes bfloat16 (an ml_dtypes extension
    # type) as kind 'V'; jnp.issubdtype knows better
    dt = jnp.dtype(w.dtype)
    return bool(jnp.issubdtype(dt, jnp.floating)) and dt.itemsize < 4


def _multi_precision_rule(base_init, base_update):
    def init(w, stacked=False):
        if not _needs_master(w):
            return base_init(w, stacked=stacked)
        master = w.astype(jnp.float32)
        return (master,) + tuple(base_init(master, stacked=stacked))

    def update(w, g, state, lr, wd, stacked=False):
        if not _needs_master(w):
            return base_update(w, g, state, lr, wd, stacked=stacked)
        master = state[0]
        w2, st2 = base_update(master, g.astype(jnp.float32),
                              tuple(state[1:]), lr, wd,
                              stacked=stacked)
        # the ONLY narrowing in the chain: master -> stored weight
        return w2.astype(w.dtype), (w2,) + tuple(st2)
    return init, update


def _base_rule(optimizer):
    if isinstance(optimizer, _opt.LAMB):
        fn = get_op("lamb_update").fn

        def init(w, stacked=False):
            # per-param step count rides in the state (traced, so lr
            # schedules and resume never recompile); stacked buckets
            # carry one counter per row
            t0 = jnp.zeros((w.shape[0],) if stacked else (), jnp.int32)
            return (jnp.zeros_like(w), jnp.zeros_like(w), t0)

        def update(w, g, state, lr, wd, stacked=False):
            t = state[2] + 1
            w2, m, v = fn(w, g, state[0], state[1], t, lr=lr,
                          beta1=optimizer.beta1, beta2=optimizer.beta2,
                          epsilon=optimizer.epsilon, wd=wd,
                          rescale_grad=optimizer.rescale_grad,
                          clip_gradient=optimizer._clip(),
                          bias_correction=optimizer.bias_correction,
                          stacked=stacked)
            return w2, (m, v, t)
        return init, update
    if isinstance(optimizer, _opt.Adam):
        fn = get_op("adam_update").fn

        def init(w, stacked=False):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def update(w, g, state, lr, wd, stacked=False):
            w2, m, v = fn(w, g, state[0], state[1], lr=lr,
                          beta1=optimizer.beta1, beta2=optimizer.beta2,
                          epsilon=optimizer.epsilon, wd=wd,
                          rescale_grad=optimizer.rescale_grad,
                          clip_gradient=optimizer._clip())
            return w2, (m, v)
        return init, update
    if isinstance(optimizer, _opt.RMSProp) and not optimizer.centered:
        fn = get_op("rmsprop_update").fn

        def init(w, stacked=False):
            return (jnp.zeros_like(w),)

        def update(w, g, state, lr, wd, stacked=False):
            w2, n = fn(w, g, state[0], lr=lr, gamma1=optimizer.gamma1,
                       epsilon=optimizer.epsilon, wd=wd,
                       rescale_grad=optimizer.rescale_grad,
                       clip_gradient=optimizer._clip())
            return w2, (n,)
        return init, update
    if isinstance(optimizer, _opt.SGD):
        if optimizer.momentum:
            fn = get_op("sgd_mom_update").fn

            def init(w, stacked=False):
                return (jnp.zeros_like(w),)

            def update(w, g, state, lr, wd, stacked=False):
                w2, m = fn(w, g, state[0], lr=lr,
                           momentum=optimizer.momentum, wd=wd,
                           rescale_grad=optimizer.rescale_grad,
                           clip_gradient=optimizer._clip())
                return w2, (m,)
            return init, update
        fn = get_op("sgd_update").fn

        def init(w, stacked=False):
            return ()

        def update(w, g, state, lr, wd, stacked=False):
            return fn(w, g, lr=lr, wd=wd,
                      rescale_grad=optimizer.rescale_grad,
                      clip_gradient=optimizer._clip()), ()
        return init, update
    raise MXNetError(
        f"compiled train step supports SGD/Adam/RMSProp/LAMB; got "
        f"{type(optimizer).__name__} (use gluon.Trainer eager path)")
