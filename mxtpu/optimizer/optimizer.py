"""Optimizer registry + Updater (reference ``python/mxnet/optimizer.py``†).

The reference's design — "optimizers are ops" (``src/operator/
optimizer_op.cc``†) — is kept: each ``update()`` dispatches to a fused
registry op (``sgd_update``/``adam_update``/…) which is a single XLA
kernel; under a hybridized Trainer step the whole update fuses into the
training executable.  States are NDArrays rebound functionally instead of
mutated in place.
"""
from __future__ import annotations

import math
import pickle
from typing import Any, Dict, Optional

import numpy as np

from ..base import MXNetError, Registry
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray

__all__ = ["Optimizer", "Updater", "get_updater", "register", "create",
           "SGD", "NAG", "Adam", "AdaGrad", "AdaDelta", "Adamax", "Nadam",
           "RMSProp", "Ftrl", "Signum", "SGLD", "LBSGD", "LAMB", "Test"]

_REGISTRY: Registry["type"] = Registry("optimizer")


def register(klass):
    """Register an Optimizer subclass under its (lowercased) name
    (reference ``Optimizer.register``†)."""
    _REGISTRY.register(klass.__name__, aliases=(klass.__name__.lower(),))(
        klass)
    return klass


def create(name, **kwargs) -> "Optimizer":
    if isinstance(name, Optimizer):
        return name
    try:
        cls = _REGISTRY.get(name)
    except KeyError:
        raise MXNetError(f"unknown optimizer {name!r}; "
                         f"choices: {sorted(_REGISTRY._entries)}")
    return cls(**kwargs)


def _lazy_rows(grad):
    """Row indices of a RowSparseNDArray gradient (None for dense).
    Drives the reference's ``lazy_update`` semantics: untouched rows
    skip BOTH the gradient step and weight decay."""
    from ..ndarray.sparse import RowSparseNDArray
    if isinstance(grad, RowSparseNDArray):
        return grad.indices
    return None


def _lazy_blend(updated: NDArray, original: NDArray, rows):
    """Keep ``updated`` only on ``rows`` (lazy row-sparse update);
    pass-through when rows is None (dense path)."""
    if rows is None:
        return updated
    import jax.numpy as jnp
    mask = jnp.zeros((original.shape[0],), bool).at[
        rows.data.astype(jnp.int32)].set(True)
    mask = mask.reshape((-1,) + (1,) * (original.data.ndim - 1))
    return NDArray(jnp.where(mask, updated.data, original.data),
                   None, _placed=True)


def _assign(dst: NDArray, src: NDArray) -> None:
    """Rebind dst's buffer to the functionally-updated value."""
    dst._data = src._data if isinstance(src, NDArray) else src


class Optimizer:
    """Base optimizer (reference ``mx.optimizer.Optimizer``†).

    Tracks per-parameter update counts for lr scheduling, applies
    ``lr_mult``/``wd_mult`` (by index or name), and delegates the math to
    fused update ops.
    """

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01,
                 lr_scheduler=None, sym=None, begin_num_update=0,
                 multi_precision=None, param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = dict(param_dict or {})
        self.multi_precision = multi_precision
        self.lr_mult: Dict[Any, float] = {}
        self.wd_mult: Dict[Any, float] = {}

    # -- registry passthroughs (reference API) -------------------------
    create_optimizer = staticmethod(create)

    # -- state ----------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def _wants_master(self, weight) -> bool:
        """fp32-master-weight recipe applies: ``multi_precision`` is
        on (None = auto, the default) and the weight is a sub-f32
        float.  False = explicit opt-out (what mxprec's
        ``master-weight`` rule flags for bf16/f16 params)."""
        if self.multi_precision is False:
            return False
        dt = str(weight.data.dtype)
        return dt in ("float16", "bfloat16")

    def create_state_multi_precision(self, index, weight):
        if not self._wants_master(weight):
            return self.create_state(index, weight)
        master = weight.astype("float32")
        return (master, self.create_state(index, master))

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if not self._wants_master(weight):
            self.update(index, weight, grad, state)
            return
        master, base = state
        self.update(index, master, grad.astype("float32"), base)
        # the only narrowing in the chain: master -> stored weight
        _assign(weight, master.astype(str(weight.data.dtype)))

    # -- hyperparams -----------------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is set; cannot set lr directly")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    @learning_rate.setter
    def learning_rate(self, lr):
        self.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # reference default: no decay on biases and norm params
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) \
            if self.lr_scheduler is not None else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _clip(self):
        return self.clip_gradient if self.clip_gradient else -1.0


@register
class SGD(Optimizer):
    """(Momentum) SGD → ``sgd_update``/``sgd_mom_update`` ops
    (reference ``optimizer.SGD``†)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context,
                        dtype=str(weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        lazy_rows = _lazy_rows(grad) if self.lazy_update else None
        if state is None:
            new_w = nd.sgd_update(
                weight, grad, lr=lr, wd=wd,
                rescale_grad=self.rescale_grad,
                clip_gradient=self._clip())
            _assign(weight, _lazy_blend(new_w, weight, lazy_rows))
        else:
            w, m = nd.sgd_mom_update(
                weight, grad, state, lr=lr, momentum=self.momentum,
                wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=self._clip())
            _assign(weight, _lazy_blend(w, weight, lazy_rows))
            _assign(state, _lazy_blend(m, state, lazy_rows))


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference ``optimizer.NAG``†)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context,
                        dtype=str(weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        grad = grad + wd * weight
        if state is None:
            _assign(weight, weight - lr * grad)
        else:
            m = self.momentum * state + grad
            _assign(state, m)
            _assign(weight, weight - lr * (grad + self.momentum * m))


@register
class Adam(Optimizer):
    """Adam → ``adam_update`` op (reference ``optimizer.Adam``†)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        dtype = str(weight.data.dtype)
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        # bias correction folded into lr (reference does the same)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr = lr * math.sqrt(coef2) / coef1
        mean, var = state
        w, m, v = nd.adam_update(
            weight, grad, mean, var, lr=lr, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon, wd=wd,
            rescale_grad=self.rescale_grad, clip_gradient=self._clip())
        rows = _lazy_rows(grad) if self.lazy_update else None
        _assign(weight, _lazy_blend(w, weight, rows))
        _assign(mean, _lazy_blend(m, mean, rows))
        _assign(var, _lazy_blend(v, var, rows))


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference ``optimizer.AdaGrad``†)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context,
                        dtype=str(weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        if wd:
            grad = grad + wd * weight
        hist = state + nd.square(grad)
        _assign(state, hist)
        _assign(weight, weight - lr * grad /
                nd.sqrt(hist + self.float_stable_eps))


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference ``optimizer.AdaDelta``†)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        dtype = str(weight.data.dtype)
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        if wd:
            grad = grad + wd * weight
        acc_g, acc_delta = state
        g2 = self.rho * acc_g + (1 - self.rho) * nd.square(grad)
        delta = nd.sqrt(acc_delta + self.epsilon) / \
            nd.sqrt(g2 + self.epsilon) * grad
        d2 = self.rho * acc_delta + (1 - self.rho) * nd.square(delta)
        _assign(acc_g, g2)
        _assign(acc_delta, d2)
        _assign(weight, weight - delta)


@register
class Adamax(Optimizer):
    """Adamax, the inf-norm Adam variant (reference ``optimizer.Adamax``†)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        dtype = str(weight.data.dtype)
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1.0 - self.beta1 ** t)
        grad = grad * self.rescale_grad
        if self.clip_gradient:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        if wd:
            grad = grad + wd * weight
        m, u = state
        m_new = self.beta1 * m + (1 - self.beta1) * grad
        u_new = nd.maximum(self.beta2 * u, nd.abs(grad))
        _assign(m, m_new)
        _assign(u, u_new)
        _assign(weight, weight - lr * m_new / (u_new + 1e-8))


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference ``optimizer.Nadam``†)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        dtype = str(weight.data.dtype)
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad
        if self.clip_gradient:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        if wd:
            grad = grad + wd * weight
        m_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        m_t1 = self.beta1 * (1.0 - 0.5 * 0.96 **
                             ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * m_t
        sched1 = self.m_schedule * m_t1
        m, v = state
        g_prime = grad / (1.0 - self.m_schedule)
        m_new = self.beta1 * m + (1 - self.beta1) * grad
        v_new = self.beta2 * v + (1 - self.beta2) * nd.square(grad)
        m_prime = m_new / (1.0 - sched1)
        v_prime = v_new / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - m_t) * g_prime + m_t1 * m_prime
        _assign(m, m_new)
        _assign(v, v_new)
        _assign(weight, weight - lr * m_bar /
                (nd.sqrt(v_prime) + self.epsilon))


@register
class RMSProp(Optimizer):
    """RMSProp (centered=False→Tieleman, True→Graves)
    → ``rmsprop_update``/``rmspropalex_update`` ops
    (reference ``optimizer.RMSProp``†)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        dtype = str(weight.data.dtype)
        z = lambda: nd.zeros(weight.shape, ctx=weight.context,  # noqa:E731
                             dtype=dtype)
        if self.centered:
            return (z(), z(), z())
        return (z(),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if not self.centered:
            (n,) = state
            w, n_new = nd.rmsprop_update(
                weight, grad, n, lr=lr, gamma1=self.gamma1,
                epsilon=self.epsilon, wd=wd,
                rescale_grad=self.rescale_grad,
                clip_gradient=self._clip(),
                clip_weights=self.clip_weights or -1.0)
            _assign(weight, w)
            _assign(n, n_new)
        else:
            n, g, delta = state
            w, n2, g2, d2 = nd.rmspropalex_update(
                weight, grad, n, g, delta, lr=lr, gamma1=self.gamma1,
                gamma2=self.gamma2, epsilon=self.epsilon, wd=wd,
                rescale_grad=self.rescale_grad,
                clip_gradient=self._clip())
            _assign(weight, w)
            _assign(n, n2)
            _assign(g, g2)
            _assign(delta, d2)


@register
class LAMB(Optimizer):
    """LAMB (You et al. 2020, "Large Batch Optimization for Deep
    Learning") → ``lamb_update`` op: Adam moments with a per-tensor
    trust ratio, the large-batch BERT pretraining optimizer."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        dtype = str(weight.data.dtype)
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        w, m, v = nd.lamb_update(
            weight, grad, mean, var, nd.array(np.asarray(t, np.int32)),
            lr=lr, beta1=self.beta1, beta2=self.beta2,
            epsilon=self.epsilon, wd=wd,
            rescale_grad=self.rescale_grad,
            clip_gradient=self._clip(),
            bias_correction=self.bias_correction)
        _assign(weight, w)
        _assign(mean, m)
        _assign(var, v)


@register
class Ftrl(Optimizer):
    """FTRL-proximal → ``ftrl_update`` op (reference ``optimizer.Ftrl``†)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        dtype = str(weight.data.dtype)
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        w, z2, n2 = nd.ftrl_update(
            weight, grad, z, n, lr=lr, lamda1=self.lamda1, beta=self.beta,
            wd=wd, rescale_grad=self.rescale_grad,
            clip_gradient=self._clip())
        _assign(weight, w)
        _assign(z, z2)
        _assign(n, n2)


@register
class Signum(Optimizer):
    """SignSGD/Signum → ``signsgd_update``/``signum_update`` ops
    (reference ``optimizer.Signum``†)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context,
                        dtype=str(weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is None:
            _assign(weight, nd.signsgd_update(
                weight, grad, lr=lr, wd=wd,
                rescale_grad=self.rescale_grad,
                clip_gradient=self._clip()))
        else:
            w, m = nd.signum_update(
                weight, grad, state, lr=lr, momentum=self.momentum,
                wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=self._clip(), wd_lh=self.wd_lh)
            _assign(weight, w)
            _assign(state, m)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference
    ``optimizer.SGLD``†)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        grad = grad + wd * weight
        noise = nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 ctx=weight.context)
        _assign(weight, weight - lr / 2 * grad + noise)


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise scaling (reference
    ``optimizer.LBSGD``†; here the warmup/LARS heuristics reduce to
    momentum SGD — the multipliers matter on 8k+ batches only)."""


@register
class Test(Optimizer):
    """Trivial test optimizer (reference ``optimizer.Test``†)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        _assign(weight, weight + grad * self.rescale_grad)
        _assign(state, weight)


# `ccSGD` was an alias of SGD by this era
ccSGD = SGD
_REGISTRY.register("ccSGD", aliases=("ccsgd",))(SGD)


class Updater:
    """Applies an optimizer with per-index states (reference
    ``optimizer.Updater``† — the object a KVStore runs server-side)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}
        self.states_synced: Dict[Any, bool] = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        elif not self.states_synced.get(index, True):
            self.states[index] = self.sync_state_context(
                self.states[index], weight.context)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, (tuple, list)):
            return type(state)(
                self.sync_state_context(s, context) for s in state)
        return state

    def get_states(self, dump_optimizer=False):
        """Serialize states (+ optionally the optimizer) — reference
        pickle protocol for Trainer.save_states / dist kvstore."""
        def to_np(s):
            if isinstance(s, NDArray):
                return s.asnumpy()
            if isinstance(s, (tuple, list)):
                return type(s)(to_np(x) for x in s)
            return s
        states = {k: to_np(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((states, self.optimizer))
        return pickle.dumps(states)

    def set_states(self, states_bytes):
        data = pickle.loads(states_bytes)  # mxlint: disable=raw-deserialize (MXNet get_states/set_states contract: caller-supplied state blob, not a cache artifact)
        if isinstance(data, tuple) and len(data) == 2 and \
                isinstance(data[1], Optimizer):
            states, self.optimizer = data
        else:
            states = data

        def to_nd(s):
            if isinstance(s, np.ndarray):
                return nd.array(s)
            if isinstance(s, (tuple, list)):
                return type(s)(to_nd(x) for x in s)
            return s
        self.states = {k: to_nd(v) for k, v in states.items()}
        self.states_synced = {k: False for k in self.states}


def get_updater(optimizer: Optimizer) -> Updater:
    """Reference ``mx.optimizer.get_updater``†."""
    return Updater(optimizer)
