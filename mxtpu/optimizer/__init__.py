"""``mxtpu.optimizer`` (reference ``python/mxnet/optimizer.py``† +
``lr_scheduler.py``†)."""
from .optimizer import *          # noqa: F401,F403
from .optimizer import Optimizer, Updater, get_updater, register, create
from . import lr_scheduler        # noqa: F401
from .functional import adam_bias_correction, opt_rule  # noqa: F401
