"""mxrace Pass 1 — static lock-order graph over the threaded tree.

The serving/obs stack shares state across worker, watcher, and
control-plane threads; PR 7 *documented* its lock order in a module
docstring.  This pass turns that prose into a machine-checked fact:

* find every lock definition (``self._x = threading.Lock()`` /
  ``RLock`` / ``Condition``, plus module-level ``_LOCK = ...``);
* find every acquisition site (``with self._lock:`` /
  ``with _LOCK:``), resolving nesting *interprocedurally* through
  direct calls (``self.m()``, typed attrs like ``self.batcher``,
  annotated params, unique method names) and the ``*_locked``
  called-with-lock-held convention;
* emit the resulting lock-order DAG; cycles are potential deadlocks
  (errors), and the edge set is pinned in ``contracts/lockorder.json``
  so new nesting is growth-only drift ``--check`` flags;
* flag unannotated shared mutable attrs: in a lock-owning class, an
  attr written outside ``__init__`` and touched from >= 2 methods
  (thread entry points) must carry ``# guarded-by: <lock>`` or a
  justified ``# mxrace: disable=unguarded-attr`` pragma.

Pure stdlib (``ast``/``re``/``json``) like tools/mxlint — this module
must never import jax or the mxtpu package, so a broken tree is still
analyzable.  It *reuses* mxlint's file model (FileCtx, pragma and
``# guarded-by:`` parsing, discovery, Finding/baseline machinery),
loading it by path when ``tools`` is not importable.
"""
from __future__ import annotations

import ast
import json
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]

# what mxrace scans: everything that owns a lock or a thread today
SCOPES = ("mxtpu/serving", "mxtpu/obs", "mxtpu/parallel",
          "mxtpu/profiler.py", "mxtpu/guards.py", "mxtpu/cache.py")

DEFAULT_LOCKFILE = REPO_ROOT / "contracts" / "lockorder.json"

LOCKORDER_BEGIN = "<!-- mxrace:lockorder:begin -->"
LOCKORDER_END = "<!-- mxrace:lockorder:end -->"

_RACE_SUPPRESS_RE = re.compile(
    r"#\s*mxrace:\s*disable=([\w\-, ]+?)(?:\s*\(([^)]*)\))?\s*(?:#|$)")

# threading constructors that make an attr a sync primitive, not data
_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTORS = {"Condition"}
_SYNC_CTORS = _LOCK_CTORS | _COND_CTORS | {
    "Event", "Semaphore", "BoundedSemaphore", "Barrier", "Thread",
    "Timer", "local"}

# calls/ctors whose result is a mutable container (in-place mutation
# of these never shows up as an attribute Store)
_MUTABLE_CTORS = {"list", "dict", "set", "bytearray", "deque",
                  "defaultdict", "OrderedDict", "Counter"}

# method names that mutate a container in place
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "pop", "popleft", "popitem", "clear", "update", "add",
             "remove", "discard", "setdefault", "sort", "reverse",
             "rotate"}

# stdlib modules used as call receivers in the scanned tree: a call
# through one of these (`os.close(fd)`, `pickle.dumps(x)`) leaves the
# scanned universe and must never name-resolve to an unrelated scanned
# method (`DebugServer.close`, `Profiler.dumps`)
_STDLIB_RECEIVERS = frozenset({
    "os", "sys", "time", "json", "math", "re", "ast", "io", "errno",
    "signal", "socket", "shutil", "pickle", "struct", "hashlib",
    "logging", "threading", "subprocess", "tempfile", "atexit", "gc",
    "random", "warnings", "itertools", "functools", "collections",
    "np", "numpy", "jax", "jnp"})


# ----------------------------------------------------------------------
# mxlint core reuse (shared FileCtx / pragma / Finding machinery)
# ----------------------------------------------------------------------
def _load_lintcore():
    try:
        from tools.mxlint import core  # repo root on sys.path
        return core
    except ImportError:
        import importlib.util
        path = REPO_ROOT / "tools" / "mxlint" / "core.py"
        spec = importlib.util.spec_from_file_location(
            "_mxrace_lintcore", path)
        mod = importlib.util.module_from_spec(spec)
        assert spec.loader is not None
        spec.loader.exec_module(mod)
        return mod


lintcore = _load_lintcore()
Finding = lintcore.Finding
FileCtx = lintcore.FileCtx
dotted_name = lintcore.dotted_name
_GUARDED_RE = lintcore._GUARDED_RE
_ASSIGN_RE = re.compile(r"self\.(\w+)\s*(?::[^=]*)?=[^=]")


# ----------------------------------------------------------------------
# scan model
# ----------------------------------------------------------------------
class MethodRec:
    """One function/method body plus its first-sweep summary."""

    __slots__ = ("qual", "cls", "name", "node", "rel", "modname",
                 "local_types", "direct_acquires", "direct_calls")

    def __init__(self, qual: str, cls: Optional[str], name: str,
                 node: ast.AST, rel: str, modname: str):
        self.qual = qual
        self.cls = cls
        self.name = name
        self.node = node
        self.rel = rel
        self.modname = modname
        self.local_types: Dict[str, str] = {}
        self.direct_acquires: Set[str] = set()
        self.direct_calls: Set[str] = set()


class ClassRec:
    __slots__ = ("name", "rel", "modname", "line", "end_line", "bases",
                 "methods", "lock_attrs", "alias_locks", "attr_types",
                 "guarded", "race_supp", "init_attrs", "sync_attrs",
                 "container_attrs", "writes", "touches",
                 "first_write_line")

    def __init__(self, name: str, rel: str, modname: str, line: int,
                 end_line: int, bases: List[str]):
        self.name = name
        self.rel = rel
        self.modname = modname
        self.line = line
        self.end_line = end_line
        self.bases = bases
        self.methods: Dict[str, MethodRec] = {}
        # attr -> (kind, line): locks *created* here (threading ctor)
        self.lock_attrs: Dict[str, Tuple[str, int]] = {}
        # attrs used as `with self.x:` without a local threading ctor
        # (lock passed in / shared — e.g. metrics child handles)
        self.alias_locks: Dict[str, int] = {}
        self.attr_types: Dict[str, str] = {}
        self.guarded: Dict[str, str] = {}          # attr -> lock attr
        self.race_supp: Dict[str, Set[str]] = {}   # attr -> rules
        self.init_attrs: Set[str] = set()
        self.sync_attrs: Set[str] = set()
        self.container_attrs: Set[str] = set()
        self.writes: Dict[str, Set[str]] = {}      # attr -> methods
        self.touches: Dict[str, Set[str]] = {}     # attr -> methods
        self.first_write_line: Dict[str, int] = {}

    def has_locks(self) -> bool:
        return bool(self.lock_attrs or self.alias_locks)


class Analysis:
    """Everything the graph/finding passes need, fully resolved."""

    def __init__(self) -> None:
        self.ctxs: List[FileCtx] = []
        self.parse_errors: List[Finding] = []
        self.classes: Dict[str, ClassRec] = {}
        self.methods: Dict[str, MethodRec] = {}
        self.methods_by_name: Dict[str, List[str]] = {}
        self.functions_by_name: Dict[str, List[str]] = {}
        self.module_locks: Dict[str, Dict[str, Tuple[str, int, str]]] \
            = {}  # modname -> name -> (kind, line, rel)
        self.module_funcs: Dict[str, Set[str]] = {}
        self.modules: Dict[str, str] = {}  # modname -> rel
        # line-level mxrace pragma map per rel path
        self.race_suppressions: Dict[str, Dict[int, Set[str]]] = {}


def _modname(rel: str) -> str:
    p = Path(rel)
    return p.parent.name if p.stem == "__init__" else p.stem


def _ctor_kind(value: ast.AST) -> Optional[str]:
    """'Lock'/'RLock'/'Condition'/... when value is a threading
    constructor call, else None."""
    if not isinstance(value, ast.Call):
        return None
    d = dotted_name(value.func)
    if d is None:
        return None
    last = d.rsplit(".", 1)[-1]
    return last if last in _SYNC_CTORS else None


def _is_mutable_value(value: ast.AST) -> bool:
    if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        d = dotted_name(value.func)
        if d and d.rsplit(".", 1)[-1] in _MUTABLE_CTORS:
            return True
    return False


def _type_name(ann: Optional[ast.AST]) -> Optional[str]:
    if ann is None:
        return None
    d = dotted_name(ann)
    return d.rsplit(".", 1)[-1] if d else None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


# ----------------------------------------------------------------------
# scan: files -> Analysis
# ----------------------------------------------------------------------
def scan(paths: Sequence[str] = SCOPES,
         root: Path = REPO_ROOT) -> Analysis:
    an = Analysis()
    files = lintcore.iter_py_files(paths, root)
    an.ctxs, an.parse_errors = lintcore.parse_files(files, root)
    for ctx in an.ctxs:
        _scan_file(an, ctx)
    _first_sweep(an)
    return an


def _race_supp_map(ctx: FileCtx) -> Dict[int, Set[str]]:
    """line -> suppressed mxrace rule names; a comment-only pragma
    line also covers the line after it (same semantics as mxlint)."""
    supp: Dict[int, Set[str]] = {}
    for i, ln in enumerate(ctx.lines, start=1):
        m = _RACE_SUPPRESS_RE.search(ln)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        supp.setdefault(i, set()).update(rules)
        if ln.lstrip().startswith("#"):
            supp.setdefault(i + 1, set()).update(rules)
    return supp


def _scan_file(an: Analysis, ctx: FileCtx) -> None:
    mod = _modname(ctx.rel)
    an.modules[mod] = ctx.rel
    an.module_locks.setdefault(mod, {})
    an.module_funcs.setdefault(mod, set())
    an.race_suppressions[ctx.rel] = _race_supp_map(ctx)
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign):
            kind = _ctor_kind(stmt.value)
            if kind and kind in (_LOCK_CTORS | _COND_CTORS):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        an.module_locks[mod][tgt.id] = \
                            (kind, stmt.lineno, ctx.rel)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{mod}.{stmt.name}"
            rec = MethodRec(qual, None, stmt.name, stmt, ctx.rel, mod)
            an.methods[qual] = rec
            an.module_funcs[mod].add(stmt.name)
            an.functions_by_name.setdefault(stmt.name, []).append(qual)
        elif isinstance(stmt, ast.ClassDef):
            _scan_class(an, ctx, mod, stmt)


def _scan_class(an: Analysis, ctx: FileCtx, mod: str,
                cls: ast.ClassDef) -> None:
    rec = ClassRec(cls.name, ctx.rel, mod, cls.lineno,
                   cls.end_lineno or len(ctx.lines),
                   [d.rsplit(".", 1)[-1] for d in
                    (dotted_name(b) for b in cls.bases) if d])
    # keep the first definition on (unlikely) duplicate class names —
    # deterministic because files arrive sorted
    an.classes.setdefault(cls.name, rec)
    if an.classes[cls.name] is not rec:
        return
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        qual = f"{cls.name}.{meth.name}"
        mrec = MethodRec(qual, cls.name, meth.name, meth, ctx.rel, mod)
        rec.methods[meth.name] = mrec
        an.methods[qual] = mrec
        an.methods_by_name.setdefault(meth.name, []).append(qual)
        _scan_method_attrs(an, rec, meth)
    _scan_annotations(ctx, rec)


def _scan_annotations(ctx: FileCtx, rec: ClassRec) -> None:
    """# guarded-by: and # mxrace: disable= pragmas paired with a
    ``self.<attr> = ...`` assignment on the same or the next line
    (same pairing LockDiscipline uses)."""
    for i in range(rec.line, min(rec.end_line, len(ctx.lines)) + 1):
        line = ctx.lines[i - 1] if i <= len(ctx.lines) else ""
        gm = _GUARDED_RE.search(line)
        sm = _RACE_SUPPRESS_RE.search(line)
        if not gm and not sm:
            continue
        am = _ASSIGN_RE.search(line)
        if am is None and i < len(ctx.lines):
            am = _ASSIGN_RE.search(ctx.lines[i])
        if am is None:
            continue
        attr = am.group(1)
        if gm:
            rec.guarded[attr] = gm.group(1)
        if sm:
            rec.race_supp.setdefault(attr, set()).update(
                r.strip() for r in sm.group(1).split(",") if r.strip())


def _scan_method_attrs(an: Analysis, rec: ClassRec,
                       meth: ast.AST) -> None:
    """Collect self.<attr> definitions, writes and touches for the
    unguarded-attr pass, plus typed-attr and lock-attr inventories."""
    name = meth.name
    in_init = name == "__init__"
    # param-annotation types feed attr_types for `self.x = x`
    params: Dict[str, str] = {}
    for a in list(meth.args.posonlyargs) + list(meth.args.args) + \
            list(meth.args.kwonlyargs):
        t = _type_name(a.annotation)
        if t:
            params[a.arg] = t

    def note_write(attr: str, line: int) -> None:
        rec.writes.setdefault(attr, set()).add(name)
        rec.touches.setdefault(attr, set()).add(name)
        if not in_init and attr not in rec.first_write_line:
            rec.first_write_line[attr] = line

    def note_value(attr: str, value: ast.AST) -> None:
        kind = _ctor_kind(value)
        if kind:
            rec.sync_attrs.add(attr)
            if kind in (_LOCK_CTORS | _COND_CTORS) and \
                    attr not in rec.lock_attrs:
                rec.lock_attrs[attr] = (kind, value.lineno)
        if _is_mutable_value(value):
            rec.container_attrs.add(attr)
        if isinstance(value, ast.Call):
            d = dotted_name(value.func)
            if d:
                rec.attr_types.setdefault(attr, d.rsplit(".", 1)[-1])
        elif isinstance(value, ast.Name) and value.id in params:
            rec.attr_types.setdefault(attr, params[value.id])

    for node in ast.walk(meth):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr:
                    note_write(attr, node.lineno)
                    if in_init:
                        rec.init_attrs.add(attr)
                        note_value(attr, node.value)
                elif isinstance(tgt, ast.Subscript):
                    attr = _self_attr(tgt.value)
                    if attr:
                        note_write(attr, node.lineno)
        elif isinstance(node, ast.AnnAssign):
            attr = _self_attr(node.target)
            if attr and node.value is not None:
                note_write(attr, node.lineno)
                if in_init:
                    rec.init_attrs.add(attr)
                    note_value(attr, node.value)
        elif isinstance(node, ast.AugAssign):
            attr = _self_attr(node.target)
            if attr:
                note_write(attr, node.lineno)
            elif isinstance(node.target, ast.Subscript):
                attr = _self_attr(node.target.value)
                if attr:
                    note_write(attr, node.lineno)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                attr = _self_attr(tgt) or (
                    _self_attr(tgt.value)
                    if isinstance(tgt, ast.Subscript) else None)
                if attr:
                    note_write(attr, node.lineno)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
            if attr:
                note_write(attr, node.lineno)
        elif isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr:
                rec.touches.setdefault(attr, set()).add(name)


# ----------------------------------------------------------------------
# resolution helpers
# ----------------------------------------------------------------------
def _mro(an: Analysis, cls: str,
         _seen: Optional[Set[str]] = None) -> List[str]:
    seen = _seen if _seen is not None else set()
    if cls in seen or cls not in an.classes:
        return []
    seen.add(cls)
    out = [cls]
    for b in an.classes[cls].bases:
        out.extend(_mro(an, b, seen))
    return out


def _method_in_mro(an: Analysis, cls: str, name: str) -> Optional[str]:
    for c in _mro(an, cls):
        if name in an.classes[c].methods:
            return f"{c}.{name}"
    return None


def _lock_owner(an: Analysis, cls: str, attr: str) -> Optional[str]:
    for c in _mro(an, cls):
        if attr in an.classes[c].lock_attrs:
            return c
    return None


def _attr_type(an: Analysis, cls: str, attr: str) -> Optional[str]:
    for c in _mro(an, cls):
        t = an.classes[c].attr_types.get(attr)
        if t and t in an.classes:
            return t
    return None


def _resolve_lock(an: Analysis, expr: ast.AST,
                  rec: MethodRec) -> Optional[Tuple[str, str]]:
    """(node_name, kind) for a with-item that is a lock reference."""
    d = dotted_name(expr)
    if d is None:
        return None
    parts = d.split(".")
    if parts[0] == "self" and len(parts) == 2 and rec.cls:
        attr = parts[1]
        owner = _lock_owner(an, rec.cls, attr)
        if owner:
            return (f"{owner}.{attr}",
                    an.classes[owner].lock_attrs[attr][0])
        # `with self.x:` on an attr with no local threading ctor —
        # a lock passed in (metrics child handles share the family
        # lock); model it as its own alias node, still DAG-checked
        an.classes[rec.cls].alias_locks.setdefault(attr, expr.lineno)
        return (f"{rec.cls}.{attr}", "alias")
    if len(parts) == 1:
        locks = an.module_locks.get(rec.modname, {})
        if parts[0] in locks:
            return (f"{rec.modname}.{parts[0]}", locks[parts[0]][0])
    if len(parts) == 2 and parts[0] in an.module_locks:
        locks = an.module_locks[parts[0]]
        if parts[1] in locks:
            return (f"{parts[0]}.{parts[1]}", locks[parts[1]][0])
    return None


def _return_type(an: Analysis, qual: str) -> Optional[str]:
    cls, _, name = qual.partition(".")
    if name == "__init__":
        return cls
    rec = an.methods.get(qual)
    if rec is None:
        return None
    t = _type_name(getattr(rec.node, "returns", None))
    return t if t in an.classes else None


def _resolve_call(an: Analysis, func: ast.AST,
                  rec: MethodRec) -> Tuple[str, ...]:
    """Qualnames a call may dispatch to.  Typed resolutions (self
    methods, ctor/param-typed attrs, annotated locals, module
    functions, chained calls via return annotations) are exact;
    otherwise every scanned method sharing the name is a candidate
    (conservative, deterministic)."""
    if isinstance(func, ast.Attribute) and \
            isinstance(func.value, ast.Call):
        # chained call: obs.flight("compile").record(...) — resolve
        # the inner call, then its annotated return type's method
        out: Set[str] = set()
        inner = _resolve_call(an, func.value.func, rec)
        for q in inner:
            t = _return_type(an, q)
            if t:
                m = _method_in_mro(an, t, func.attr)
                if m:
                    out.add(m)
        if out or not inner or func.attr in _MUTATORS:
            return tuple(sorted(out))
        # inner call known but un-annotated (obs.flight returns the
        # recorder or its null twin): every method with this name
        return tuple(sorted(an.methods_by_name.get(func.attr, ())))
    d = dotted_name(func)
    if d is None:
        return ()
    parts = d.split(".")
    last = parts[-1]
    if parts[0] == "self" and rec.cls:
        if len(parts) == 2:
            q = _method_in_mro(an, rec.cls, last)
            return (q,) if q else ()
        if len(parts) == 3:
            t = _attr_type(an, rec.cls, parts[1])
            if t:
                q = _method_in_mro(an, t, last)
                return (q,) if q else ()
    elif len(parts) == 2:
        t = rec.local_types.get(parts[0])
        if t and t in an.classes:
            q = _method_in_mro(an, t, last)
            return (q,) if q else ()
        if parts[0] in _STDLIB_RECEIVERS and \
                parts[0] not in an.modules:
            return ()
        if parts[0] in an.modules:
            if last in an.module_funcs.get(parts[0], ()):
                return (f"{parts[0]}.{last}",)
            # fall through: `obs.span` is re-exported from trace
    elif len(parts) == 1:
        if last in an.module_funcs.get(rec.modname, ()):
            return (f"{rec.modname}.{last}",)
        if last in an.classes:  # constructor
            q = _method_in_mro(an, last, "__init__")
            return (q,) if q else ()
        return ()
    if len(parts) >= 2 and last in an.classes:  # mod.Class(...) ctor
        q = _method_in_mro(an, last, "__init__")
        return (q,) if q else ()
    if last in _MUTATORS:
        # `self._queue.clear()` must not name-resolve to an unrelated
        # `def clear` (FlightRecorder.clear) — container mutators only
        # resolve through a typed receiver
        return ()
    cands = tuple(sorted(an.methods_by_name.get(last, ())))
    if cands:
        return cands
    return tuple(sorted(an.functions_by_name.get(last, ())))


# ----------------------------------------------------------------------
# first sweep: per-function local types, direct acquires, direct calls
# ----------------------------------------------------------------------
def _first_sweep(an: Analysis) -> None:
    for rec in an.methods.values():
        node = rec.node
        for a in list(node.args.posonlyargs) + list(node.args.args) + \
                list(node.args.kwonlyargs):
            t = _type_name(a.annotation)
            if t and t in an.classes:
                rec.local_types[a.arg] = t
        for sub in ast.walk(node):
            if isinstance(sub, ast.AnnAssign) and \
                    isinstance(sub.target, ast.Name):
                t = _type_name(sub.annotation)
                if t and t in an.classes:
                    rec.local_types[sub.target.id] = t
    for rec in an.methods.values():
        for sub in _walk_no_nested(rec.node):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    lk = _resolve_lock(an, item.context_expr, rec)
                    if lk:
                        rec.direct_acquires.add(lk[0])
            elif isinstance(sub, ast.Call):
                rec.direct_calls.update(_resolve_call(an, sub.func, rec))


def _walk_no_nested(func_node: ast.AST):
    """ast.walk, but do not descend into nested def/lambda (they run
    in a different dynamic context)."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _trans_acquires(an: Analysis, qual: str,
                    memo: Dict[str, Set[str]],
                    stack: Set[str]) -> Set[str]:
    if qual in memo:
        return memo[qual]
    if qual in stack or qual not in an.methods:
        return set()
    stack.add(qual)
    rec = an.methods[qual]
    out = set(rec.direct_acquires)
    for callee in rec.direct_calls:
        out |= _trans_acquires(an, callee, memo, stack)
    stack.discard(qual)
    memo[qual] = out
    return out


# ----------------------------------------------------------------------
# graph build
# ----------------------------------------------------------------------
class Graph:
    def __init__(self) -> None:
        self.locks: Dict[str, Dict[str, Any]] = {}
        # (a, b) -> set of (rel, line) sites where b is taken under a
        self.edges: Dict[Tuple[str, str], Set[Tuple[str, int]]] = {}

    def add_lock(self, name: str, kind: str, rel: str,
                 line: int) -> None:
        self.locks.setdefault(
            name, {"kind": kind, "site": f"{rel}:{line}"})

    def add_edge(self, a: str, b: str, rel: str, line: int) -> None:
        if a == b:
            return
        self.edges.setdefault((a, b), set()).add((rel, line))

    def adjacency(self) -> Dict[str, List[str]]:
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
        for v in adj.values():
            v.sort()
        return adj


def _primary_lock(an: Analysis, cls: str) -> Optional[str]:
    """The lock a ``*_locked`` method of ``cls`` is called under: the
    attr named ``_lock`` if the class (or a base) defines one, else
    the class's only lock."""
    owner = _lock_owner(an, cls, "_lock")
    if owner:
        return f"{owner}._lock"
    for c in _mro(an, cls):
        la = an.classes[c].lock_attrs
        if len(la) == 1:
            attr = next(iter(la))
            return f"{c}.{attr}"
        if la:
            return None  # ambiguous
    return None


def build_graph(an: Analysis) -> Graph:
    g = Graph()
    for cname in sorted(an.classes):
        crec = an.classes[cname]
        for attr, (kind, line) in sorted(crec.lock_attrs.items()):
            g.add_lock(f"{cname}.{attr}", kind, crec.rel, line)
    for mod in sorted(an.module_locks):
        for lname, (kind, line, rel) in \
                sorted(an.module_locks[mod].items()):
            g.add_lock(f"{mod}.{lname}", kind, rel, line)

    memo: Dict[str, Set[str]] = {}
    nested: List[Tuple[MethodRec, ast.AST]] = []

    def visit(rec: MethodRec, node: ast.AST,
              held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                visit(rec, item.context_expr, held)
                lk = _resolve_lock(an, item.context_expr, rec)
                if lk:
                    name, kind = lk
                    if kind == "alias":
                        g.add_lock(name, "alias", rec.rel,
                                   item.context_expr.lineno)
                    for h in held:
                        g.add_edge(h, name, rec.rel,
                                   item.context_expr.lineno)
                    if name not in held:
                        acquired.append(name)
            inner = held + tuple(acquired)
            for stmt in node.body:
                visit(rec, stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            nested.append((rec, node))
            return
        if isinstance(node, ast.Call) and held:
            for callee in sorted(_resolve_call(an, node.func, rec)):
                for lock in sorted(
                        _trans_acquires(an, callee, memo, set())):
                    for h in held:
                        g.add_edge(h, lock, rec.rel, node.lineno)
        for child in ast.iter_child_nodes(node):
            visit(rec, child, held)

    for qual in sorted(an.methods):
        rec = an.methods[qual]
        held: Tuple[str, ...] = ()
        if rec.name.endswith("_locked") and rec.cls:
            primary = _primary_lock(an, rec.cls)
            if primary:
                held = (primary,)
        for stmt in _body(rec.node):
            visit(rec, stmt, held)
    while nested:
        rec, node = nested.pop()
        for stmt in _body(node):
            visit(rec, stmt, ())
    return g


def _body(node: ast.AST) -> List[ast.AST]:
    if isinstance(node, ast.Lambda):
        return [node.body]
    return list(getattr(node, "body", []))


# ----------------------------------------------------------------------
# cycles
# ----------------------------------------------------------------------
def find_cycles(g: Graph) -> List[List[str]]:
    """Deterministic DFS cycle enumeration; each cycle reported once
    in canonical (min-first) rotation."""
    adj = g.adjacency()
    seen_cycles: Set[Tuple[str, ...]] = set()
    out: List[List[str]] = []
    color: Dict[str, int] = {}
    path: List[str] = []

    def dfs(u: str) -> None:
        color[u] = 1
        path.append(u)
        for v in adj.get(u, ()):
            if color.get(v, 0) == 1:
                i = path.index(v)
                cyc = path[i:]
                k = cyc.index(min(cyc))
                canon = tuple(cyc[k:] + cyc[:k])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    out.append(list(canon))
            elif color.get(v, 0) == 0:
                dfs(v)
        path.pop()
        color[u] = 2

    for node in sorted(set(g.locks) |
                       {a for a, _ in g.edges} |
                       {b for _, b in g.edges}):
        if color.get(node, 0) == 0:
            dfs(node)
    return out


def _edge_site(g: Graph, a: str, b: str) -> str:
    sites = g.edges.get((a, b))
    if not sites:
        return "?"
    rel, line = min(sites)
    return f"{rel}:{line}"


def cycle_findings(g: Graph) -> List[Finding]:
    out = []
    for cyc in find_cycles(g):
        ring = cyc + [cyc[0]]
        sites = "; ".join(
            f"{ring[i]} -> {ring[i + 1]} at "
            f"{_edge_site(g, ring[i], ring[i + 1])}"
            for i in range(len(cyc)))
        first = g.edges.get((ring[0], ring[1]))
        rel, line = min(first) if first else ("contracts", 1)
        out.append(Finding(
            "lock-cycle", rel, line,
            f"lock-order cycle (potential deadlock): "
            f"{' -> '.join(ring)} [{sites}]",
            snippet=" -> ".join(ring)))
    return out


# ----------------------------------------------------------------------
# unguarded shared mutable attrs
# ----------------------------------------------------------------------
def unguarded_findings(an: Analysis) -> List[Finding]:
    out: List[Finding] = []
    for cname in sorted(an.classes):
        rec = an.classes[cname]
        if not rec.has_locks():
            continue
        guarded = dict(rec.guarded)
        for base in _mro(an, cname)[1:]:
            for a, lk in an.classes[base].guarded.items():
                guarded.setdefault(a, lk)
        for attr in sorted(rec.writes):
            if attr in rec.sync_attrs or attr in rec.lock_attrs or \
                    attr in rec.alias_locks:
                continue
            if attr in guarded:
                continue
            writers = rec.writes[attr] - {"__init__"}
            if not writers:
                continue
            touchers = rec.touches.get(attr, set()) - {"__init__"}
            if len(touchers) < 2:
                continue
            if "unguarded-attr" in rec.race_supp.get(attr, ()) or \
                    "*" in rec.race_supp.get(attr, ()):
                continue
            line = rec.first_write_line.get(attr, rec.line)
            supp = an.race_suppressions.get(rec.rel, {})
            if "unguarded-attr" in supp.get(line, ()) or \
                    "*" in supp.get(line, ()):
                continue
            out.append(Finding(
                "unguarded-attr", rec.rel, line,
                f"`{cname}.{attr}` is shared mutable state (written in "
                f"{sorted(writers)}, touched from "
                f"{len(touchers)} methods) in a lock-owning class but "
                f"carries no `# guarded-by:` — annotate it or justify "
                f"with `# mxrace: disable=unguarded-attr (why)`",
                snippet=f"{cname}.{attr}"))
    return out


# ----------------------------------------------------------------------
# lockfile (contracts/lockorder.json)
# ----------------------------------------------------------------------
def lockfile_dict(g: Graph) -> Dict[str, Any]:
    """Structure-only pin: lock names/kinds and the edge set.  Sites
    are deliberately excluded so unrelated line drift never dirties
    the contract."""
    return {
        "comment": "mxrace lock-order DAG; regenerate with "
                   "`python -m tools.mxrace --update`",
        "locks": {name: info["kind"]
                  for name, info in sorted(g.locks.items())},
        "edges": sorted(f"{a} -> {b}" for (a, b) in g.edges),
    }


def save_lockfile(d: Dict[str, Any],
                  path: Path = DEFAULT_LOCKFILE) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(d, indent=1, sort_keys=True) + "\n")


def load_lockfile(path: Path = DEFAULT_LOCKFILE
                  ) -> Optional[Dict[str, Any]]:
    if not path.exists():
        return None
    return json.loads(path.read_text())


def diff_lockfile(stored: Optional[Dict[str, Any]], g: Graph,
                  path: Path = DEFAULT_LOCKFILE
                  ) -> Tuple[List[Finding], List[str]]:
    """(findings, notices).  New edges are growth-only drift —
    findings; removed edges/locks and new locks are notices (code
    deleted or sync added without nesting is not a deadlock risk)."""
    rel = path.relative_to(REPO_ROOT).as_posix() \
        if path.is_relative_to(REPO_ROOT) else path.as_posix()
    current = lockfile_dict(g)
    if stored is None:
        return ([Finding(
            "lock-order-drift", rel, 1,
            f"{rel} missing — run `python -m tools.mxrace --update`",
            snippet="missing-lockfile")], [])
    findings: List[Finding] = []
    notices: List[str] = []
    old_edges = set(stored.get("edges", []))
    new_edges = set(current["edges"])
    for e in sorted(new_edges - old_edges):
        a, b = e.split(" -> ", 1)
        findings.append(Finding(
            "lock-order-drift", rel, 1,
            f"new lock-order edge `{e}` (first site "
            f"{_edge_site(g, a, b)}) not in the committed DAG — "
            f"review the nesting, then `python -m tools.mxrace "
            f"--update`",
            snippet=e))
    for e in sorted(old_edges - new_edges):
        notices.append(f"edge `{e}` vanished (stale lockfile entry; "
                       f"--update to prune)")
    old_locks = set(stored.get("locks", {}))
    new_locks = set(current["locks"])
    for n in sorted(new_locks - old_locks):
        notices.append(f"new lock `{n}` ({current['locks'][n]})")
    for n in sorted(old_locks - new_locks):
        notices.append(f"lock `{n}` vanished")
    return findings, notices


# ----------------------------------------------------------------------
# README lock-order table
# ----------------------------------------------------------------------
def render_lockorder_table(g: Graph) -> str:
    srcs: Dict[str, Set[str]] = {}
    for (a, b) in g.edges:
        srcs.setdefault(a, set()).add(b)
    lines = [LOCKORDER_BEGIN,
             "| holding | may acquire |",
             "|---|---|"]
    for a in sorted(srcs):
        tgts = ", ".join(f"`{b}`" for b in sorted(srcs[a]))
        lines.append(f"| `{a}` | {tgts} |")
    leaves = sorted(set(g.locks) - set(srcs))
    if leaves:
        lines.append("| *(leaf — acquire nothing)* | "
                     + ", ".join(f"`{n}`" for n in leaves) + " |")
    lines.append("")
    lines.append(f"*{len(g.locks)} locks, {len(g.edges)} edges; "
                 f"pinned in `contracts/lockorder.json`, regenerate "
                 f"with `python -m tools.mxrace --fix-readme`.*")
    lines.append(LOCKORDER_END)
    return "\n".join(lines)


def readme_drift(root: Path, g: Graph) -> List[Finding]:
    readme = root / "README.md"
    if not readme.exists():
        return [Finding("lockorder-readme-drift", "README.md", 1,
                        "README.md missing")]
    text = readme.read_text()
    if LOCKORDER_BEGIN not in text or LOCKORDER_END not in text:
        return [Finding(
            "lockorder-readme-drift", "README.md", 1,
            "README.md lacks the mxrace:lockorder markers — run "
            "`python -m tools.mxrace --fix-readme`")]
    current = text.split(LOCKORDER_BEGIN, 1)[1] \
                  .split(LOCKORDER_END, 1)[0]
    want = render_lockorder_table(g) \
        .split(LOCKORDER_BEGIN, 1)[1].split(LOCKORDER_END, 1)[0]
    if current.strip() != want.strip():
        line = text[:text.index(LOCKORDER_BEGIN)].count("\n") + 1
        return [Finding(
            "lockorder-readme-drift", "README.md", line,
            "README lock-order table is stale — run "
            "`python -m tools.mxrace --fix-readme`",
            snippet="lockorder-table")]
    return []


def fix_readme(root: Path, g: Graph) -> bool:
    readme = root / "README.md"
    text = readme.read_text()
    if LOCKORDER_BEGIN not in text or LOCKORDER_END not in text:
        raise SystemExit(
            f"README.md lacks the markers {LOCKORDER_BEGIN!r} … "
            f"{LOCKORDER_END!r}; add them where the table should live")
    head = text.split(LOCKORDER_BEGIN, 1)[0]
    tail = text.split(LOCKORDER_END, 1)[1]
    new = head + render_lockorder_table(g) + tail
    if new != text:
        readme.write_text(new)
        return True
    return False


# ----------------------------------------------------------------------
# one-call driver (CLI, tests, bench --contracts gate)
# ----------------------------------------------------------------------
def run_check(paths: Sequence[str] = SCOPES, root: Path = REPO_ROOT,
              lockfile: Path = DEFAULT_LOCKFILE, check_readme: bool =
              True) -> Tuple[List[Finding], List[str], Graph]:
    """(findings, notices, graph) for the full static pass."""
    an = scan(paths, root)
    g = build_graph(an)
    findings = list(an.parse_errors)
    findings.extend(cycle_findings(g))
    findings.extend(unguarded_findings(an))
    drift, notices = diff_lockfile(load_lockfile(lockfile), g, lockfile)
    findings.extend(drift)
    if check_readme:
        findings.extend(readme_drift(root, g))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, notices, g
