"""mxtpu.analysis — static analysis over lowered/compiled XLA
programs (ISSUE 6).

Three layers:

* :mod:`.hlo` — the structural HLO-text parser (the ONE in the tree);
* :mod:`.summary` — deterministic program summaries across the five
  rule families (collectives, custom-call brackets, dtype policy,
  budgets, host transfers) plus the report-only bracket evidence
  table;
* :mod:`.contracts` — committed lockfiles under ``contracts/`` and
  the check that compares a fresh summary against them
  (``python -m tools.hlocheck`` is the CLI).

Tests inspect compiled programs through :func:`compiled_summary` /
:func:`compiled_evidence` rather than grepping ``hlo_text()``
directly — mxlint's ``hlo-raw-assert`` rule enforces this.

The runtime audit (:func:`maybe_audit`, knob ``MXTPU_HLO_AUDIT``)
applies the contract-free hygiene subset — no host transfers, no f64
creep, no bracketed custom calls — to every program ``TrainStep`` and
serving's ``ModelRunner`` compile: ``1`` warns, ``2`` raises, unset
costs nothing.

:mod:`.memflow` (ISSUE 20) is the memory sibling: the ONE ``hbm_peak``
analyzer, per-device HBM decomposition, the five memory hazard rules,
and the committed ledgers under ``contracts/mem/`` (``python -m
tools.mxmem`` is the CLI; knob ``MXTPU_MEM_AUDIT``).
"""
from __future__ import annotations

import warnings
from typing import Dict, List, Optional, Tuple

from .hlo import HloProgram, parse_hlo
from .summary import (BRACKET_OPS, COLLECTIVE_OPS, HOST_TRANSFER_OPS,
                      audit_findings, bracket_evidence,
                      format_evidence_table, summarize)
from . import dtypeflow
from .dtypeflow import (cast_flows, dtype_summary, format_hazard,
                        hazard_findings, master_weight_findings,
                        program_ledger)
from . import memflow
from .memflow import (collective_scratch_bytes, decompose,
                      hazard_findings_mem, mem_audit_findings,
                      mem_stats)
from .contracts import (CONTRACTS_DIR, DEFAULT_TOLERANCES, Violation,
                        check_contract, contract_path, load_contract,
                        make_contract, save_contract)

__all__ = [
    "HloProgram", "parse_hlo", "summarize", "bracket_evidence",
    "format_evidence_table", "audit_findings", "Violation",
    "check_contract", "make_contract", "save_contract",
    "load_contract", "contract_path", "CONTRACTS_DIR",
    "DEFAULT_TOLERANCES", "COLLECTIVE_OPS", "BRACKET_OPS",
    "HOST_TRANSFER_OPS", "mem_stats", "compiled_artifact",
    "compiled_summary", "compiled_evidence", "maybe_audit",
    "audit_mode", "dtypeflow", "dtype_summary", "cast_flows",
    "hazard_findings", "format_hazard", "master_weight_findings",
    "program_ledger", "lowered_text", "lowered_summary",
    "prec_audit_mode", "audit_stamp", "needs_reaudit",
    "memflow", "decompose", "collective_scratch_bytes",
    "hazard_findings_mem", "mem_audit_findings", "mem_audit_mode",
]


def compiled_artifact(fn, *args, **jit_kwargs
                      ) -> Tuple[str, Optional[Dict[str, int]]]:
    """``(hlo_text, mem_stats)`` of ``fn`` lowered and compiled on
    the current backend — the sanctioned route for tests that need a
    compiled program (keeps raw ``.lower()``/``.hlo_text()`` calls
    out of ``tests/``)."""
    import jax
    compiled = jax.jit(fn, **jit_kwargs).lower(*args).compile()
    return compiled.as_text(), mem_stats(compiled)


def lowered_text(fn, *args, **jit_kwargs) -> str:
    """PRE-optimization HLO text of ``fn`` lowered (not compiled),
    with per-instruction ``metadata={op_name= source_file=
    source_line=}`` — mxprec's substrate.  The pre-opt dump keeps the
    program as written (a bf16 ``dot`` without
    ``preferred_element_type`` is still a bf16 dot, not the f32 op +
    round-trip converts backend float normalization rewrites it
    into), which is the level an AMP policy must reason at."""
    import jax
    from jax._src.lib import xla_extension as xe
    lowered = jax.jit(fn, **jit_kwargs).lower(*args)
    asm = lowered.compiler_ir().operation.get_asm(
        enable_debug_info=True)
    try:
        comp = xe.mlir.mlir_module_to_xla_computation(
            asm, use_tuple_args=False, return_tuple=False)
        opts = xe.HloPrintOptions()
        opts.print_metadata = True
        return comp.get_hlo_module().to_string(opts)
    except AttributeError as e:  # jaxlib drift: report, don't crash
        from mxtpu.base import MXNetError
        raise MXNetError(
            f"pre-optimization HLO conversion unavailable on this "
            f"jaxlib ({e}) — mxprec needs "
            f"xla_extension.mlir.mlir_module_to_xla_computation")


def lowered_summary(fn, *args, **jit_kwargs) -> Dict:
    """``program_ledger`` of the PRE-optimization lowering of ``fn``
    — the sanctioned route for tests that need dtype-flow facts about
    a program as written."""
    return program_ledger(lowered_text(fn, *args, **jit_kwargs))


def compiled_summary(fn, *args, **jit_kwargs) -> Dict:
    """Contract-shaped summary of ``fn`` compiled on the current
    backend."""
    text, mem = compiled_artifact(fn, *args, **jit_kwargs)
    return summarize(text, mem)


def compiled_evidence(fn, *args, **jit_kwargs) -> List[Dict[str, str]]:
    """Custom-call bracket evidence rows for ``fn`` compiled on the
    current backend."""
    text, _ = compiled_artifact(fn, *args, **jit_kwargs)
    return bracket_evidence(parse_hlo(text))


# ----------------------------------------------------------------------
# runtime audit (MXTPU_HLO_AUDIT)
# ----------------------------------------------------------------------
def _knob_mode(name: str) -> int:
    from mxtpu import knobs
    v = str(knobs.get(name)).strip().lower()
    if v in ("", "0", "false", "off"):
        return 0
    return 2 if v == "2" else 1


def audit_mode() -> int:
    """0 off (default), 1 warn, 2 raise."""
    return _knob_mode("MXTPU_HLO_AUDIT")


def prec_audit_mode() -> int:
    """``MXTPU_PREC_AUDIT``: 0 off (default), 1 warn, 2 raise."""
    return _knob_mode("MXTPU_PREC_AUDIT")


def mem_audit_mode() -> int:
    """``MXTPU_MEM_AUDIT``: 0 off (default), 1 warn, 2 raise."""
    return _knob_mode("MXTPU_MEM_AUDIT")


def audit_stamp() -> Dict[str, int]:
    """This process's audit modes as the persistent-cache entry meta
    (``mxtpu.cache``): the knobs are per-process, so a disk entry
    records how strictly its WRITER audited and a reader with
    stricter modes re-audits the reloaded program instead of trusting
    the writer's (possibly absent) cold-birth audit."""
    return {"hlo_audit": audit_mode(), "prec_audit": prec_audit_mode(),
            "mem_audit": mem_audit_mode()}


def needs_reaudit(meta: Dict) -> bool:
    """True when this process audits more strictly than the writer of
    a cache entry stamped with ``meta`` did (missing/legacy stamps
    count as unaudited)."""
    def _m(v) -> int:
        return v if isinstance(v, int) else 0
    return (audit_mode() > _m(meta.get("hlo_audit"))
            or prec_audit_mode() > _m(meta.get("prec_audit"))
            or mem_audit_mode() > _m(meta.get("mem_audit")))


def maybe_audit(compiled, label: str = "",
                mem: Optional[Dict[str, int]] = None
                ) -> Optional[Dict]:
    """Audit one freshly compiled program if ``MXTPU_HLO_AUDIT`` /
    ``MXTPU_PREC_AUDIT`` ask for it; returns the summary (or None when
    both audits are off).  Called at compile sites only — compiles are
    rare and expensive, so reading the knobs here keeps the off path
    at zero overhead.

    The precision audit classifies dtypeflow hazards over the same
    compiled text; post-optimization dumps lack source metadata and
    normalize some sub-f32 math, so it catches the surviving forms
    (f64 creep, narrowing-accumulator reduce regions, sub-f32 dots) —
    the full pre-opt analysis lives in ``python -m tools.mxprec``.

    The memory audit (``MXTPU_MEM_AUDIT``) checks the program's peak
    HBM per device against the device-class budget
    (``MXTPU_MEM_BUDGET`` override, else contracts/mem/budgets.json)
    — the ledger-level decomposition lives in ``python -m
    tools.mxmem``."""
    mode = audit_mode()
    pmode = prec_audit_mode()
    mmode = mem_audit_mode()
    if not mode and not pmode and not mmode:
        return None
    if mem is None:
        mem = mem_stats(compiled)
    program = parse_hlo(compiled.as_text())
    summ = summarize(program, mem)
    if mode:
        findings = audit_findings(summ, label)
        if findings:
            msg = "HLO audit: " + "; ".join(findings)
            if mode >= 2:
                from mxtpu.base import MXNetError
                raise MXNetError(msg + " (MXTPU_HLO_AUDIT=2)")
            warnings.warn(msg, RuntimeWarning, stacklevel=3)
    if pmode:
        where = f" in {label}" if label else ""
        hazards = hazard_findings(program)
        if hazards:
            msg = (f"precision audit{where}: "
                   + "; ".join(format_hazard(h) for h in hazards))
            if pmode >= 2:
                from mxtpu.base import MXNetError
                raise MXNetError(msg + " (MXTPU_PREC_AUDIT=2)")
            warnings.warn(msg, RuntimeWarning, stacklevel=3)
    if mmode:
        mfindings = mem_audit_findings(mem, label)
        if mfindings:
            msg = "memory audit: " + "; ".join(mfindings)
            if mmode >= 2:
                from mxtpu.base import MXNetError
                raise MXNetError(msg + " (MXTPU_MEM_AUDIT=2)")
            warnings.warn(msg, RuntimeWarning, stacklevel=3)
    return summ
