"""mxtpu.analysis — static analysis over lowered/compiled XLA
programs (ISSUE 6).

Three layers:

* :mod:`.hlo` — the structural HLO-text parser (the ONE in the tree);
* :mod:`.summary` — deterministic program summaries across the five
  rule families (collectives, custom-call brackets, dtype policy,
  budgets, host transfers) plus the report-only bracket evidence
  table;
* :mod:`.contracts` — committed lockfiles under ``contracts/`` and
  the check that compares a fresh summary against them
  (``python -m tools.hlocheck`` is the CLI).

Tests inspect compiled programs through :func:`compiled_summary` /
:func:`compiled_evidence` rather than grepping ``hlo_text()``
directly — mxlint's ``hlo-raw-assert`` rule enforces this.

The runtime audit (:func:`maybe_audit`, knob ``MXTPU_HLO_AUDIT``)
applies the contract-free hygiene subset — no host transfers, no f64
creep, no bracketed custom calls — to every program ``TrainStep`` and
serving's ``ModelRunner`` compile: ``1`` warns, ``2`` raises, unset
costs nothing.
"""
from __future__ import annotations

import warnings
from typing import Dict, List, Optional, Tuple

from .hlo import HloProgram, parse_hlo
from .summary import (BRACKET_OPS, COLLECTIVE_OPS, HOST_TRANSFER_OPS,
                      audit_findings, bracket_evidence,
                      format_evidence_table, summarize)
from .contracts import (CONTRACTS_DIR, DEFAULT_TOLERANCES, Violation,
                        check_contract, contract_path, load_contract,
                        make_contract, save_contract)

__all__ = [
    "HloProgram", "parse_hlo", "summarize", "bracket_evidence",
    "format_evidence_table", "audit_findings", "Violation",
    "check_contract", "make_contract", "save_contract",
    "load_contract", "contract_path", "CONTRACTS_DIR",
    "DEFAULT_TOLERANCES", "COLLECTIVE_OPS", "BRACKET_OPS",
    "HOST_TRANSFER_OPS", "mem_stats", "compiled_artifact",
    "compiled_summary", "compiled_evidence", "maybe_audit",
    "audit_mode",
]


def mem_stats(compiled) -> Optional[Dict[str, int]]:
    """``memory_analysis()`` of a compiled program as the
    ``hbm_peak``-bearing dict (same shape as
    ``mxtpu.parallel._mem_stats``); None when the backend doesn't
    report."""
    from mxtpu.parallel import _mem_stats
    return _mem_stats(compiled)


def compiled_artifact(fn, *args, **jit_kwargs
                      ) -> Tuple[str, Optional[Dict[str, int]]]:
    """``(hlo_text, mem_stats)`` of ``fn`` lowered and compiled on
    the current backend — the sanctioned route for tests that need a
    compiled program (keeps raw ``.lower()``/``.hlo_text()`` calls
    out of ``tests/``)."""
    import jax
    compiled = jax.jit(fn, **jit_kwargs).lower(*args).compile()
    return compiled.as_text(), mem_stats(compiled)


def compiled_summary(fn, *args, **jit_kwargs) -> Dict:
    """Contract-shaped summary of ``fn`` compiled on the current
    backend."""
    text, mem = compiled_artifact(fn, *args, **jit_kwargs)
    return summarize(text, mem)


def compiled_evidence(fn, *args, **jit_kwargs) -> List[Dict[str, str]]:
    """Custom-call bracket evidence rows for ``fn`` compiled on the
    current backend."""
    text, _ = compiled_artifact(fn, *args, **jit_kwargs)
    return bracket_evidence(parse_hlo(text))


# ----------------------------------------------------------------------
# runtime audit (MXTPU_HLO_AUDIT)
# ----------------------------------------------------------------------
def audit_mode() -> int:
    """0 off (default), 1 warn, 2 raise."""
    from mxtpu import knobs
    v = str(knobs.get("MXTPU_HLO_AUDIT")).strip().lower()
    if v in ("", "0", "false", "off"):
        return 0
    return 2 if v == "2" else 1


def maybe_audit(compiled, label: str = "",
                mem: Optional[Dict[str, int]] = None
                ) -> Optional[Dict]:
    """Audit one freshly compiled program if ``MXTPU_HLO_AUDIT`` asks
    for it; returns the summary (or None when the audit is off).
    Called at compile sites only — compiles are rare and expensive,
    so reading the knob here keeps the off path at zero overhead."""
    mode = audit_mode()
    if not mode:
        return None
    summ = summarize(compiled.as_text(),
                     mem if mem is not None else mem_stats(compiled))
    findings = audit_findings(summ, label)
    if findings:
        msg = "HLO audit: " + "; ".join(findings)
        if mode >= 2:
            from mxtpu.base import MXNetError
            raise MXNetError(msg + " (MXTPU_HLO_AUDIT=2)")
        warnings.warn(msg, RuntimeWarning, stacklevel=3)
    return summ
