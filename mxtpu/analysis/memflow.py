"""memflow — the ONE memory-footprint analyzer (ISSUE 20).

Exactly as :mod:`.dtypeflow` consolidated dtype facts behind one
analyzer, this module owns every byte-accounting fact in the tree:

* :func:`mem_stats` — XLA ``memory_analysis()`` as a plain dict with
  the repo-wide ``hbm_peak`` = temp + argument convention (moved here
  from ``mxtpu.parallel._mem_stats``, which now delegates);
* :func:`opt_state_leaf_bytes` — per-device optimizer-state bytes
  (ZeRO-sharded leaves count only the local shard);
* :func:`decompose` — peak HBM per device split into params /
  optimizer state / activations+temps / collectives scratch / KV
  table / donated-aliased / other-input bytes;
* the five hazard rules (mxprec finding shape — ``rule``/``op``/
  ``site``/``detail``): **donation-missed**, **zero-replication**
  (:func:`mxtpu.parallel.plan_zero_buckets` is the oracle),
  **kv-overcommit**, **padding-waste**, **budget-exceeded** (against
  the declarative per-device-class budgets in
  ``contracts/mem/budgets.json``);
* committed-ledger build/compare for ``contracts/mem/<target>.json``
  (``python -m tools.mxmem`` is the CLI; serialization matches the
  repo lockfile idiom, so ``--update`` -> ``--check`` is a
  byte-identical fixed point) and the README HBM table.

The runtime knob ``MXTPU_MEM_AUDIT`` (1 warn / 2 raise) applies
:func:`mem_audit_findings` — the budget check — to every program
``TrainStep`` / ``ModelRunner`` / ``GenerateRunner`` compiles, via
``analysis.maybe_audit`` beside the HLO/PREC audits.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from .hlo import HloProgram, parse_hlo
from .summary import COLLECTIVE_OPS

REPO_ROOT = Path(__file__).resolve().parents[2]

MEM_SUBDIR = "mem"
BUDGETS_NAME = "budgets"

MEM_BEGIN = "<!-- mxmem:hbm:begin -->"
MEM_END = "<!-- mxmem:hbm:end -->"

# padding-waste thresholds: a pad is a finding only when it wastes
# both a meaningful FRACTION of the buffer and a meaningful number of
# absolute bytes (tiny fixtures pad a few rows by design)
PAD_WASTE_FRAC = 0.25
PAD_WASTE_MIN_BYTES = 1 << 16

# optimizer kind -> f32 state leaves per parameter (adam: m+v; the
# momentum family: one velocity; plain sgd: none).  The oracle the
# zero-replication rule scales plan_zero_buckets geometry by.
STATE_LEAVES = {"adam": 2, "adamw": 2, "lamb": 2, "rmsprop": 2,
                "ftrl": 2, "adagrad": 1, "sgd": 1, "nag": 1}

_MIB = 1024.0 * 1024.0


# ----------------------------------------------------------------------
# mem stats (the hbm_peak convention — canonical here)
# ----------------------------------------------------------------------
def mem_stats(compiled) -> Optional[Dict[str, int]]:
    """``memory_analysis()`` of a compiled program as a plain dict
    (None when the backend doesn't report).  ``hbm_peak`` is
    temp + argument bytes — the resident high-water the program needs
    beyond its outputs.  Every committed peak-bytes budget in
    ``contracts/`` pins this exact convention."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out: Dict[str, int] = {}
    for k in ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            out[k] = int(v)
    out["hbm_peak"] = (out.get("temp_size_in_bytes", 0) +
                       out.get("argument_size_in_bytes", 0))
    return out


def opt_state_leaf_bytes(opt_state) -> int:
    """Optimizer-state bytes resident PER DEVICE: replicated leaves
    count in full, sharded leaves only the local shard (the dp×
    saving ZeRO-1 exists for).  ``TrainStep.opt_state_bytes``
    delegates here."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(opt_state):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            total += shards[0].data.nbytes
        else:
            total += int(getattr(leaf, "nbytes", 0))
    return total


def collective_scratch_bytes(program: Union[str, HloProgram]) -> int:
    """Bytes materialized by collective results in one program —
    the exchange buffers the compiled step keeps live during
    all-reduce / reduce-scatter / all-gather (async ``-start`` forms
    count once; their ``-done`` halves are skipped)."""
    if isinstance(program, str):
        program = parse_hlo(program)
    total = 0
    for comp in program.computations.values():
        for instr in comp.instructions:
            op = instr.opcode
            if op.endswith("-done") and op[:-5] in COLLECTIVE_OPS:
                continue
            kind = op[:-6] if op.endswith("-start") else op
            if kind in COLLECTIVE_OPS:
                total += instr.result_bytes()
    return total


# ----------------------------------------------------------------------
# decomposition
# ----------------------------------------------------------------------
def decompose(mem: Optional[Dict[str, int]], *,
              params_bytes: int = 0, opt_state_bytes: int = 0,
              kv_table_bytes: int = 0,
              collective_scratch: int = 0) -> Dict[str, int]:
    """Split one program's per-device footprint into the ledger
    categories.  ``params`` / ``opt_state`` / ``kv_table`` are
    semantic byte counts the caller attributes (they ride inside the
    argument buffers); ``inputs_other`` is the argument remainder
    (batches, frozen params, rng keys, hyperparameters);
    ``collectives_scratch`` is a report-only attribution WITHIN the
    temp bytes, not an additional term.  ``peak_hbm`` keeps the
    repo-wide temp + argument convention byte-for-byte."""
    mem = mem or {}
    arg = int(mem.get("argument_size_in_bytes", 0))
    temp = int(mem.get("temp_size_in_bytes", 0))
    attributed = params_bytes + opt_state_bytes + kv_table_bytes
    return {
        "params": int(params_bytes),
        "opt_state": int(opt_state_bytes),
        "kv_table": int(kv_table_bytes),
        "activations_temps": temp,
        "collectives_scratch": int(collective_scratch),
        "donated_aliased": int(mem.get("alias_size_in_bytes", 0)),
        "inputs_other": max(0, arg - attributed),
        "output": int(mem.get("output_size_in_bytes", 0)),
        "peak_hbm": temp + arg,
    }


# ----------------------------------------------------------------------
# hazard rules (mxprec finding shape: rule / op / site / detail)
# ----------------------------------------------------------------------
def _finding(rule: str, op: str, site: str, detail: str) -> Dict:
    return {"rule": rule, "op": op, "site": site, "detail": detail}


def donation_hazards(record: Dict) -> List[Dict]:
    """**donation-missed** — a donatable argument buffer (declared by
    the runner's geometry: the train-vals/opt-state pair, the serving
    input tuple, the decode KV slot table) is not in the program's
    donated set, so caller copy + callee output both stay resident
    and the footprint doubles for that buffer."""
    out: List[Dict] = []
    for prog in sorted(record.get("programs", {})):
        entry = record["programs"][prog]
        don = entry.get("donation")
        if not don:
            continue
        declared = {int(i) for i in don.get("declared", ())}
        for idx in sorted(don.get("donatable", {}),
                          key=lambda s: int(s)):
            if int(idx) in declared:
                continue
            info = don["donatable"][idx]
            out.append(_finding(
                "donation-missed", "parameter",
                f"{prog}:arg{idx}",
                f"{info.get('label', 'buffer')} "
                f"({int(info.get('bytes', 0))} B) is donatable but "
                f"not donated — pass donate_argnums so XLA aliases "
                f"it to the output instead of keeping both live"))
    return out


def zero_hazards(record: Dict) -> List[Dict]:
    """**zero-replication** — a ZeRO target whose measured per-device
    optimizer-state bytes exceed the ``plan_zero_buckets`` shard
    geometry: the states are (partially) replicated where the plan
    says they must be sharded.  Fires only on targets DECLARED to
    shard (``expected``): the replicated baselines carry the oracle
    for comparison without tripping it."""
    z = record.get("zero")
    if not z or not z.get("expected", True):
        return []
    actual = int(z.get("opt_state_bytes", 0))
    planned = int(z.get("planned_shard_bytes", 0))
    if actual <= planned:
        return []
    return [_finding(
        "zero-replication", "opt-state",
        f"{record.get('target', '?')}:opt_state",
        f"optimizer state holds {actual} B/device but the "
        f"plan_zero_buckets dp={z.get('dp')} shard geometry allows "
        f"{planned} B — states are replicated, not sharded "
        f"({z.get('states_per_param')} leaves/param)")]


def kv_hazards(record: Dict) -> List[Dict]:
    """**kv-overcommit** — the decode KV slot table holds more bytes
    than the declared ``kv_cache_spec`` geometry plus the one scratch
    slot prefill padding scatters into."""
    kv = record.get("kv")
    if not kv:
        return []
    actual = int(kv.get("table_bytes", 0))
    expected = int(kv.get("expected_bytes", 0))
    if actual <= expected:
        return []
    spec = tuple(kv.get("spec", ()))
    return [_finding(
        "kv-overcommit", "kv-table",
        f"{record.get('target', '?')}:kv_table",
        f"KV slot table holds {actual} B but kv_cache_spec "
        f"{spec} + 1 scratch slot allows {expected} B — lanes grew "
        f"past the declared cache geometry")]


def padding_hazards(record: Dict, *, frac: float = PAD_WASTE_FRAC,
                    min_bytes: int = PAD_WASTE_MIN_BYTES
                    ) -> List[Dict]:
    """**padding-waste** — a bucket pads more than ``frac`` of its
    payload away (and more than ``min_bytes`` absolute): the ladder /
    shard geometry is burning HBM on zeros."""
    out: List[Dict] = []
    for row in record.get("padding", ()):
        used = int(row.get("used_bytes", 0))
        padded = int(row.get("padded_bytes", 0))
        waste = padded - used
        if used <= 0 or waste <= 0:
            continue
        if waste / used > frac and waste >= min_bytes:
            out.append(_finding(
                "padding-waste", "pad", str(row.get("site", "?")),
                f"{waste} B of padding on {used} B of payload "
                f"({100.0 * waste / used:.1f}% > "
                f"{100.0 * frac:.0f}% threshold)"))
    return out


def budget_hazards(record: Dict,
                   budgets: Optional[Dict]) -> List[Dict]:
    """**budget-exceeded** — a program's peak HBM per device exceeds
    the target's declared device-class budget
    (``contracts/mem/budgets.json``)."""
    if not budgets:
        return []
    cls, limit = resolve_budget(record.get("target", ""), budgets)
    if limit is None:
        return []
    out: List[Dict] = []
    for prog in sorted(record.get("programs", {})):
        entry = record["programs"][prog]
        mem = entry.get("mem") or {}
        peak = int(mem.get(
            "hbm_peak",
            int(mem.get("temp_size_in_bytes", 0))
            + int(mem.get("argument_size_in_bytes", 0))))
        if peak > limit:
            out.append(_finding(
                "budget-exceeded", "program", f"{prog}",
                f"peak {peak} B exceeds the {cls} device-class "
                f"budget of {limit} B — this target no longer fits "
                f"its declared device"))
    return out


def hazard_findings_mem(record: Dict,
                        budgets: Optional[Dict] = None) -> List[Dict]:
    """All memory hazards of one target record, sorted for
    byte-deterministic ledgers (same ordering contract as
    ``dtypeflow.hazard_findings``)."""
    out = (donation_hazards(record) + zero_hazards(record)
           + kv_hazards(record) + padding_hazards(record)
           + budget_hazards(record, budgets))
    return sorted(out, key=lambda h: (h["rule"], h["op"], h["site"],
                                      h["detail"]))


# ----------------------------------------------------------------------
# budgets (declarative, hand-edited — --update never rewrites an
# existing file, only bootstraps a missing one)
# ----------------------------------------------------------------------
DEFAULT_BUDGETS = {
    "comment": "Declarative per-device-class HBM budgets for "
               "`python -m tools.mxmem` (hand-edited; --update only "
               "bootstraps this file when missing).  The mem ledgers "
               "check every target's peak HBM/device against its "
               "class — the gate ROADMAP item 2's tensor-parallel "
               "dp x tp meshes will extend.",
    "classes": {
        "hbm16": {"bytes": 16 * 1024 ** 3,
                  "doc": "16 GiB HBM per device (v2/v3-era chip)"},
        "hbm32": {"bytes": 32 * 1024 ** 3,
                  "doc": "32 GiB HBM per device"},
        "host-ci": {"bytes": 2 * 1024 ** 3,
                    "doc": "2 GiB — the CPU-backend CI fixture "
                           "class every tiny contract target must "
                           "fit with room to spare"},
    },
    "default_class": "hbm16",
    "targets": {},
}


def mem_dir(directory: Path) -> Path:
    return Path(directory) / MEM_SUBDIR


def ledger_path(name: str, directory: Path) -> Path:
    return mem_dir(directory) / f"{name}.json"


def budgets_path(directory: Path) -> Path:
    return mem_dir(directory) / f"{BUDGETS_NAME}.json"


def load_budgets(directory: Path) -> Optional[Dict]:
    p = budgets_path(directory)
    if not p.exists():
        return None
    return json.loads(p.read_text())


def resolve_budget(target: str, budgets: Optional[Dict]
                   ) -> Tuple[Optional[str], Optional[int]]:
    """(device_class, byte limit) for one target; (None, None) when
    no budgets are declared."""
    if not budgets:
        return None, None
    cls = budgets.get("targets", {}).get(
        target, budgets.get("default_class"))
    info = budgets.get("classes", {}).get(cls)
    if info is None:
        return cls, None
    return cls, int(info.get("bytes", 0))


def _dump(obj) -> str:
    return json.dumps(obj, indent=1, sort_keys=True) + "\n"


def save_ledger(ledger: Dict, directory: Path) -> Path:
    path = ledger_path(ledger["target"], directory)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(_dump(ledger))
    return path


def load_ledger(name: str, directory: Path) -> Dict:
    return json.loads(ledger_path(name, directory).read_text())


def save_budgets(budgets: Dict, directory: Path) -> Path:
    path = budgets_path(directory)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(_dump(budgets))
    return path


def committed_ledgers(directory: Path) -> Dict[str, Dict]:
    d = mem_dir(directory)
    if not d.is_dir():
        return {}
    return {p.stem: json.loads(p.read_text())
            for p in sorted(d.glob("*.json"))
            if p.stem != BUDGETS_NAME}


def compare_ledgers(committed: Dict, fresh: Dict) -> List[str]:
    """Drift between a committed mem ledger and a fresh build — empty
    when byte-identical under the lockfile serialization."""
    from tools.mxprec.core import _diff
    if _dump(committed) == _dump(fresh):
        return []
    out: List[str] = []
    _diff(committed, fresh, "", out)
    return out or ["ledger drifted (serialization-level difference)"]


# ----------------------------------------------------------------------
# target records -> ledgers
# ----------------------------------------------------------------------
def build_ledger(record: Dict,
                 budgets: Optional[Dict] = None) -> Dict:
    """One target record (``tools/hlocheck/targets.py`` MEM_TARGETS
    builds these) into the committed ``contracts/mem/<target>.json``
    shape: per-program decomposition, the semantic sections (zero /
    kv / padding / donation), the resolved device-class budget, and
    the hazard findings — every value an int or a string, so two
    builds of the same tree are byte-identical."""
    target = record["target"]
    cls, limit = resolve_budget(target, budgets)
    programs: Dict[str, Dict] = {}
    peak = 0
    for prog in sorted(record.get("programs", {})):
        entry = record["programs"][prog]
        mem = entry.get("mem") or {}
        dec = decompose(
            mem,
            params_bytes=entry.get("params_bytes",
                                   record.get("params_bytes", 0)),
            opt_state_bytes=entry.get(
                "opt_state_bytes", record.get("opt_state_bytes") or 0),
            kv_table_bytes=entry.get("kv_table_bytes", 0),
            collective_scratch=entry.get("collective_scratch", 0))
        peak = max(peak, dec["peak_hbm"])
        row: Dict[str, Any] = {"decomposition": dec}
        if entry.get("donation"):
            row["donation"] = {
                "declared": sorted(int(i) for i in
                                   entry["donation"]["declared"]),
                "donatable": {
                    str(k): {"label": v.get("label", "buffer"),
                             "bytes": int(v.get("bytes", 0))}
                    for k, v in sorted(
                        entry["donation"]["donatable"].items(),
                        key=lambda kv: int(kv[0]))}}
        programs[prog] = row
    ledger: Dict[str, Any] = {
        "comment": "mxmem memory ledger -- regenerate with "
                   f"`python -m tools.mxmem --update {target}`",
        "target": target,
        "programs": programs,
        "peak_hbm": peak,
        "hazards": hazard_findings_mem(record, budgets),
    }
    if cls is not None:
        ledger["device_class"] = cls
        if limit:
            ledger["budget_bytes"] = limit
            ledger["headroom_frac"] = round(
                (limit - peak) / limit, 6)
    for key in ("zero", "kv"):
        if record.get(key):
            ledger[key] = {k: (list(v) if isinstance(v, tuple) else v)
                           for k, v in sorted(record[key].items())}
    if record.get("padding"):
        ledger["padding"] = [
            {"site": str(r["site"]),
             "used_bytes": int(r["used_bytes"]),
             "padded_bytes": int(r["padded_bytes"])}
            for r in record["padding"]]
    return ledger


# ----------------------------------------------------------------------
# record builders — the sanctioned views TrainStep / ModelRunner /
# GenerateRunner ``memory_summary()`` delegate to
# ----------------------------------------------------------------------
def _sig_bytes(shape: Sequence[int], dtype: str) -> int:
    import numpy as np
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def train_step_record(step, x, y, target: str = "train_step",
                      zero_expected: Optional[bool] = None) -> Dict:
    """Memory record of one ``TrainStep`` batch signature: ONE
    compile, then decomposition inputs (trainable-param bytes from
    ``param_sigs``, per-device optimizer-state bytes, collective
    scratch from the compiled text), the donation declaration
    (train-vals + opt-state are the donatable pair, ``donate=(0, 2)``
    when on), and — under ZeRO — the ``plan_zero_buckets`` oracle and
    its padding table."""
    compiled = step._compiled_for(x, y)
    mem = mem_stats(compiled) or {}
    scratch = collective_scratch_bytes(compiled.as_text())
    sigs = step.param_sigs(x, y)
    params_bytes = sum(_sig_bytes(shape, dt) for _, shape, dt in sigs)
    opt_bytes = step.opt_state_bytes()
    donation = {
        "declared": [0, 2] if step.donate else [],
        "donatable": {
            "0": {"label": "train_vals", "bytes": params_bytes},
            "2": {"label": "opt_state", "bytes": opt_bytes}}}
    record: Dict[str, Any] = {
        "target": target,
        "programs": {"train_step": {
            "mem": mem, "collective_scratch": scratch,
            "donation": donation}},
        "params_bytes": params_bytes,
        "opt_state_bytes": opt_bytes,
    }
    zero_dp = _zero_dp(step)
    if zero_dp:
        if zero_expected is None:
            # without a target-level declaration, a step claiming
            # ZeRO (``zero``) must deliver its plan; a deliberately
            # replicated step carries the oracle informationally
            zero_expected = bool(step.zero)
        record.update(zero_oracle(step, zero_dp,
                                  expected=zero_expected))
    return record


def _zero_dp(step) -> int:
    """dp width of a ZeRO-evaluated step (0 = not a zero target)."""
    if step.mesh is None or step.dp_axis not in step.mesh.shape:
        return 0
    dp = int(step.mesh.shape[step.dp_axis])
    return dp if dp > 1 else 0


def planned_shard_bytes(sigs: Sequence[Tuple], dp: int,
                        states_per_param: int = 2) -> int:
    """Planned per-device optimizer-state bytes for ``(shape,
    dtype)`` signatures sharded dp-wide: the ``plan_zero_buckets``
    geometry × the optimizer's f32 state-leaf count — THE
    zero-replication oracle (bench.py's dp8 projection uses it
    too)."""
    from mxtpu.parallel import plan_zero_buckets
    buckets = plan_zero_buckets(list(sigs), dp)
    return int(sum(states_per_param * b["padded_bytes"] // dp
                   for b in buckets))


def zero_oracle(step, dp: int,
                states_per_param: Optional[int] = None,
                expected: bool = True) -> Dict:
    """The zero-replication oracle for one step: planned per-device
    shard bytes from ``plan_zero_buckets`` geometry × the optimizer's
    state-leaf count, plus the per-bucket padding table.  Optimizer
    states are f32 regardless of the param storage dtype (the fp32-
    master rule mxprec enforces), so the plan is computed on f32
    signatures — and under AMP the sharded master copy counts as one
    more state leaf.  A step that SHOULD shard (``zero=0`` forced
    under a dp>1 mesh on a declared-ZeRO target) fails the rule
    exactly because its measured bytes exceed this plan."""
    from mxtpu.parallel import plan_zero_buckets
    kind = type(step.optimizer).__name__.lower()
    if states_per_param is None:
        states_per_param = STATE_LEAVES.get(kind, 2)
        if step.amp:
            states_per_param += 1  # the sharded fp32 master
    sigs = [(shape, "float32") for _, shape, _ in step.param_sigs()]
    buckets = plan_zero_buckets(sigs, dp)
    planned = planned_shard_bytes(sigs, dp, states_per_param)
    return {
        "zero": {"dp": dp, "optimizer": kind,
                 "states_per_param": int(states_per_param),
                 "planned_shard_bytes": int(planned),
                 "opt_state_bytes": int(step.opt_state_bytes()),
                 "sharded": bool(step.zero),
                 "expected": bool(expected)},
        "padding": [
            {"site": f"zero_bucket{j}"
                     f"[{b['stacked_shape']}:{b['dtype']}]",
             "used_bytes": b["param_bytes"],
             "padded_bytes": b["padded_bytes"]}
            for j, b in enumerate(buckets)],
    }


def runner_record(runner, target: str = "serving",
                  buckets: Optional[Sequence] = None) -> Dict:
    """Memory record of a ``ModelRunner`` bucket ladder: per-bucket
    decomposition (weights ride as the param-vals operand; the padded
    input tuple is the donatable arg 0)."""
    weight_bytes = runner.weight_bytes()
    programs: Dict[str, Dict] = {}
    for bucket in (buckets if buckets is not None
                   else runner.buckets()):
        batch, seq = bucket
        text, mem = runner.program_artifact(bucket)
        mem = mem or {}
        inputs = max(0, int(mem.get("argument_size_in_bytes", 0))
                     - weight_bytes)
        programs[f"bucket_b{batch}_s{seq}"] = {
            "mem": mem,
            "collective_scratch": collective_scratch_bytes(text),
            "donation": {
                "declared": [0] if runner._donate else [],
                "donatable": {"0": {"label": "input_batch",
                                    "bytes": inputs}}}}
    return {"target": target, "programs": programs,
            "params_bytes": weight_bytes}


def generate_record(runner, target: str = "generate",
                    buckets: Optional[Sequence] = None) -> Dict:
    """Memory record of a ``GenerateRunner``: per-rung prefill + the
    decode step.  The KV slot table is both the dominant argument
    buffer (attributed per program) and the donatable operand (last
    data arg of every entry); the kv section pins table bytes ==
    declared ``kv_cache_spec`` geometry + 1 scratch slot — the
    equality the kv-overcommit rule guards."""
    import numpy as np
    weight_bytes = runner.weight_bytes()
    itemsize = 4  # the slot table is float32 (new_cache)
    table_bytes = int(np.prod(runner._kv_shape,
                              dtype=np.int64)) * itemsize
    spec = tuple(runner.kv_spec)
    expected = kv_expected_bytes(spec, itemsize)
    programs: Dict[str, Dict] = {}
    for bucket in (buckets if buckets is not None
                   else runner.buckets()):
        kind, shp = bucket
        name = "decode_step" if kind == "decode" \
            else f"prefill_b{shp[0]}_s{shp[1]}"
        text, mem = runner.program_artifact(bucket)
        mem = mem or {}
        # the kv table is the LAST data operand of every entry
        kv_argnum = 2 if kind == "decode" else 3
        programs[name] = {
            "mem": mem,
            "collective_scratch": collective_scratch_bytes(text),
            "kv_table_bytes": table_bytes,
            "donation": {
                "declared": [kv_argnum] if runner._donate else [],
                "donatable": {str(kv_argnum): {
                    "label": "kv_table", "bytes": table_bytes}}}}
    return {
        "target": target, "programs": programs,
        "params_bytes": weight_bytes,
        "kv": {"spec": list(spec), "itemsize": itemsize,
               "slots": int(runner._kv_shape[2]),
               "table_bytes": table_bytes,
               "expected_bytes": expected},
    }


def kv_expected_bytes(kv_spec: Sequence[int],
                      itemsize: int = 4) -> int:
    """Bytes the declared ``kv_cache_spec`` geometry allows the slot
    table: the spec's lane count plus ONE scratch slot."""
    spec = tuple(int(d) for d in kv_spec)
    shape = spec[:2] + (spec[2] + 1,) + spec[3:]
    n = 1
    for d in shape:
        n *= d
    return n * int(itemsize)


def summary_view(record: Dict,
                 budgets: Optional[Dict] = None) -> Dict:
    """The ``memory_summary()`` dict the runners expose: per-program
    decomposition + hazards — the sanctioned alternative to raw
    ``compiled.memory_analysis()`` grepping (mxlint's ``mem-hygiene``
    rule)."""
    led = build_ledger(record, budgets)
    out = {"target": led["target"],
           "programs": {p: v["decomposition"]
                        for p, v in led["programs"].items()},
           "peak_hbm": led["peak_hbm"],
           "hazards": led["hazards"]}
    for key in ("zero", "kv", "device_class", "budget_bytes"):
        if key in led:
            out[key] = led[key]
    return out


# ----------------------------------------------------------------------
# runtime audit (MXTPU_MEM_AUDIT via analysis.maybe_audit)
# ----------------------------------------------------------------------
def mem_audit_findings(mem: Optional[Dict[str, int]],
                       label: str = "") -> List[str]:
    """The contract-free memory audit for freshly compiled programs:
    peak HBM per device against the default device-class budget
    (``MXTPU_MEM_BUDGET`` overrides the byte limit for tests /
    constrained deploys; 0 = use ``contracts/mem/budgets.json``'s
    default class).  Ledger checks live in ``python -m
    tools.mxmem``."""
    if not mem:
        return []
    from mxtpu import knobs
    limit = int(knobs.get("MXTPU_MEM_BUDGET"))
    cls = "MXTPU_MEM_BUDGET"
    if not limit:
        budgets = load_budgets(REPO_ROOT / "contracts")
        if not budgets:
            return []
        cls, limit = resolve_budget("", budgets)
        if not limit:
            return []
    peak = int(mem.get("hbm_peak", 0))
    where = f" in {label}" if label else ""
    if peak > limit:
        return [f"peak HBM {peak} B{where} exceeds the {cls} budget "
                f"of {limit} B"]
    return []


# ----------------------------------------------------------------------
# README table (committed ledgers -> markdown between markers)
# ----------------------------------------------------------------------
def _mib(n: int) -> str:
    return f"{n / _MIB:.2f}"


def _ledger_row(name: str, led: Dict) -> str:
    params = opt = act = kv = 0
    for prog in led.get("programs", {}).values():
        d = prog.get("decomposition", {})
        params = max(params, d.get("params", 0))
        opt = max(opt, d.get("opt_state", 0))
        act = max(act, d.get("activations_temps", 0))
        kv = max(kv, d.get("kv_table", 0))
    peak = led.get("peak_hbm", 0)
    cls = led.get("device_class", "—")
    hazards = len(led.get("hazards", []))
    return (f"| {name} | {len(led.get('programs', {}))} "
            f"| {_mib(params)} | {_mib(opt)} | {_mib(act)} "
            f"| {_mib(kv)} | {_mib(peak)} | {cls} | {hazards} |")


def render_mem_table(ledgers: Dict[str, Dict]) -> str:
    lines = [MEM_BEGIN,
             "| target | programs | params | opt state | activ+temps"
             " | KV table | peak HBM | class | hazards |",
             "|---|---|---|---|---|---|---|---|---|"]
    for name in sorted(ledgers):
        lines.append(_ledger_row(name, ledgers[name]))
    lines.append("")
    lines.append(f"*MiB per device (max over each target's programs);"
                 f" committed in `contracts/mem/`, regenerate with "
                 f"`python -m tools.mxmem --fix-readme`.*")
    lines.append(MEM_END)
    return "\n".join(lines)


def readme_drift(root: Path, ledgers: Dict[str, Dict]) -> List[str]:
    readme = root / "README.md"
    if not readme.exists():
        return ["README.md missing"]
    text = readme.read_text()
    if MEM_BEGIN not in text or MEM_END not in text:
        return ["README.md lacks the mxmem:hbm markers — run "
                "`python -m tools.mxmem --fix-readme`"]
    current = text.split(MEM_BEGIN, 1)[1].split(MEM_END, 1)[0]
    want = render_mem_table(ledgers) \
        .split(MEM_BEGIN, 1)[1].split(MEM_END, 1)[0]
    if current.strip() != want.strip():
        return ["README memory table is stale — run "
                "`python -m tools.mxmem --fix-readme`"]
    return []


def fix_readme(root: Path, ledgers: Dict[str, Dict]) -> bool:
    readme = root / "README.md"
    text = readme.read_text()
    if MEM_BEGIN not in text or MEM_END not in text:
        raise SystemExit(
            f"README.md lacks the markers {MEM_BEGIN!r} … "
            f"{MEM_END!r}; add them where the table should live")
    head = text.split(MEM_BEGIN, 1)[0]
    tail = text.split(MEM_END, 1)[1]
    new = head + render_mem_table(ledgers) + tail
    if new != text:
        readme.write_text(new)
        return True
    return False
