"""Contract lockfiles for compiled programs (``contracts/*.json``).

A contract pins, per named program of a target (e.g. the
``train_step`` of ``bert_zero``), the summary produced by
``mxtpu.analysis.summarize``.  ``check_contract`` compares a fresh
summary against the stored one under the five rule families:

* ``collectives`` — exact match both ways.  A *vanished*
  reduce-scatter is as alarming as a new all-reduce (it means ZeRO
  silently fell back to the replicated path).
* ``custom-call-bracket`` — per-target call count exact; bracketed
  count may shrink (an improvement) but not grow.
* ``dtype-policy`` — f64 op count and each upcast pair may not grow.
* ``budget`` — fusion/instruction counts and peak bytes must stay
  within ``stored * (1 + tolerance)``; dropping *below*
  ``stored * (1 - tolerance)`` is reported as a notice (regenerate
  the lockfile to bank the win), not a failure.
* ``host-transfer`` — the transfer count may not grow.

Violations fail ``--check``; notices don't.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]
CONTRACTS_DIR = REPO_ROOT / "contracts"

DEFAULT_TOLERANCES = {"fusion_count": 0.10,
                      "instruction_count": 0.10,
                      "peak_bytes": 0.10}


class Violation:
    __slots__ = ("rule", "target", "program", "message")

    def __init__(self, rule: str, target: str, program: str,
                 message: str):
        self.rule = rule
        self.target = target
        self.program = program
        self.message = message

    def format(self) -> str:
        return (f"{self.target}/{self.program}: [{self.rule}] "
                f"{self.message}")

    def as_json(self) -> Dict[str, str]:
        return {"rule": self.rule, "target": self.target,
                "program": self.program, "message": self.message}


def make_contract(target: str,
                  programs: Dict[str, Dict],
                  tolerances: Optional[Dict[str, float]] = None
                  ) -> Dict:
    return {
        "comment": "hlocheck lockfile -- regenerate with "
                   f"`python -m tools.hlocheck --update {target}`",
        "target": target,
        "tolerances": dict(tolerances or DEFAULT_TOLERANCES),
        "programs": programs,
    }


def contract_path(target: str,
                  directory: Path = CONTRACTS_DIR) -> Path:
    return directory / f"{target}.json"


def save_contract(contract: Dict,
                  directory: Path = CONTRACTS_DIR) -> Path:
    directory.mkdir(parents=True, exist_ok=True)
    path = contract_path(contract["target"], directory)
    path.write_text(json.dumps(contract, indent=1, sort_keys=True)
                    + "\n")
    return path


def load_contract(target: str,
                  directory: Path = CONTRACTS_DIR) -> Dict:
    return json.loads(contract_path(target, directory).read_text())


def _check_collectives(stored: Dict, observed: Dict, target: str,
                       program: str) -> List[Violation]:
    out = []
    for op in sorted(set(stored) | set(observed)):
        s, o = stored.get(op), observed.get(op)
        if s is None:
            out.append(Violation(
                "collectives", target, program,
                f"new collective `{op}` not in contract: {o}"))
        elif o is None:
            out.append(Violation(
                "collectives", target, program,
                f"collective `{op}` vanished (contract has {s})"))
        elif s != o:
            out.append(Violation(
                "collectives", target, program,
                f"`{op}` drifted: contract {s} vs observed {o}"))
    return out


def _check_custom_calls(stored: Dict, observed: Dict, target: str,
                        program: str) -> List[Violation]:
    out = []
    for tgt in sorted(set(stored) | set(observed)):
        s, o = stored.get(tgt), observed.get(tgt)
        if s is None:
            out.append(Violation(
                "custom-call-bracket", target, program,
                f"new custom call `{tgt}` not in contract: {o}"))
            continue
        if o is None:
            out.append(Violation(
                "custom-call-bracket", target, program,
                f"custom call `{tgt}` vanished (kernel silently "
                f"off?); contract has {s}"))
            continue
        if o["count"] != s["count"]:
            out.append(Violation(
                "custom-call-bracket", target, program,
                f"`{tgt}` call count {s['count']} -> {o['count']}"))
        if o["bracketed"] > s["bracketed"]:
            out.append(Violation(
                "custom-call-bracket", target, program,
                f"`{tgt}` grew layout brackets: {s['bracketed']} -> "
                f"{o['bracketed']} transpose/copy/bitcast ops at the "
                f"call boundary"))
    return out


def _check_dtype(stored: Dict, observed: Dict, target: str,
                 program: str) -> List[Violation]:
    out = []
    if observed.get("f64_ops", 0) > stored.get("f64_ops", 0):
        out.append(Violation(
            "dtype-policy", target, program,
            f"f64 ops grew {stored.get('f64_ops', 0)} -> "
            f"{observed.get('f64_ops', 0)} (silent f32->f64 "
            f"promotion)"))
    s_up = stored.get("upcasts", {})
    for pair, n in sorted(observed.get("upcasts", {}).items()):
        if n > s_up.get(pair, 0):
            out.append(Violation(
                "dtype-policy", target, program,
                f"upcast `{pair}` grew {s_up.get(pair, 0)} -> {n}"))
    return out


def _check_budgets(stored: Dict, observed: Dict, tol: Dict,
                   target: str, program: str
                   ) -> Tuple[List[Violation], List[str]]:
    out, notices = [], []
    for key in sorted(set(stored) | set(observed)):
        s, o = stored.get(key), observed.get(key)
        if s is None or o is None:
            # a budget appearing/vanishing (e.g. backend stopped
            # reporting memory stats) is drift worth failing on
            out.append(Violation(
                "budget", target, program,
                f"budget `{key}`: contract {s} vs observed {o}"))
            continue
        t = tol.get(key, DEFAULT_TOLERANCES.get(key, 0.10))
        if o > s * (1 + t):
            out.append(Violation(
                "budget", target, program,
                f"`{key}` over budget: {o} > {s} (+{t:.0%} "
                f"tolerance)"))
        elif o < s * (1 - t):
            notices.append(
                f"{target}/{program}: `{key}` improved {s} -> {o} "
                f"(>{t:.0%} under contract — regenerate the lockfile "
                f"to bank it)")
    return out, notices


def _check_host(stored: Dict, observed: Dict, target: str,
                program: str) -> List[Violation]:
    if observed.get("count", 0) > stored.get("count", 0):
        return [Violation(
            "host-transfer", target, program,
            f"host transfers grew {stored.get('count', 0)} -> "
            f"{observed.get('count', 0)}: {observed.get('ops')}")]
    return []


def check_contract(contract: Dict,
                   observed_programs: Dict[str, Dict]
                   ) -> Tuple[List[Violation], List[str]]:
    """(violations, notices) of observed summaries vs the lockfile."""
    target = contract.get("target", "?")
    tol = contract.get("tolerances", DEFAULT_TOLERANCES)
    stored_programs = contract.get("programs", {})
    violations: List[Violation] = []
    notices: List[str] = []
    for prog in sorted(set(stored_programs) | set(observed_programs)):
        s, o = stored_programs.get(prog), observed_programs.get(prog)
        if s is None:
            violations.append(Violation(
                "contract", target, prog,
                "program not in contract — run --update"))
            continue
        if o is None:
            violations.append(Violation(
                "contract", target, prog,
                "program in contract but not produced by the "
                "target"))
            continue
        violations += _check_collectives(
            s.get("collectives", {}), o.get("collectives", {}),
            target, prog)
        violations += _check_custom_calls(
            s.get("custom_calls", {}), o.get("custom_calls", {}),
            target, prog)
        violations += _check_dtype(
            s.get("dtype", {}), o.get("dtype", {}), target, prog)
        v, n = _check_budgets(
            s.get("budgets", {}), o.get("budgets", {}), tol,
            target, prog)
        violations += v
        notices += n
        violations += _check_host(
            s.get("host_transfers", {}), o.get("host_transfers", {}),
            target, prog)
    return violations, notices
