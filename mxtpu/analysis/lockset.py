"""mxrace Pass 2 — deterministic Eraser-style lockset sanitizer.

The static pass (``concurrency.py``) proves lock-order facts the AST
can see; this pass checks the ones it can't — watcher callbacks,
aliased locks, ``*_locked`` conventions actually honored at runtime —
by instrumenting the clock-injected sync-mode tests:

* ``threading.Lock``/``RLock`` are monkeypatched so every
  acquire/release updates a per-thread held-set (Condition-compatible:
  ``wait()`` correctly drops the lock while parked);
* attribute access on instrumented classes updates per-``(object,
  attr)`` *candidate locksets* — the intersection of locks held at
  every access.  Lockset refinement is schedule-independent: accesses
  under ``{A}`` then ``{B}`` intersect to ∅ no matter how threads
  interleave, which is what makes this sanitizer deterministic enough
  to gate CI on single-threaded sync-mode tests.

Rules (each seeded-race fixture in tests/test_race.py trips exactly
one):

* ``lockset-empty``      — a tracked shared attr's candidate lockset
  became empty; reported with BOTH access sites.
* ``guarded-by-violation`` — an attr annotated ``# guarded-by: L``
  was touched while ``L`` was not held (the dynamic twin of mxlint's
  lock-discipline rule, but alias- and call-path-aware).
* ``lock-order``         — a runtime acquisition order contradicts an
  already-observed order (cycle ⇒ potential deadlock).

Zero overhead when off: nothing here is imported unless the
``MXTPU_RACE`` knob (or a test) activates a checker, mirroring the
obs layer's off-is-free contract.
"""
from __future__ import annotations

import sys
import threading
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_THIS_FILE = str(Path(__file__).resolve())


def _skip_frame(f) -> bool:
    fname = f.f_code.co_filename
    return (str(Path(fname).resolve()) == _THIS_FILE
            or Path(fname).name == "threading.py")


def _site_of(frame) -> str:
    f = frame
    while f is not None and _skip_frame(f):
        f = f.f_back
    if f is None:
        return "?:0"
    p = Path(f.f_code.co_filename)
    try:
        rel = p.relative_to(REPO_ROOT).as_posix()
    except ValueError:
        rel = p.name
    return f"{rel}:{f.f_lineno}"


class RaceReport:
    """One sanitizer finding."""

    __slots__ = ("rule", "subject", "sites", "message")

    def __init__(self, rule: str, subject: str, sites: List[str],
                 message: str):
        self.rule = rule
        self.subject = subject
        self.sites = sites
        self.message = message

    def format(self) -> str:
        return (f"[{self.rule}] {self.subject}: {self.message} "
                f"(sites: {', '.join(self.sites)})")

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<RaceReport {self.format()}>"


class _TracedLock:
    """Wrapper around a real Lock/RLock that notifies the checker.
    Exposes the private Condition protocol (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``) so ``threading.Condition``
    built on a traced lock keeps exact wait semantics — including the
    held-set dropping while a waiter is parked."""

    def __init__(self, checker: "LocksetChecker", reentrant: bool):
        self._raw = _REAL_RLOCK() if reentrant else _REAL_LOCK()
        self._checker = checker
        self._reentrant = reentrant
        self.name: Optional[str] = None
        self.seq = checker._next_seq()
        # the checker keeps every traced lock alive: the order graph
        # and locksets key by id(), and a GC'd lock's id being reused
        # by a fresh one would alias stale edges onto it (a recycled
        # request's _wlock/cond pair can otherwise read as a
        # lock-order inversion of its predecessor's)
        checker._all_locks.append(self)

    # -- the public lock protocol ---------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            self._checker._on_acquire(self)
        return ok

    def release(self) -> None:
        self._checker._on_release(self)
        self._raw.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<TracedLock {self.label()} raw={self._raw!r}>"

    def label(self) -> str:
        return self.name or f"lock#{self.seq}"

    # -- Condition compatibility ----------------------------------------
    def _release_save(self):
        self._checker._on_release(self, full=True)
        if hasattr(self._raw, "_release_save"):
            return self._raw._release_save()
        self._raw.release()
        return None

    def _acquire_restore(self, state) -> None:
        if hasattr(self._raw, "_acquire_restore"):
            self._raw._acquire_restore(state)
        else:
            self._raw.acquire()
        self._checker._on_acquire(self)

    def _is_owned(self) -> bool:
        if hasattr(self._raw, "_is_owned"):
            return self._raw._is_owned()
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True

    def _at_fork_reinit(self) -> None:  # pragma: no cover
        if hasattr(self._raw, "_at_fork_reinit"):
            self._raw._at_fork_reinit()


class _Held(threading.local):
    """Per-thread held-lock state."""

    def __init__(self):
        self.stack: List[_TracedLock] = []
        self.counts: Dict[int, int] = {}


def _traced_of(value: Any) -> Optional[_TracedLock]:
    """The traced lock behind ``value`` — unwrapping a Condition to
    the lock it synchronizes on."""
    if isinstance(value, _TracedLock):
        return value
    inner = getattr(value, "_lock", None)  # threading.Condition
    if isinstance(inner, _TracedLock):
        return inner
    return None


def _lock_id_of(value: Any) -> Optional[int]:
    lk = _traced_of(value)
    return None if lk is None else id(lk)


class LocksetChecker:
    """Patch point + report sink.  Use as a context manager::

        checker = LocksetChecker()
        checker.instrument(MyClass, attrs=("count",),
                           guarded={"items": "_lock"})
        with checker.activate():
            ... run the scenario ...
        assert not checker.reports
    """

    def __init__(self) -> None:
        self.reports: List[RaceReport] = []
        self._active = False
        self._held = _Held()
        self._mu = _REAL_LOCK()      # raw: guards the shared maps
        self._seq = 0
        self._all_locks: List[_TracedLock] = []   # id-reuse pin
        # (id(obj), attr) -> {"lockset": set of lock ids, "last": site}
        self._attrs: Dict[Tuple[int, str], Dict[str, Any]] = {}
        # runtime order edges: (id_a, id_b) -> site of first observation
        self._edges: Dict[Tuple[int, int], str] = {}
        self._adj: Dict[int, Set[int]] = {}
        self._reported: Set[Tuple[str, Any]] = set()
        # class instrumentation bookkeeping for restore
        self._patched: List[Tuple[type, Dict[str, Any]]] = []
        self._instrumented: List[Tuple[type, Set[str],
                                       Dict[str, str]]] = []

    # -- sequence / naming ------------------------------------------------
    def _next_seq(self) -> int:
        with self._mu:
            self._seq += 1
            return self._seq

    def name_lock(self, value: Any, name: str) -> None:
        """Give the traced lock behind ``value`` a stable report
        name (done automatically when a lock lands on an
        instrumented class's attribute).  The lock may predate this
        checker — a prior activation window created it — so work on
        the object itself, never a per-checker registry."""
        lk = _traced_of(value)
        if lk is not None and lk.name is None:
            lk.name = name

    # -- activation -------------------------------------------------------
    def activate(self) -> "LocksetChecker":
        if self._active:
            return self
        checker = self

        def make_lock():
            return _TracedLock(checker, reentrant=False)

        def make_rlock():
            return _TracedLock(checker, reentrant=True)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        for cls, tracked, guarded in self._instrumented:
            self._apply_instrumentation(cls, tracked, guarded)
        self._active = True
        return self

    def deactivate(self) -> None:
        if not self._active:
            return
        self._active = False
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        for cls, saved in reversed(self._patched):
            for name, orig in saved.items():
                if orig is None:
                    try:
                        delattr(cls, name)
                    except AttributeError:  # pragma: no cover
                        pass
                else:
                    setattr(cls, name, orig)
        self._patched.clear()

    def __enter__(self) -> "LocksetChecker":
        return self.activate()

    def __exit__(self, *exc) -> bool:
        self.deactivate()
        return False

    # -- class instrumentation -------------------------------------------
    def instrument(self, cls: type, attrs: Iterable[str] = (),
                   guarded: Optional[Dict[str, str]] = None) -> None:
        """Track ``attrs`` with candidate locksets and check
        ``guarded`` (attr -> lock-attr name) accesses dynamically.
        Takes effect at :meth:`activate`."""
        tracked = set(attrs)
        gmap = dict(guarded or {})
        self._instrumented.append((cls, tracked, gmap))
        if self._active:
            self._apply_instrumentation(cls, tracked, gmap)

    def _apply_instrumentation(self, cls: type, tracked: Set[str],
                               guarded: Dict[str, str]) -> None:
        checker = self
        watched = tracked | set(guarded)
        orig_get = cls.__getattribute__
        orig_set = cls.__setattr__
        saved = {
            "__getattribute__":
                cls.__dict__.get("__getattribute__"),
            "__setattr__": cls.__dict__.get("__setattr__"),
        }

        def __getattribute__(obj, name):
            if name in watched and checker._active:
                checker._on_access(obj, cls, name, orig_get,
                                   guarded.get(name),
                                   name in tracked, write=False)
            return orig_get(obj, name)

        def __setattr__(obj, name, value):
            if name in watched and checker._active:
                checker._on_access(obj, cls, name, orig_get,
                                   guarded.get(name),
                                   name in tracked, write=True)
            orig_set(obj, name, value)
            if checker._active and _lock_id_of(value) is not None:
                checker.name_lock(value, f"{cls.__name__}.{name}")

        cls.__getattribute__ = __getattribute__
        cls.__setattr__ = __setattr__
        self._patched.append((cls, saved))

    # -- lock events ------------------------------------------------------
    def _on_acquire(self, lock: _TracedLock) -> None:
        h = self._held
        lid = id(lock)
        c = h.counts.get(lid, 0)
        h.counts[lid] = c + 1
        if c:
            return  # reentrant re-acquire: order already recorded
        if self._active and h.stack:
            site = _site_of(sys._getframe(1))
            for prev in h.stack:
                self._order_edge(prev, lock, site)
        h.stack.append(lock)

    def _on_release(self, lock: _TracedLock,
                    full: bool = False) -> None:
        h = self._held
        lid = id(lock)
        c = h.counts.get(lid, 0)
        if c <= 1 or full:
            h.counts.pop(lid, None)
            try:
                h.stack.remove(lock)
            except ValueError:  # released by a non-acquiring thread
                pass
        else:
            h.counts[lid] = c - 1

    def _order_edge(self, a: _TracedLock, b: _TracedLock,
                    site: str) -> None:
        ka, kb = id(a), id(b)
        if ka == kb:
            return
        with self._mu:
            if (ka, kb) in self._edges:
                return
            self._edges[(ka, kb)] = site
            self._adj.setdefault(ka, set()).add(kb)
            # does b already reach a?  then (a -> b) closes a cycle
            hop = self._first_hop(kb, ka)
        if hop is None:
            return
        key = ("lock-order", (ka, kb))
        if key in self._reported:
            return
        self._reported.add(key)
        back_site = self._edges.get((kb, hop), "?")
        self.reports.append(RaceReport(
            "lock-order",
            f"{a.label()} -> {b.label()}",
            [site, back_site],
            f"acquired `{b.label()}` while holding `{a.label()}`, "
            f"but the opposite order was already observed — "
            f"deadlock-prone inversion"))

    def _first_hop(self, src: int, dst: int) -> Optional[int]:
        """First hop of some path src -> ... -> dst, else None."""
        seen: Set[int] = {src}
        queue: List[Tuple[int, int]] = [
            (v, v) for v in sorted(self._adj.get(src, ()))]
        while queue:
            u, hop = queue.pop(0)
            if u == dst:
                return hop
            if u in seen:
                continue
            seen.add(u)
            queue.extend((v, hop)
                         for v in sorted(self._adj.get(u, ())))
        return None

    # -- attribute events -------------------------------------------------
    def _on_access(self, obj: Any, cls: type, name: str, orig_get,
                   guard_attr: Optional[str], tracked: bool,
                   write: bool) -> None:
        frame = sys._getframe(2)
        # construction is single-threaded by definition; Eraser
        # excludes the init window so first-publication writes do not
        # poison the lockset
        f = frame
        while f is not None and _skip_frame(f):
            f = f.f_back
        if f is not None and f.f_code.co_name == "__init__" and \
                f.f_locals.get("self") is obj:
            return
        site = _site_of(frame)
        h = self._held
        held_ids = frozenset(id(lk) for lk in h.stack)
        subject = f"{cls.__name__}.{name}"
        if guard_attr is not None:
            try:
                lock_val = orig_get(obj, guard_attr)
            except AttributeError:
                lock_val = None
            lk = _traced_of(lock_val)
            # only locks created under THIS checker are checkable: a
            # raw lock (instance predates activation) or a leftover
            # from a prior checker's window notifies someone else's
            # held-set, so "not held" would be a false alarm
            if lk is not None and lk._checker is self \
                    and id(lk) not in held_ids:
                key = ("guarded-by-violation", (id(obj), name, site))
                if key not in self._reported:
                    self._reported.add(key)
                    self.reports.append(RaceReport(
                        "guarded-by-violation", subject, [site],
                        f"{'write' if write else 'read'} without "
                        f"holding `{guard_attr}` (annotated "
                        f"`# guarded-by: {guard_attr}`)"))
            return
        if not tracked:
            return
        key = (id(obj), name)
        with self._mu:
            st = self._attrs.get(key)
            if st is None:
                # "obj" pins the instance so id(obj) stays unique
                self._attrs[key] = {"lockset": set(held_ids),
                                    "last": site, "reported": False,
                                    "obj": obj}
                return
            st["lockset"] &= held_ids
            empty = not st["lockset"] and not st["reported"]
            prev = st["last"]
            st["last"] = site
            if empty:
                st["reported"] = True
        if empty:
            self.reports.append(RaceReport(
                "lockset-empty", subject, [prev, site],
                f"no single lock protects every access — candidate "
                f"lockset went empty at this "
                f"{'write' if write else 'read'}"))


# ----------------------------------------------------------------------
# default wiring: instrument the real serving/obs classes with the
# guarded-by annotations the static pass extracted
# ----------------------------------------------------------------------
def _dotted_module(rel: str) -> str:
    p = Path(rel)
    parts = list(p.parts)
    if p.stem == "__init__":
        parts = parts[:-1]
    else:
        parts[-1] = p.stem
    return ".".join(parts)


def install_default(checker: LocksetChecker) -> List[str]:
    """Instrument every lock-owning class the static pass knows,
    wiring its ``# guarded-by:`` annotations into dynamic checks.
    Returns the instrumented class names."""
    import importlib

    from . import concurrency

    an = concurrency.scan()
    done: List[str] = []
    for cname in sorted(an.classes):
        rec = an.classes[cname]
        if not (rec.has_locks() and rec.guarded):
            continue
        try:
            mod = importlib.import_module(_dotted_module(rec.rel))
        except ImportError:  # pragma: no cover - broken tree
            continue
        cls = getattr(mod, cname, None)
        if cls is None:
            continue
        guarded = {attr: lk for attr, lk in sorted(rec.guarded.items())}
        checker.instrument(cls, attrs=(), guarded=guarded)
        done.append(cname)
    return done
