"""Program summaries: the five rule families of ISSUE 6 distilled
from one parsed HLO module.

``summarize()`` produces the deterministic dict that becomes a
contract lockfile (``contracts/*.json``): only opcode counts, byte
totals, dtype pairs, and budgets — never instruction names, channel
ids, or anything else XLA is free to renumber between lowerings
(pinned by tests/test_analysis.py's two-lowering stability test).

``bracket_evidence()`` is the report-only companion: the per-call-site
table of transpose/copy/bitcast ops feeding or consuming custom calls
that ROADMAP item 3 asks for.  It names instructions, so it stays out
of the lockfile.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Union

from .dtypeflow import dtype_summary, is_upcast as _is_upcast
from .hlo import (DTYPE_BYTES, _FLOAT_WIDTH, Computation, HloProgram,
                  Instruction, parse_hlo, shape_elems)

# collective ops inventoried exactly (async `-start` forms count once,
# their `-done` halves are skipped)
COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                  "all-to-all", "collective-permute",
                  "collective-broadcast")

# layout-shuffling ops that, adjacent to a custom call, mean XLA is
# paying data movement to satisfy the call's operand/result layouts
BRACKET_OPS = ("transpose", "copy", "bitcast", "bitcast-convert")

# host <-> device traffic visible in the program itself
HOST_TRANSFER_OPS = ("infeed", "outfeed", "send", "recv",
                     "send-done", "recv-done")
_HOST_TARGET_MARKERS = ("callback", "host", "infeed", "outfeed")


def _fmt_shapes(instr: Instruction) -> str:
    return ", ".join(f"{dt}[{','.join(str(d) for d in dims)}]"
                     for dt, dims in instr.shapes)


def _chase_gte(comp: Computation,
               instr: Optional[Instruction]) -> Optional[Instruction]:
    seen = 0
    while instr is not None and \
            instr.opcode == "get-tuple-element" and seen < 8:
        instr = comp.by_name.get(instr.operands[0]) \
            if instr.operands else None
        seen += 1
    return instr


def _fusion_bracket_ops(program: HloProgram,
                        fusion: Instruction) -> List[Instruction]:
    out: List[Instruction] = []
    for cname in fusion.calls:
        comp = program.computations.get(cname)
        if comp is None:
            continue
        out.extend(i for i in comp.instructions
                   if i.opcode in BRACKET_OPS)
    return out


def bracket_evidence(program: HloProgram) -> List[Dict[str, str]]:
    """Per-call-site rows: every transpose/copy/bitcast directly
    feeding or consuming a custom call (get-tuple-element hops are
    transparent; a fusion neighbour is inspected for bracket ops it
    hides).  Row keys: target, call, side (feeds/consumes), op,
    shape, via ("" or the wrapping fusion's name)."""
    rows: List[Dict[str, str]] = []

    def add(call: Instruction, side: str, op: Instruction,
            via: str = "") -> None:
        rows.append({"target": call.target or "<unknown>",
                     "call": call.name, "side": side,
                     "op": op.opcode, "shape": _fmt_shapes(op),
                     "via": via})

    for comp in program.computations.values():
        for instr in comp.instructions:
            if instr.opcode != "custom-call":
                continue
            for opname in instr.operands:
                p = _chase_gte(comp, comp.by_name.get(opname))
                if p is None:
                    continue
                if p.opcode in BRACKET_OPS:
                    add(instr, "feeds", p)
                elif p.opcode == "fusion":
                    for b in _fusion_bracket_ops(program, p):
                        add(instr, "feeds", b, via=p.name)
            for u in comp.consumers(instr.name):
                chain = [u]
                if u.opcode == "get-tuple-element":
                    chain = comp.consumers(u.name)
                for c in chain:
                    if c.opcode in BRACKET_OPS:
                        add(instr, "consumes", c)
                    elif c.opcode == "fusion":
                        for b in _fusion_bracket_ops(program, c):
                            add(instr, "consumes", b, via=c.name)
    return rows


def format_evidence_table(rows: List[Dict[str, str]]) -> str:
    """The human-readable bracket report (BASELINE.md format)."""
    if not rows:
        return "(no bracket ops adjacent to custom calls)"
    head = ("target", "side", "op", "shape", "via")
    widths = [max(len(h), *(len(r[h]) for r in rows)) for h in head]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*head), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*(r[h] for h in head)) for r in rows]
    return "\n".join(lines)


def _is_host_custom_call(target: str) -> bool:
    t = target.lower()
    return any(m in t for m in _HOST_TARGET_MARKERS)


def summarize(program: Union[str, HloProgram],
              mem: Optional[Dict[str, int]] = None) -> Dict:
    """The contract view of one compiled program.

    ``mem`` is the ``_mem_stats``-shaped dict (``hbm_peak`` = temp +
    argument bytes); without it the peak-bytes budget is omitted.
    Every field is deterministic across lowerings of the same
    program.
    """
    if isinstance(program, str):
        program = parse_hlo(program)

    collectives: Dict[str, Dict[str, int]] = {}
    custom_calls: Dict[str, Dict[str, int]] = {}
    host_ops: Dict[str, int] = {}
    fusion_count = 0

    for comp in program.computations.values():
        for instr in comp.instructions:
            op = instr.opcode
            if op.endswith("-done"):
                base = op[:-5]
                if base in COLLECTIVE_OPS:
                    continue  # counted at the -start half
            kind = op[:-6] if op.endswith("-start") else op
            if kind in COLLECTIVE_OPS:
                slot = collectives.setdefault(
                    kind, {"count": 0, "bytes": 0, "max_elems": 0})
                slot["count"] += 1
                slot["bytes"] += instr.result_bytes()
                slot["max_elems"] = max(slot["max_elems"],
                                        instr.result_elems())
                continue
            if op == "fusion":
                fusion_count += 1
            elif op == "custom-call":
                tgt = instr.target or "<unknown>"
                slot = custom_calls.setdefault(
                    tgt, {"count": 0, "bracketed": 0})
                slot["count"] += 1
                if _is_host_custom_call(tgt):
                    host_ops[tgt] = host_ops.get(tgt, 0) + 1
            elif op in HOST_TRANSFER_OPS:
                host_ops[op] = host_ops.get(op, 0) + 1

    for row in bracket_evidence(program):
        slot = custom_calls.get(row["target"])
        if slot is not None:
            slot["bracketed"] += 1

    out = {
        "collectives": {k: collectives[k] for k in sorted(collectives)},
        "custom_calls": {k: custom_calls[k]
                         for k in sorted(custom_calls)},
        # the dtype family is owned by dtypeflow (ISSUE 10: ONE dtype
        # analyzer) — same keys/ordering the committed contracts pin
        "dtype": dtype_summary(program),
        "budgets": {"instruction_count": program.instruction_count(),
                    "fusion_count": fusion_count},
        "host_transfers": {"count": sum(host_ops.values()),
                           "ops": {k: host_ops[k]
                                   for k in sorted(host_ops)}},
    }
    if mem:
        out["budgets"]["peak_bytes"] = int(
            mem.get("hbm_peak") or
            (mem.get("temp_size_in_bytes", 0) +
             mem.get("argument_size_in_bytes", 0)))
    return out


def audit_findings(summary: Dict, label: str = "") -> List[str]:
    """Program-hygiene findings for the runtime audit knob
    (``MXTPU_HLO_AUDIT``): properties that should hold for EVERY
    production program, contract or not — no host transfers inside
    the compiled step, no f64 creep, no layout brackets around custom
    calls."""
    where = f" in {label}" if label else ""
    out: List[str] = []
    ht = summary.get("host_transfers", {})
    if ht.get("count"):
        out.append(f"host transfer(s){where}: {ht.get('ops')} — the "
                   f"compiled step should never round-trip the host")
    f64 = summary.get("dtype", {}).get("f64_ops", 0)
    if f64:
        out.append(f"{f64} f64 op(s){where} — silent f32->f64 "
                   f"promotion (check jax_enable_x64 and np scalar "
                   f"leaks)")
    bracketed = {t: s["bracketed"]
                 for t, s in summary.get("custom_calls", {}).items()
                 if s.get("bracketed")}
    if bracketed:
        out.append(f"custom call(s) bracketed by transpose/copy/"
                   f"bitcast{where}: {bracketed} — XLA is paying "
                   f"layout movement at the kernel boundary")
    return out
