"""Structural parser for post-optimization XLA HLO text.

The ONE HLO parser in the tree (ISSUE 6): ``tests/test_zero.py``'s
regex helpers and every future compiled-artifact check go through
this module instead of re-growing ad-hoc ``re.findall`` over
``hlo_text()``.  Scope is deliberately the dump format this repo's
jaxlib emits from ``compiled.as_text()`` — instruction lines of the
form::

    [ROOT ]%name = <shape> opcode(operands), attr=..., metadata={...}

grouped into computations (``ENTRY`` marks the entry one).  Unknown
lines are skipped, not errors: the parser must survive dialect drift
across jaxlib upgrades and report *less*, never crash.

Pure stdlib — importable without jax so ``tools/hlocheck`` can check
saved dumps and mxlint-adjacent tooling can reuse it.
"""
from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Tuple

# bytes per element for HLO primitive types (token/opaque count as 0)
DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "f8e4m3fnuz": 1, "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_FLOAT_WIDTH = {"f8e4m3fn": 1, "f8e5m2": 1, "f16": 2, "bf16": 2,
                "f32": 4, "f64": 8}

_NAME_RE = re.compile(r"^\s*(ROOT\s+)?%?([\w.\-]+)\s*=\s*")
_SIMPLE_SHAPE_RE = re.compile(
    r"[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?")
_SHAPE_TOKEN_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_OPCODE_RE = re.compile(r"\s*([\w\-]+)\s*\(")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')
_STRING_RE = re.compile(r'"[^"]*"')


def shape_elems(dims: Tuple[int, ...]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


class Instruction:
    """One HLO instruction: result shape(s), opcode, operand names,
    raw attribute text."""

    __slots__ = ("name", "opcode", "root", "shapes", "operands",
                 "attrs", "target", "calls")

    def __init__(self, name: str, opcode: str, root: bool,
                 shapes: List[Tuple[str, Tuple[int, ...]]],
                 operands: List[str], attrs: str):
        self.name = name
        self.opcode = opcode
        self.root = root
        self.shapes = shapes          # [(dtype, dims), ...]
        self.operands = operands      # %-names used inside the parens
        self.attrs = attrs            # raw text after the operand list
        m = _TARGET_RE.search(attrs)
        self.target: Optional[str] = m.group(1) if m else None
        # computations referenced from attributes (calls=, to_apply=,
        # body=/condition=, branch_computations={...}); attribute
        # strings are stripped first so quoted text can't alias a name
        self.calls: List[str] = _OPERAND_NAME_RE.findall(
            _STRING_RE.sub('""', attrs))

    def result_bytes(self) -> int:
        return sum(DTYPE_BYTES.get(dt, 0) * shape_elems(dims)
                   for dt, dims in self.shapes)

    def result_elems(self) -> int:
        return sum(shape_elems(dims) for dt, dims in self.shapes
                   if dt in DTYPE_BYTES)

    def dtypes(self) -> List[str]:
        return [dt for dt, _ in self.shapes]


class Computation:
    __slots__ = ("name", "is_entry", "instructions", "by_name",
                 "_consumers")

    def __init__(self, name: str, is_entry: bool):
        self.name = name
        self.is_entry = is_entry
        self.instructions: List[Instruction] = []
        self.by_name: Dict[str, Instruction] = {}
        self._consumers: Optional[Dict[str, List[Instruction]]] = None

    def add(self, instr: Instruction) -> None:
        self.instructions.append(instr)
        self.by_name[instr.name] = instr

    def consumers(self, name: str) -> List[Instruction]:
        if self._consumers is None:
            cons: Dict[str, List[Instruction]] = {}
            for i in self.instructions:
                for op in i.operands:
                    cons.setdefault(op, []).append(i)
            self._consumers = cons
        return self._consumers.get(name, [])


class HloProgram:
    """All computations of one HLO module, entry marked."""

    def __init__(self, computations: Dict[str, Computation],
                 entry: Optional[str]):
        self.computations = computations
        self.entry_name = entry

    @property
    def entry(self) -> Optional[Computation]:
        return self.computations.get(self.entry_name) \
            if self.entry_name else None

    def all_instructions(self) -> Iterable[Instruction]:
        for comp in self.computations.values():
            for instr in comp.instructions:
                yield instr

    def instruction_count(self) -> int:
        return sum(len(c.instructions)
                   for c in self.computations.values())

    def count_opcode(self, opcode: str) -> int:
        return sum(1 for i in self.all_instructions()
                   if i.opcode == opcode)


def _parse_instruction(line: str) -> Optional[Instruction]:
    m = _NAME_RE.match(line)
    if not m:
        return None
    root, name = bool(m.group(1)), m.group(2)
    rest = line[m.end():]
    # result shape: either a (possibly nested) tuple or a simple
    # array/token shape with optional layout braces
    if rest.startswith("("):
        depth = 0
        end = -1
        for i, ch in enumerate(rest):
            depth += (ch == "(") - (ch == ")")
            if depth == 0:
                end = i
                break
        if end < 0:
            return None
        shape_text, rest = rest[:end + 1], rest[end + 1:]
    else:
        sm = _SIMPLE_SHAPE_RE.match(rest)
        if not sm:
            return None
        shape_text, rest = sm.group(0), rest[sm.end():]
    om = _OPCODE_RE.match(rest)
    if not om:
        return None
    opcode = om.group(1)
    # operand list: balanced parens starting at the opcode's "("
    start = om.end() - 1
    depth = 0
    end = -1
    for i in range(start, len(rest)):
        depth += (rest[i] == "(") - (rest[i] == ")")
        if depth == 0:
            end = i
            break
    if end < 0:
        return None
    operand_text = rest[start + 1:end]
    attrs = rest[end + 1:]
    shapes = [(dt, tuple(int(x) for x in dims.split(",") if x))
              for dt, dims in _SHAPE_TOKEN_RE.findall(shape_text)]
    operands = _OPERAND_NAME_RE.findall(operand_text)
    return Instruction(name, opcode, root, shapes, operands, attrs)


def parse_hlo(text: str) -> HloProgram:
    """Parse ``compiled.as_text()`` output.  Lines that are neither a
    computation header, an instruction, nor a closing brace are
    ignored."""
    computations: Dict[str, Computation] = {}
    entry: Optional[str] = None
    current: Optional[Computation] = None
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped or stripped.startswith("//"):
            continue
        # computation header: `[ENTRY ]%name (params) -> type {` —
        # instruction lines always contain " = " before any brace
        if stripped.endswith("{") and " = " not in stripped:
            hm = re.match(r"(ENTRY\s+)?%?([\w.\-]+)\s*\(", stripped)
            if hm:
                current = Computation(hm.group(2), bool(hm.group(1)))
                computations[current.name] = current
                if current.is_entry:
                    entry = current.name
            continue
        if stripped.startswith("}"):
            current = None
            continue
        if current is None:
            continue
        instr = _parse_instruction(line)
        if instr is not None:
            current.add(instr)
    return HloProgram(computations, entry)
